"""Advanced SQL features: window functions (OVER), UDAFs, async UDFs,
lookup joins."""

import asyncio
import json

import pyarrow as pa
import pytest

from arroyo_tpu.engine import Engine
from arroyo_tpu.sql import plan_query
from arroyo_tpu.sql.lexer import SqlError

IMPULSE = """
CREATE TABLE impulse WITH (
  connector = 'impulse', event_rate = '1000000',
  message_count = '8000', start_time = '0'
);
"""


def run_sql(sql, parallelism=1):
    results = []
    plan = plan_query(sql, parallelism=parallelism, preview_results=results)

    async def go():
        eng = Engine(plan.graph).start()
        await eng.join(60)

    asyncio.run(go())
    return results


def test_row_number_top_n():
    """Top-2 keys per window by count (the q5-style topN pattern,
    reference reinvoke_window_function.sql)."""
    rows = run_sql(
        IMPULSE
        + """
        SELECT k, cnt, rn FROM (
          SELECT k, cnt,
                 row_number() OVER (PARTITION BY w ORDER BY cnt DESC, k ASC)
                   as rn
          FROM (
            SELECT counter % 4 as k, tumble(interval '2 millisecond') as w,
                   count(*) as cnt
            FROM impulse WHERE counter % 4 < 3 OR counter % 8 = 3
            GROUP BY 1, 2
          )
        ) WHERE rn <= 2;
        """
    )
    # 8ms of data / 2ms windows = 4 windows; keys 0,1,2 have 500/window,
    # key 3 has 250 -> top2 = two of {0,1,2} (ties broken by k asc)
    assert len(rows) == 8
    by_rn = {}
    for r in rows:
        by_rn.setdefault(r["rn"], []).append(r)
    assert len(by_rn[1]) == 4 and len(by_rn[2]) == 4
    assert all(r["cnt"] == 500 for r in rows)
    assert all(r["k"] in (0, 1) for r in rows)  # tie-break by k


def test_rank_and_dense_rank():
    rows = run_sql(
        IMPULSE
        + """
        SELECT k, cnt, rank() OVER (PARTITION BY w ORDER BY cnt DESC) as r
        FROM (
          SELECT counter % 4 as k, tumble(interval '8 millisecond') as w,
                 count(*) as cnt
          FROM impulse GROUP BY 1, 2
        );
        """
    )
    # single window, all four keys tie at 2000 -> all rank 1
    assert len(rows) == 4
    assert all(r["r"] == 1 for r in rows)


def test_udaf_in_window():
    from arroyo_tpu.udf import udaf

    @udaf(pa.float64(), [pa.int64()], name="median_t")
    def median_t(values):
        import numpy as np

        return float(np.median(values)) if len(values) else None

    rows = run_sql(
        IMPULSE
        + """
        SELECT k, med, cnt FROM (
          SELECT counter % 2 as k, tumble(interval '4 millisecond') as w,
                 median_t(counter) as med, count(*) as cnt
          FROM impulse GROUP BY 1, 2
        );
        """
    )
    # 2 windows x 2 keys; window 0 has counters 0..3999
    assert len(rows) == 4
    rows.sort(key=lambda r: (r["med"]))
    assert rows[0]["cnt"] == 2000
    # k=0 window0: evens 0..3998 -> median 1999; k=1: odds -> 2000
    meds = sorted(r["med"] for r in rows)
    assert meds == [1999.0, 2000.0, 5999.0, 6000.0]


def test_async_udf():
    from arroyo_tpu.udf import udf

    @udf(pa.int64(), [pa.int64()], name="slow_double")
    async def slow_double(x):
        await asyncio.sleep(0.001)
        return x * 2

    rows = run_sql(
        IMPULSE.replace("'8000'", "'50'")
        + "SELECT counter, slow_double(counter) as d FROM impulse;"
    )
    assert len(rows) == 50
    assert all(r["d"] == 2 * r["counter"] for r in rows)


def test_lookup_join(tmp_path):
    lookup_file = tmp_path / "users.json"
    with open(lookup_file, "w") as f:
        for i in range(4):
            f.write(json.dumps({"uid": i, "name": f"user-{i}"}) + "\n")
    rows = run_sql(
        IMPULSE.replace("'8000'", "'10'")
        + f"""
        CREATE TABLE users (
          uid BIGINT,
          name TEXT
        ) WITH (
          connector = 'single_file', path = '{lookup_file}',
          format = 'json', type = 'lookup', lookup_key = 'uid'
        );
        SELECT counter, name FROM impulse
        JOIN users ON counter % 5 = users.uid;
        """
    )
    # counters 0..9; keys 0..4 looked up; uid 4 missing -> inner join drops
    assert len(rows) == 8
    assert all(r["name"] == f"user-{r['counter'] % 5}" for r in rows)


def test_lookup_left_join(tmp_path):
    lookup_file = tmp_path / "users.json"
    with open(lookup_file, "w") as f:
        f.write(json.dumps({"uid": 0, "name": "zero"}) + "\n")
    rows = run_sql(
        IMPULSE.replace("'8000'", "'4'")
        + f"""
        CREATE TABLE users (uid BIGINT, name TEXT) WITH (
          connector = 'single_file', path = '{lookup_file}',
          format = 'json', type = 'lookup', lookup_key = 'uid'
        );
        SELECT counter, name FROM impulse
        LEFT JOIN users ON counter = users.uid;
        """
    )
    assert len(rows) == 4
    named = {r["counter"]: r["name"] for r in rows}
    assert named[0] == "zero" and named[1] is None


def test_async_udf_nested_rejected():
    from arroyo_tpu.udf import udf

    @udf(pa.int64(), [pa.int64()], name="slow_inc")
    async def slow_inc(x):
        return x + 1

    with pytest.raises(SqlError, match="async UDF"):
        plan_query(IMPULSE + "SELECT slow_inc(counter) + 1 FROM impulse;")


def test_unnest(tmp_path):
    data = tmp_path / "lists.json"
    with open(data, "w") as f:
        f.write(json.dumps({"id": 1, "tags": [10, 20]}) + "\n")
        f.write(json.dumps({"id": 2, "tags": []}) + "\n")
        f.write(json.dumps({"id": 3, "tags": [30]}) + "\n")
    rows = run_sql(
        f"""
        CREATE TABLE t (id BIGINT, tags BIGINT ARRAY) WITH (
          connector = 'single_file', path = '{data}',
          format = 'json', type = 'source'
        );
        SELECT id, unnest(tags) as tag FROM t;
        """
    )
    assert sorted((r["id"], r["tag"]) for r in rows) == [
        (1, 10), (1, 20), (3, 30)
    ]


def test_unnest_requires_list():
    with pytest.raises(SqlError, match="list argument"):
        plan_query(IMPULSE + "SELECT unnest(counter) FROM impulse;")


def test_unnest_guards():
    with pytest.raises(SqlError, match="GROUP BY"):
        plan_query(
            """
            CREATE TABLE t (id BIGINT, tags BIGINT ARRAY) WITH (
              connector = 'single_file', path = '/tmp/x', format = 'json',
              type = 'source'
            );
            SELECT id, unnest(tags) FROM t GROUP BY id;
            """
        )
    with pytest.raises(SqlError, match="updating"):
        plan_query(
            IMPULSE
            + """
            CREATE TABLE t (id BIGINT, tags BIGINT ARRAY) WITH (
              connector = 'single_file', path = '/tmp/x', format = 'json',
              type = 'source'
            );
            SELECT unnest(t.tags) FROM t
            JOIN impulse ON t.id = impulse.counter;
            """
        )
    with pytest.raises(SqlError, match="top-level"):
        plan_query(
            """
            CREATE TABLE t (id BIGINT, tags BIGINT ARRAY) WITH (
              connector = 'single_file', path = '/tmp/x', format = 'json',
              type = 'source'
            );
            SELECT unnest(tags) + 1 FROM t;
            """
        )
    # nested in a CASE branch: the generic expression walker must see it
    with pytest.raises(SqlError, match="top-level"):
        plan_query(
            """
            CREATE TABLE t (id BIGINT, tags BIGINT ARRAY) WITH (
              connector = 'single_file', path = '/tmp/x', format = 'json',
              type = 'source'
            );
            SELECT CASE WHEN id > 0 THEN unnest(tags) ELSE 0 END FROM t;
            """
        )


def test_unnest_alias_collision(tmp_path):
    """A plain column aliased to the unnest output's name must not collide
    with the exploded column's internal mapping."""
    data = tmp_path / "lists.json"
    with open(data, "w") as f:
        f.write(json.dumps({"id": 7, "tags": [1, 2]}) + "\n")
    rows = run_sql(
        f"""
        CREATE TABLE t (id BIGINT, tags BIGINT ARRAY) WITH (
          connector = 'single_file', path = '{data}',
          format = 'json', type = 'source'
        );
        SELECT id AS unnest, unnest(tags) FROM t;
        """
    )
    assert len(rows) == 2
    vals = sorted(r["unnest_1"] for r in rows)
    assert vals == [1, 2] and all(r["unnest"] == 7 for r in rows)


def test_sized_array_type_parses():
    from arroyo_tpu.sql.parser import parse_statements

    stmts = parse_statements(
        "CREATE TABLE t (tags VARCHAR(10) ARRAY) WITH (connector='x')"
    )
    assert stmts[0].columns[0].type_name == "VARCHAR ARRAY"


def test_async_udf_inflight_persistence(tmp_path):
    """A checkpoint barrier does NOT drain the async UDF: slow in-flight
    calls persist as state (reference async_udf.rs :495 in-flight tables)
    and are re-submitted on restore — every input row emits exactly once
    across the stop/restore cycle."""
    import time

    from arroyo_tpu.udf import udf

    @udf(pa.int64(), [pa.int64()], name="two_speed")
    async def two_speed(x):
        if x >= 10:
            await asyncio.sleep(1.2)
        return x + 100

    src = tmp_path / "in.json"
    with open(src, "w") as f:
        base = 1677628800000  # 2023-03-01T00:00:00Z in ms
        for i in range(30):
            f.write(json.dumps(
                {"ts": base + i, "counter": i}) + "\n")
    out = tmp_path / "out.json"
    sql = f"""
    CREATE TABLE t (ts TIMESTAMP, counter BIGINT) WITH (
      connector = 'single_file', path = '{src}', format = 'json',
      type = 'source', event_time_field = 'ts', throttle_per_sec = '80'
    );
    CREATE TABLE sink (counter BIGINT, d BIGINT) WITH (
      connector = 'single_file', path = '{out}', format = 'json',
      type = 'sink'
    );
    INSERT INTO sink SELECT counter, two_speed(counter) as d FROM t;
    """
    storage = str(tmp_path / "state")

    async def phase1():
        plan = plan_query(sql, parallelism=1)
        eng = Engine(plan.graph, job_id="af_restore",
                     storage_url=storage).start()
        # rows arrive fast; counters >= 10 are still in flight here
        await asyncio.sleep(0.15)
        t0 = time.monotonic()
        await eng.checkpoint_and_wait(then_stop=True)
        barrier_secs = time.monotonic() - t0
        await eng.join(60)
        return barrier_secs

    barrier_secs = asyncio.run(phase1())
    # the barrier must not have waited out the 0.8s in-flight calls
    assert barrier_secs < 1.0, f"barrier drained in-flight work ({barrier_secs:.2f}s)"
    phase1_rows = [json.loads(line) for line in open(out)] if out.exists() else []
    assert len(phase1_rows) < 30

    async def phase2():
        plan = plan_query(sql, parallelism=1)
        eng = Engine(plan.graph, job_id="af_restore",
                     storage_url=storage).start()
        await eng.join(60)

    asyncio.run(phase2())
    rows = [json.loads(line) for line in open(out)]
    assert sorted(r["counter"] for r in rows) == list(range(30)), rows
    assert all(r["d"] == r["counter"] + 100 for r in rows)
