"""MUST fire JAX001: host syncs inside jitted bodies."""
from functools import partial

import jax
import numpy as np


@jax.jit
def step(x):
    return x + np.asarray(x)


@partial(jax.jit, donate_argnums=(0,))
def scalarize(x):
    return x.sum().item()


def gather(state):
    state.block_until_ready()
    return state


gather_fn = jax.jit(gather)
