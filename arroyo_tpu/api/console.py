"""Web console served at /console.

A hash-routed single-page app mirroring the reference's React webui
(/root/reference/webui, router.tsx routes): pipelines list/detail with
DAG visualization, live per-operator metric graphs, checkpoint inspector
and error tail, a SQL editor with validate/preview/create, a connections
wizard generated from connector config_schema metadata, and a UDF
editor. Static assets live in arroyo_tpu/api/static/ and are served by
the API process — no build step, no framework."""

import os

STATIC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "static")

_CONTENT_TYPES = {
    ".html": "text/html",
    ".css": "text/css",
    ".js": "application/javascript",
    ".svg": "image/svg+xml",
}


def add_console_routes(app):
    from aiohttp import web

    def serve(filename):
        path = os.path.join(STATIC_DIR, filename)
        ext = os.path.splitext(filename)[1]

        async def handler(request):
            with open(path, "r", encoding="utf-8") as f:
                return web.Response(
                    text=f.read(),
                    content_type=_CONTENT_TYPES.get(ext, "text/plain"),
                )

        return handler

    index = serve("index.html")
    app.router.add_get("/", index)
    app.router.add_get("/console", index)
    app.router.add_get("/console/", index)
    for name in os.listdir(STATIC_DIR):
        if name != "index.html":
            app.router.add_get(f"/console/{name}", serve(name))
