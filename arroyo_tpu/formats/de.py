"""Deserialization: raw bytes -> Arrow RecordBatches.

Capability parity with the reference's ArrowDeserializer
(/root/reference/crates/arroyo-formats/src/de.rs:312): JSON (schema'd,
unstructured `value` mode, Debezium envelope), raw string/bytes, framing
(newline / length) via a FramingIterator (de.rs:69), BadData fail|drop
policy, and incremental Arrow array building. Avro and Protobuf decoding
use pure-python decoders gated on schema availability.
"""

from __future__ import annotations

import json
from typing import Iterator, List, Optional

import pyarrow as pa

from ..schema import StreamSchema, TIMESTAMP_FIELD
from ..types import now_nanos


class BadDataError(Exception):
    pass


def framing_iterator(framing: Optional[str], payload: bytes) -> Iterator[bytes]:
    """Split one message payload into records (reference FramingIterator)."""
    if framing == "newline":
        for line in payload.split(b"\n"):
            if line:
                yield line
    else:
        yield payload


class Deserializer:
    """Bytes -> rows for one declared schema + format."""

    def __init__(
        self,
        schema: StreamSchema,
        format: str = "json",
        bad_data: str = "fail",
        framing: Optional[str] = None,
        unstructured: bool = False,
        proto_descriptor=None,
        avro_schema: Optional[str] = None,
        schema_registry=None,
    ):
        self.schema = schema
        self.format = format or "json"
        self.bad_data = bad_data
        self.framing = framing
        self.unstructured = unstructured
        self.errors: List[str] = []
        self._field_names = [
            f.name for f in schema.schema if f.name != TIMESTAMP_FIELD
        ]
        self._fields = {f.name: f for f in schema.schema}
        self.schema_registry = schema_registry
        self._avro_by_id: dict = {}
        if self.format == "avro":
            from .avro import AvroDecoder

            # with a registry, the writer schema resolves per record from
            # the Confluent framing id (reference schema_resolver.rs);
            # a static avro.schema is then only a fallback for unframed
            # records
            self.avro = (
                AvroDecoder(avro_schema)
                if avro_schema or schema_registry is None else None
            )
        if self.format in ("protobuf", "proto"):
            from .proto import ProtoDecoder

            self.proto = ProtoDecoder(proto_descriptor)

    def deserialize_slice(
        self, payload: bytes, timestamp: Optional[int] = None,
        error_reporter=None,
    ) -> List[dict]:
        """Decode one transport message into rows (dicts keyed by column)."""
        rows = []
        ts = timestamp if timestamp is not None else now_nanos()
        for record in framing_iterator(self.framing, payload):
            try:
                rows.append(self._decode_one(record, ts))
            except Exception as e:  # noqa: BLE001 - bad-data policy boundary
                if self.bad_data == "drop":
                    if error_reporter is not None:
                        error_reporter.report("bad data dropped", str(e))
                    continue
                raise BadDataError(f"{e}: {record[:200]!r}") from e
        return rows

    def _decode_one(self, record: bytes, ts: int) -> dict:
        if self.format == "raw_string":
            return {"value": record.decode("utf-8"), TIMESTAMP_FIELD: ts}
        if self.format == "raw_bytes":
            return {"value": record, TIMESTAMP_FIELD: ts}
        if self.format == "json":
            obj = json.loads(record)
            if self.unstructured:
                return {"value": json.dumps(obj), TIMESTAMP_FIELD: ts}
            return self._json_row(obj, ts)
        if self.format == "debezium_json":
            obj = json.loads(record)
            payload = obj.get("payload", obj)
            # unroll happens upstream of updating operators; here we take
            # the after-image (c/r/u) and tag deletes
            op = payload.get("op", "r")
            image = payload.get("after") if op != "d" else payload.get("before")
            row = self._json_row(image or {}, ts)
            row["__op"] = op
            return row
        if self.format == "avro":
            return self._json_row(self._decode_avro(record), ts)
        if self.format in ("protobuf", "proto"):
            return self._json_row(self.proto.decode(record), ts)
        raise ValueError(f"unknown format {self.format!r}")

    def _decode_avro(self, record: bytes) -> dict:
        """Registry-aware avro decode: Confluent-framed records resolve
        their writer schema by id (cached per id); reader-side field
        mapping by name happens in _json_row (missing -> null, unknown
        dropped) — the subset of avro schema resolution real pipelines
        rely on."""
        if (
            self.schema_registry is not None
            and len(record) > 5
            and record[0] == 0
        ):
            import struct as _struct

            (schema_id,) = _struct.unpack_from(">I", record, 1)
            dec = self._avro_by_id.get(schema_id)
            if dec is None:
                from .avro import AvroDecoder

                writer = self.schema_registry.get_schema_for_id(schema_id)
                dec = AvroDecoder(json.dumps(writer))
                self._avro_by_id[schema_id] = dec
            return dec.decode_raw(record[5:])
        if self.avro is None:
            raise ValueError(
                "avro record without Confluent framing needs a static "
                "avro.schema option"
            )
        return self.avro.decode(record)

    def _json_row(self, obj: dict, ts: int) -> dict:
        row = {TIMESTAMP_FIELD: ts}
        for name in self._field_names:
            v = obj.get(name)
            f = self._fields[name]
            if v is not None and pa.types.is_timestamp(f.type):
                v = _parse_timestamp(v)
            row[name] = v
        return row


def _parse_timestamp(v) -> int:
    """tolerant timestamp parse -> nanos."""
    if isinstance(v, (int, float)):
        # heuristically scale: seconds vs millis vs nanos
        iv = int(v)
        if iv < 10_000_000_000:  # seconds
            return int(v * 1_000_000_000)
        if iv < 10_000_000_000_000:  # millis
            return int(v * 1_000_000)
        if iv < 10_000_000_000_000_000:  # micros
            return int(v * 1_000)
        return iv
    import pandas as pd

    return int(pd.Timestamp(v).value)


def rows_to_batch(rows: List[dict], schema: StreamSchema) -> pa.RecordBatch:
    cols = {name: [] for name in schema.names}
    for row in rows:
        for name in cols:
            cols[name].append(row.get(name))
    arrays = []
    for f in schema.schema:
        vals = cols[f.name]
        if pa.types.is_timestamp(f.type):
            arrays.append(pa.array(vals, type=pa.int64()).cast(f.type))
        else:
            arrays.append(pa.array(vals, type=f.type))
    return pa.RecordBatch.from_arrays(arrays, schema=schema.schema)
