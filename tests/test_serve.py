"""StateServe — the queryable-state serving tier (ISSUE 12).

Fast-tier coverage of the read path's three load-bearing contracts:

  * epoch consistency — a read issued mid-checkpoint returns ONLY
    last-published-epoch values (unit: the view's stage/seal/fold
    layers; model: the reader actor explores clean with reads enabled);
  * routing exactness — the gateway routes every key to the subtask
    that actually owns it, for shard counts 2/4/8 and across a live
    1 -> 4 -> 2 controller-driven rescale (cross-checked against
    `MeshSlotDirectory.owners_for` and the job's assignment table);
  * degradation — a worker SIGKILL mid-read-load yields retriable
    errors or consistent values, never a torn one; a torn-down
    incarnation's route fences (`stale_route`) instead of serving.

Plus the serving-tier surfaces: REST point/bulk/table routes, the
read-through cache's epoch invalidation, per-tenant QPS admission with
the doctor's noisy-neighbor penalty, and GC on job stop.
"""

import asyncio
import json
import time

import numpy as np
import pytest

from arroyo_tpu.config import config, update
from arroyo_tpu.controller.controller import ControllerServer
from arroyo_tpu.controller.scheduler import EmbeddedScheduler
from arroyo_tpu.controller.state_machine import JobState
from arroyo_tpu.serve import ServeView, owner_subtask
from arroyo_tpu.serve.gateway import _Bucket, _Cache
from arroyo_tpu.serve.store import canon_value
from arroyo_tpu.types import (
    hash_arrays,
    hash_column,
    server_for_hash_array,
)


def _view(**kw):
    base = dict(job_id="j", table="t", node_id=1, task_index=0,
                parallelism=1, key_names=["k"], key_kinds=("i",),
                value_names=["cnt"], kind="window", live_mode=False)
    base.update(kw)
    return ServeView(**base)


# -- epoch consistency (the acceptance unit test) ----------------------------


def test_read_mid_checkpoint_returns_last_published_only():
    """A read issued mid-checkpoint (state staged, sealed, or even
    sealed-at-a-later-epoch) must return only values of the last
    PUBLISHED epoch the gateway resolved."""
    v = _view()
    v.stage((1,), {"cnt": 10})
    # staged but not yet captured: invisible at every published level
    assert v.read((1,), 0) == (False, None)
    v.seal(1)  # captured at epoch 1's barrier
    # epoch 1 not published yet -> still invisible
    assert v.read((1,), 0) == (False, None)
    # epoch 1 published -> visible
    assert v.read((1,), 1) == (True, {"cnt": 10})
    # next interval: a newer value captured at epoch 2, published at 1:
    # the read must keep answering epoch 1's value (no torn/early read)
    v.stage((1,), {"cnt": 99})
    assert v.read((1,), 1) == (True, {"cnt": 10})
    v.seal(2)
    assert v.read((1,), 1) == (True, {"cnt": 10})
    assert v.read((1,), 2) == (True, {"cnt": 99})


def test_view_tombstones_and_pending_cap():
    v = _view()
    v.stage((7,), {"cnt": 1})
    v.seal(1)
    v.stage_tomb((7,))
    v.seal(2)
    assert v.read((7,), 1) == (True, {"cnt": 1})
    assert v.read((7,), 2) == (False, None)
    # pending cap: publication stalls for > max_pending_epochs — the
    # oldest epochs fold forward instead of growing without bound
    with update(serve={"max_pending_epochs": 4}):
        v2 = _view()
        for e in range(1, 10):
            v2.stage((e,), {"cnt": e})
            v2.seal(e)
        assert len(v2.pending) <= 4


def test_view_live_mode_serves_latest():
    """Jobs without durable state have no epochs: views serve live."""
    v = _view(live_mode=True)
    v.stage((1,), {"cnt": 5})
    assert v.read((1,), None) == (True, {"cnt": 5})
    v.stage_tomb((1,))
    assert v.read((1,), None) == (False, None)


def test_model_faithful_reader_clean_and_mutant_caught():
    """The PR 9 checker with the reader actor: faithful model explores
    exhaustively clean with reads enabled; the mutant's counterexample
    is exercised by the standard corpus tests (test_model_check.py
    parametrizes over every mutant, this one included)."""
    from arroyo_tpu.analysis.model import explore as explore_mod
    from arroyo_tpu.analysis.model import mutants as mutants_mod
    from arroyo_tpu.analysis.model.extract import (
        job_state_machine,
        load_project,
    )
    from arroyo_tpu.analysis.model.spec import Model, ModelConfig
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    _m, terminals, table = job_state_machine(
        load_project(repo, roots=("arroyo_tpu/controller",))
    )
    cfg = ModelConfig(workers=2, epochs=2, inflight=2, faults=1,
                      restarts=2, reads=2,
                      fault_kinds=("fault.kill",))
    res = explore_mod.explore(Model(cfg, table, terminals),
                              budget=400_000)
    assert res.exhaustive
    assert not res.violations, [t.violation for t in res.violations]
    assert "serve_reads_unpublished_epoch" in mutants_mod.MUTANTS


# -- routing exactness -------------------------------------------------------


@pytest.mark.parametrize("shards", [2, 4, 8])
def test_owner_subtask_matches_engine_ownership(shards):
    """store.owner_subtask == the engine's shuffle partitioning == the
    mesh directory's owners_for, for int / unsigned / string / composite
    keys — the routing contract the gateway relies on."""
    from arroyo_tpu.parallel.sharded_state import MeshSlotDirectory

    msd = MeshSlotDirectory(shards)
    int_keys = list(range(200)) + [10**12 + 7, -5]
    for k in int_keys:
        got = owner_subtask((canon_value(k, "i"),), ("i",), shards)
        col = np.asarray([k], dtype=np.int64)
        want = int(server_for_hash_array(
            hash_arrays([hash_column(col)]), shards)[0])
        assert got == want, (k, shards)
        assert got == int(msd.owners_for([col], 1)[0]), (k, shards)
    for k in ["", "a", "auction-17", "x" * 40]:
        got = owner_subtask((canon_value(k, "s"),), ("s",), shards)
        col = np.array([k], dtype=object)
        want = int(server_for_hash_array(
            hash_arrays([hash_column(col)]), shards)[0])
        assert got == want, (k, shards)
    # composite (int, str) keys: per-column hash + seeded combine
    for k in [(1, "a"), (2, "bb"), (10**9, "ccc")]:
        got = owner_subtask(
            (canon_value(k[0], "i"), canon_value(k[1], "s")),
            ("i", "s"), shards,
        )
        cols = [hash_column(np.asarray([k[0]], dtype=np.int64)),
                hash_column(np.array([k[1]], dtype=object))]
        want = int(server_for_hash_array(hash_arrays(cols), shards)[0])
        assert got == want, (k, shards)


# -- gateway cache + admission ----------------------------------------------


def test_cache_epoch_and_incarnation_invalidation():
    c = _Cache()
    c.put(("j", "t", "1"), 3, 1, {"cnt": 5}, budget=1 << 20)
    assert c.get(("j", "t", "1"), 3, 1) == {"cnt": 5}
    # a newly published epoch silently invalidates
    assert c.get(("j", "t", "1"), 4, 1) is None
    c.put(("j", "t", "1"), 4, 1, {"cnt": 6}, budget=1 << 20)
    # a reschedule (new incarnation) invalidates too
    assert c.get(("j", "t", "1"), 4, 2) is None
    # byte budget: inserting past it evicts LRU-first
    small = _Cache()
    for i in range(100):
        small.put(("j", "t", str(i)), 1, 1, {"v": "x" * 50}, budget=2000)
    assert small.bytes <= 2000
    assert len(small.data) < 100
    # job GC empties every entry of that job
    c.drop_job("j")
    assert not c.data and c.bytes == 0


def test_tenant_bucket_throttles_and_noisy_penalty():
    b = _Bucket(100.0)
    # burst allows 2x rate up front, then sustained rate gates
    assert b.take(150, 100.0)
    assert not b.take(100, 100.0)
    # noisy penalty wiring: a flagged tenant gets a squeezed rate
    ctrl_stub = type("C", (), {"jobs": {}})()
    from arroyo_tpu.serve.gateway import StateGateway

    gw = StateGateway(ctrl_stub)
    with update(serve={"tenant_qps": 50.0, "noisy_penalty": 0.1}):
        assert gw._admit("quiet", 40)
        gw.flag_noisy("hot")
        # hot tenant's burst is 2 * 0.1 * 50 = 10 keys
        assert not gw._admit("hot", 40)
        assert gw._admit("hot", 5)
    # doctor-report wiring: a noisy-neighbor verdict flags the suspect
    # job's tenant
    job = type("J", (), {"tenant": "hogt"})()
    ctrl_stub.jobs["hog-job"] = job
    gw.note_doctor_report({"verdict": {"cause": "noisy-neighbor",
                                       "suspect": "hog-job"}})
    assert "hogt" in gw.status()["noisy_tenants"]
    # admission-quota wiring: a tenant at its COMPUTE slot quota gets
    # its read rate clamped by the same penalty
    class _Adm:
        def tenant_at_quota(self, tenant):
            return tenant == "satd"

    ctrl_stub.admission = _Adm()
    with update(serve={"tenant_qps": 50.0, "noisy_penalty": 0.1}):
        assert not gw._admit("satd", 40)  # burst is 10, not 100
        assert gw._admit("satd", 5)
        assert gw._admit("roomy", 40)


# -- end-to-end: embedded cluster, REST, rescale, kill -----------------------


def _serve_sql(wd, keys=8, rate=20000, count=2_000_000):
    return f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '{rate}',
      message_count = '{count}', start_time = '0',
      realtime = 'true', replay = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{wd}/out.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % {keys} as k,
             tumble(interval '100 millisecond') as w, count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """


async def _wait_published(job, epoch=1, timeout=30.0):
    deadline = time.monotonic() + timeout
    while job.published_epoch < epoch:
        assert time.monotonic() < deadline, (
            f"no published epoch >= {epoch} (at {job.published_epoch})"
        )
        await asyncio.sleep(0.1)


async def _wait_found(c, jid, table, key, timeout=30.0):
    deadline = time.monotonic() + timeout
    while True:
        out = await c.serve.read(jid, table, [key])
        r = out.get("results", [{}])[0]
        if r.get("found"):
            return out
        assert time.monotonic() < deadline, f"key {key} never served: {out}"
        await asyncio.sleep(0.2)


def test_e2e_point_bulk_rest_and_fencing(tmp_path):
    """The worked-example path: run a keyed windowed aggregation, read a
    point key and a bulk set through the REST routes at the published
    epoch, hit the cache on the second read, fence a stale-incarnation
    QueryState, and verify GC on stop."""
    from aiohttp import ClientSession, web

    from arroyo_tpu.api.rest import build_app
    from arroyo_tpu.metrics import REGISTRY

    wd = str(tmp_path)

    async def main():
        with update(pipeline={"checkpointing": {
                "interval": 0.5, "storage_url": f"{wd}/ck"}}):
            sched = EmbeddedScheduler()
            c = await ControllerServer(sched).start()
            job = await c.submit_job(
                "sv", sql=_serve_sql(wd), n_workers=2, parallelism=2,
                storage_url=f"{wd}/ck/sv",
            )
            app = build_app(c, db_path=f"{wd}/api.db")
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            base = f"http://127.0.0.1:{port}/api/v1"
            try:
                await c.wait_for_state("sv", JobState.RUNNING, timeout=30)
                await _wait_published(job, 1)
                await _wait_found(c, "sv", "tumbling_window", 0)
                async with ClientSession() as http:
                    # table listing
                    async with http.get(f"{base}/jobs/sv/state") as resp:
                        assert resp.status == 200
                        doc = await resp.json()
                    tables = {d["table"] for d in doc["data"]}
                    assert "tumbling_window" in tables
                    assert doc["publishedEpoch"] >= 1
                    # point read
                    async with http.get(
                        f"{base}/jobs/sv/state/tumbling_window?key=0"
                    ) as resp:
                        assert resp.status == 200
                        point = await resp.json()
                    r = point["results"][0]
                    assert r["found"], point
                    assert "__agg_out_1" in r["value"], r
                    # bulk read: all keys found, each exactly once
                    async with http.post(
                        f"{base}/jobs/sv/state/tumbling_window",
                        json={"keys": list(range(8))},
                    ) as resp:
                        assert resp.status == 200
                        bulk = await resp.json()
                    assert len(bulk["results"]) == 8
                    assert all(x["found"] for x in bulk["results"]), bulk
                    # cache: re-read the same keys at the same epoch
                    async with http.post(
                        f"{base}/jobs/sv/state/tumbling_window",
                        json={"keys": list(range(8))},
                    ) as resp:
                        bulk2 = await resp.json()
                    if bulk2.get("epoch") == bulk.get("epoch"):
                        assert bulk2["cache"]["hits"] > 0, bulk2
                    # unknown table 404s, missing key 400s
                    async with http.get(
                        f"{base}/jobs/sv/state/nope?key=1"
                    ) as resp:
                        assert resp.status == 404
                    async with http.get(
                        f"{base}/jobs/sv/state/tumbling_window"
                    ) as resp:
                        assert resp.status == 400
                # incarnation fencing: a QueryState carrying a stale
                # namespace answers stale_route (retriable), never data
                w = job.workers[0]
                resp = await w.client.call(
                    "WorkerGrpc", "QueryState",
                    {"job_id": "sv", "mode": "get",
                     "table": "tumbling_window", "keys": [0],
                     "epoch": job.published_epoch,
                     "data_ns": "sv@999"},
                )
                assert "stale_route" in resp.get("error", "")
                assert resp.get("retriable") is True
                # GC on stop: cache + routing state expunged, serve
                # series dropped with the job's metrics
                assert c.serve.cache.data
                await c.stop_job("sv", "immediate")
                await c.wait_for_state(
                    "sv", JobState.STOPPED, JobState.FAILED,
                    JobState.FINISHED, timeout=30,
                )
                assert not any(
                    k[0] == "sv" for k in c.serve.cache.data
                )
                assert "sv" not in c.serve._tables
                REGISTRY.drop_job("sv")  # TTL path shortcut for the test
                assert 'job="sv"' not in REGISTRY.expose()
            finally:
                await runner.cleanup()
                await c.stop()

    asyncio.run(main())


def test_gateway_routing_is_engine_ownership_across_rescale(tmp_path):
    """ISSUE 12 satellite: for every key the gateway routes to worker W
    at subtask S, S actually owns the key (owners_for cross-check) and
    the job's assignment table maps (node, S) -> W — held at parallelism
    2 and re-held across a live controller-driven rescale to 4 and back
    to 2 (fresh assignments + fresh view parallelism each time)."""
    from arroyo_tpu.parallel.sharded_state import MeshSlotDirectory

    wd = str(tmp_path)

    async def assert_routing(c, sched, jid, table, keys):
        info = (await c.serve.tables(jid))[table]
        job = c.jobs[jid]
        par = int(info["parallelism"])
        kinds = tuple(info["key_kinds"])
        node = int(info["node_id"])
        msd = MeshSlotDirectory(par) if par >= 2 else None
        host = {}  # task_index -> worker_id actually hosting the view
        for w, _t in sched.pool:
            jr = w._jobs.get(jid)
            if jr is None:
                continue
            for sub in jr.program.subtasks:
                for op in sub.runner.ops:
                    v = getattr(op, "_serve_view", None)
                    if v is not None and v.table == table:
                        assert v.parallelism == par
                        host[v.task_index] = w.worker_id
        assert len(host) == par, (host, par)
        for k in keys:
            key = (canon_value(k, kinds[0]),)
            own = owner_subtask(key, kinds, par)
            if msd is not None:
                col = np.asarray([key[0]], dtype=np.int64)
                assert own == int(msd.owners_for([col], 1)[0]), (k, par)
            # the gateway's worker choice == the ownership map's
            assert job.assignments[(node, own)] == host[own], (k, par)
        # and the fanned-out read actually finds every key (no
        # mis-route ever answers not_owned)
        out = await c.serve.read(jid, table, keys)
        assert out["outcome"] == "ok", out
        assert all(r["found"] for r in out["results"]), out

    async def main():
        with update(pipeline={"checkpointing": {
                "interval": 0.4, "storage_url": f"{wd}/ck"}}):
            sched = EmbeddedScheduler()
            c = await ControllerServer(sched).start()
            job = await c.submit_job(
                "rs", sql=_serve_sql(wd, keys=16), n_workers=2,
                parallelism=2, storage_url=f"{wd}/ck/rs",
            )
            try:
                await c.wait_for_state("rs", JobState.RUNNING, timeout=30)
                await _wait_published(job, 1)
                await _wait_found(c, "rs", "tumbling_window", 0)
                info = (await c.serve.tables("rs"))["tumbling_window"]
                node = int(info["node_id"])
                keys = list(range(16))
                await assert_routing(c, sched, "rs", "tumbling_window",
                                     keys)
                for target in (4, 2):
                    await c.rescale_job("rs", {node: target})
                    deadline = time.monotonic() + 60
                    while not (job.state == JobState.RUNNING
                               and job.graph.nodes[node].parallelism
                               == target):
                        assert time.monotonic() < deadline, (
                            target, job.state)
                        await asyncio.sleep(0.2)
                    await _wait_published(job, job.published_epoch + 1)
                    await _wait_found(c, "rs", "tumbling_window", 0)
                    await assert_routing(c, sched, "rs",
                                         "tumbling_window", keys)
            finally:
                await c.stop_job("rs", "immediate")
                await c.wait_for_state(
                    "rs", JobState.STOPPED, JobState.FAILED,
                    JobState.FINISHED, timeout=30,
                )
                await c.stop()

    asyncio.run(main())


def test_reads_degrade_retriable_on_worker_kill(tmp_path):
    """Chaos shape (fast tier): SIGKILL one pool worker while reads
    run. Every read outcome is found-at-published-epoch, not-found, or
    a retriable error — never an exception, never a torn value (the
    full deterministic-value variant runs in the fleet harness's
    --serve-kill scenario). After recovery, reads serve again."""
    wd = str(tmp_path)

    async def main():
        with update(
            pipeline={"checkpointing": {
                "interval": 0.5, "storage_url": f"{wd}/ck"}},
            controller={"heartbeat_timeout": 6.0},
        ):
            sched = EmbeddedScheduler()
            c = await ControllerServer(sched).start()
            job = await c.submit_job(
                "kl", sql=_serve_sql(wd), n_workers=2, parallelism=2,
                storage_url=f"{wd}/ck/kl",
            )
            try:
                await c.wait_for_state("kl", JobState.RUNNING, timeout=30)
                await _wait_published(job, 1)
                await _wait_found(c, "kl", "tumbling_window", 0)
                live = [w for w, _t in sched.pool
                        if not getattr(w, "_shutdown_started", False)]
                kill_task = asyncio.ensure_future(live[0].shutdown())
                outcomes = set()
                deadline = time.monotonic() + 30
                recovered_found = False
                while time.monotonic() < deadline:
                    out = await c.serve.read(
                        "kl", "tumbling_window", [0, 1, 2, 3]
                    )
                    if out.get("error"):
                        assert out.get("retriable"), out
                        outcomes.add("req-error")
                    else:
                        for r in out["results"]:
                            if r.get("found"):
                                outcomes.add("found")
                            elif r.get("error"):
                                assert r.get("retriable", True), r
                                outcomes.add("key-error")
                            else:
                                outcomes.add("miss")
                        if (job.restarts > 0
                                and job.state == JobState.RUNNING
                                and all(r.get("found")
                                        for r in out["results"])):
                            recovered_found = True
                            break
                    await asyncio.sleep(0.2)
                await kill_task
                assert recovered_found, (
                    f"post-recovery reads never served: {outcomes}, "
                    f"restarts={job.restarts}, state={job.state}"
                )
            finally:
                await c.stop_job("kl", "immediate")
                await c.wait_for_state(
                    "kl", JobState.STOPPED, JobState.FAILED,
                    JobState.FINISHED, timeout=30,
                )
                await c.stop()

    asyncio.run(main())


def test_updating_aggregate_view_and_restore_seed(tmp_path):
    """Updating aggregates serve their emitted values; a checkpoint-
    stopped job's restart seeds the view from restored state, so reads
    work before the first post-restore flush."""
    wd = str(tmp_path)
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '20000',
      message_count = '2000000', start_time = '0',
      realtime = 'true', replay = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{wd}/u.json',
      format = 'debezium_json', type = 'sink'
    );
    INSERT INTO out
    SELECT counter % 4 as k, count(*) as cnt FROM impulse GROUP BY 1;
    """

    async def main():
        with update(pipeline={"checkpointing": {
                "interval": 0.5, "storage_url": f"{wd}/ck"}}):
            c = await ControllerServer(EmbeddedScheduler()).start()
            job = await c.submit_job(
                "up", sql=sql, n_workers=2, parallelism=2,
                storage_url=f"{wd}/ck/up",
            )
            try:
                await c.wait_for_state("up", JobState.RUNNING, timeout=30)
                await _wait_published(job, 1)
                tables = await c.serve.tables("up")
                name = next(t for t in tables
                            if tables[t]["kind"] == "updating")
                vfield = tables[name]["value_fields"][0]
                out = await _wait_found(c, "up", name, 0)
                r = out["results"][0]
                assert r["value"].get(vfield, 0) > 0, out
                # checkpoint-stop, resubmit (same storage): the restored
                # incarnation must serve the key BEFORE any new flush
                await c.stop_job("up", "checkpoint")
                await c.wait_for_state("up", JobState.STOPPED,
                                       timeout=60)
                job2 = await c.submit_job(
                    "up2", sql=sql, n_workers=2, parallelism=2,
                    storage_url=f"{wd}/ck/up",
                )
                await c.wait_for_state("up2", JobState.RUNNING,
                                       timeout=30)
                out2 = await _wait_found(c, "up2", name, 0, timeout=20)
                assert vfield in out2["results"][0]["value"], out2
            finally:
                for jid in ("up", "up2"):
                    if jid in c.jobs and not c.jobs[jid].state.is_terminal():
                        await c.stop_job(jid, "immediate")
                        await c.wait_for_state(
                            jid, JobState.STOPPED, JobState.FAILED,
                            JobState.FINISHED, timeout=30,
                        )
                await c.stop()

    asyncio.run(main())
