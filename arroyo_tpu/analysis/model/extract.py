"""AST extraction: derive the model's ground truth from the code itself.

Two extractions feed the checker:

  * `job_state_machine` parses `controller/state_machine.py` and returns
    the JobState members, the terminal set, and the TRANSITIONS relation —
    the model's controller machine consults THIS table (not a hand copy),
    so a table edit changes the model in the same commit.

  * `annotated_handlers` finds every `@protocol_effect("name")` annotation
    in the tree; `check_bijection` then enforces the three-way bijection
    between annotations, `spec.HANDLER_BINDINGS`, and the effects the
    transition relation actually references. Any drift — a renamed
    handler, a deleted annotation, a modeled effect with no code, an
    annotated function the model ignores — is a finding, and tier-1 runs
    the check strict-clean.

Extraction reuses the arroyolint `Project`/`FileContext` machinery so the
same code paths run against the real tree and against fixture mini-trees.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import FileContext, Project, last_attr, str_const
from ..engine import collect_files, parse_project
from ..rules_protocol import (
    STATE_MACHINE_PATH,
    _jobstate_members,
    _terminal_states,
    _transitions_table,
)

# annotated protocol handlers live under these roots only (tests and
# fixture trees carry their own annotations for rule tests; the bijection
# is about the engine tree)
HANDLER_ROOTS = ("controller/", "operators/", "state/", "serve/",
                 "failover/")


class ExtractionError(Exception):
    pass


def load_project(root, roots: Iterable[str] = ("arroyo_tpu",)) -> Project:
    root = Path(root)
    return parse_project(root, collect_files(root, tuple(roots)))


# -- JobState machine --------------------------------------------------------


def job_state_machine(
    project: Project,
) -> Tuple[Set[str], Set[str], Dict[str, Set[str]]]:
    """(members, terminals, transitions) from controller/state_machine.py,
    parsed from source. Raises ExtractionError when the anchors are
    missing — the model must never silently run against an empty table."""
    sm = project.find(STATE_MACHINE_PATH)
    if sm is None:
        raise ExtractionError(f"{STATE_MACHINE_PATH} not found in project")
    members = _jobstate_members(sm)
    if not members:
        raise ExtractionError("JobState enum not found")
    parsed = _transitions_table(sm)
    if parsed is None:
        raise ExtractionError("TRANSITIONS table not found")
    _node, table = parsed
    terminals = _terminal_states(sm)
    if not terminals:
        raise ExtractionError("JobState.is_terminal() names no states")
    return set(members), terminals, table


def job_state_machine_from_root(root):
    return job_state_machine(
        load_project(root, roots=("arroyo_tpu/controller",))
    )


# -- @protocol_effect annotations --------------------------------------------


def _decorator_effect(dec: ast.expr) -> Optional[str]:
    """'name' for a `@protocol_effect("name")` decorator node."""
    if (
        isinstance(dec, ast.Call)
        and last_attr(dec.func) == "protocol_effect"
        and dec.args
    ):
        return str_const(dec.args[0])
    return None


def annotated_handlers(project: Project) -> Dict[str, List[Tuple[str, str, int]]]:
    """effect name -> [(path, qualified function name, lineno)] for every
    @protocol_effect annotation in the project."""
    out: Dict[str, List[Tuple[str, str, int]]] = {}
    for ctx in project:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                effect = _decorator_effect(dec)
                if effect is not None:
                    out.setdefault(effect, []).append(
                        (ctx.path, node.name, node.lineno)
                    )
    return out


def annotated_functions(ctx: FileContext) -> Set[str]:
    """Function names carrying a @protocol_effect annotation in one file."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_decorator_effect(d) for d in node.decorator_list):
                out.add(node.name)
    return out


# -- the bijection check -----------------------------------------------------


def check_bijection(
    project: Project,
    bindings: Dict[str, Tuple[str, str]],
    used_effects: Set[str],
) -> List[str]:
    """The model<->code drift detector. Returns problem strings (empty ==
    strict-clean):

      1. every binding's (file, function) exists and carries the matching
         @protocol_effect annotation;
      2. every annotation in the engine tree is declared in `bindings`
         (an annotated handler the model doesn't know is drift);
      3. an effect annotated on two different functions is ambiguous;
      4. every binding is referenced by >=1 model transition and every
         referenced effect is bound (the model can't cite handlers that
         don't exist, nor declare bindings no transition uses).
    """
    problems: List[str] = []
    found = annotated_handlers(project)

    for effect, (suffix, fn_name) in sorted(bindings.items()):
        ctx = project.find(suffix)
        if ctx is None:
            problems.append(f"{effect}: bound file {suffix} not in project")
            continue
        sites = [
            (p, f, ln) for (p, f, ln) in found.get(effect, [])
            if p == ctx.path and f == fn_name
        ]
        if not sites:
            problems.append(
                f"{effect}: {suffix}::{fn_name} is not annotated "
                f"@protocol_effect({effect!r}) (or the function is gone)"
            )

    by_site: Dict[Tuple[str, str], List[str]] = {}
    for effect, sites in found.items():
        for (path, fn_name, lineno) in sites:
            if not any(r in path for r in HANDLER_ROOTS):
                continue
            by_site.setdefault((path, fn_name), []).append(effect)
            if effect not in bindings:
                problems.append(
                    f"{path}:{lineno} {fn_name}() is annotated "
                    f"@protocol_effect({effect!r}) but the model declares "
                    "no such binding (spec.HANDLER_BINDINGS)"
                )
        if len({(p, f) for (p, f, _ln) in sites}) > 1 and effect in bindings:
            where = ", ".join(f"{p}::{f}" for (p, f, _ln) in sorted(sites))
            problems.append(f"{effect}: annotated on multiple functions ({where})")

    for effect in sorted(bindings):
        if effect not in used_effects:
            problems.append(
                f"{effect}: declared in HANDLER_BINDINGS but no model "
                "transition references it — dead binding"
            )
    for effect in sorted(used_effects):
        if effect not in bindings:
            problems.append(
                f"{effect}: referenced by a model transition but not bound "
                "to any handler in HANDLER_BINDINGS"
            )
    return problems
