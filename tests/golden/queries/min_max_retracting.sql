--pk=g
CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE out (g BIGINT, mn BIGINT, mx BIGINT, md DOUBLE) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO out
SELECT g, min(c) as mn, max(c) as mx, median(c) as md FROM (
  SELECT counter % 4 as g, counter % 7 as k, count(*) as c
  FROM impulse
  GROUP BY 1, 2
)
GROUP BY g;
