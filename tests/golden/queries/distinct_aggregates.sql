CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE out (
  start TIMESTAMP,
  s BIGINT,
  a DOUBLE,
  mn BIGINT,
  mx BIGINT,
  md DOUBLE
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO out
SELECT window.start, s, a, mn, mx, md FROM (
  SELECT tumble(interval '20 second') as window,
         sum(DISTINCT counter % 10) as s,
         avg(DISTINCT counter % 10) as a,
         min(DISTINCT counter % 10) as mn,
         max(DISTINCT counter % 10) as mx,
         median(DISTINCT counter % 10) as md
  FROM impulse
  GROUP BY 1
);
