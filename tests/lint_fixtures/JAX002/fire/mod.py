"""MUST fire JAX002: jitted bodies mutating captured Python state."""
import jax

CACHE = {}
TRACE_LOG = []
COUNT = 0


@jax.jit
def step(x):
    TRACE_LOG.append("traced")  # runs once, at trace time
    CACHE["last"] = x  # ditto
    return x * 2


@jax.jit
def bump(x):
    global COUNT
    COUNT += 1
    return x
