"""asyncio hazard rules.

These encode the failure modes the PR-2 chaos drills hit for real: a
fire-and-forget task garbage-collected mid-flight, an event loop stalled by
a blocking call, a sync lock held across a suspension point, and a
cancellation (or the phase-2 CommitMsg riding on it) silently swallowed on
a barrier/commit path.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import (
    FileContext,
    Finding,
    Rule,
    dotted_name,
    iter_functions,
    last_attr,
    register,
    walk_scope,
)

_TASK_SPAWNERS = {"create_task", "ensure_future"}
# TaskGroup.create_task retains its tasks; discarding that result is fine.
_TASK_GROUP_BASES = {"tg", "task_group", "taskgroup", "group"}


@register
class DanglingTaskRule(Rule):
    id = "ASY001"
    name = "asyncio-dangling-task"
    description = (
        "the result of asyncio.create_task()/ensure_future() is discarded; "
        "the event loop holds only a weak reference, so the task can be "
        "garbage-collected mid-flight — retain it (named attribute, task "
        "set with done-callback discard) or await it"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Expr) or not isinstance(node.value, ast.Call):
                continue
            func = node.value.func
            attr = last_attr(func)
            if attr not in _TASK_SPAWNERS:
                continue
            if isinstance(func, ast.Attribute):
                base = last_attr(func.value)
                if base is not None and base.lower() in _TASK_GROUP_BASES:
                    continue
            out.append(
                ctx.finding(
                    self, node,
                    f"result of {attr}() discarded — task may be GC'd "
                    "mid-flight; retain or await it",
                )
            )
        return out


# dotted call names that block the event loop when made from a coroutine
_BLOCKING_CALLS = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "subprocess.run": "use `asyncio.create_subprocess_exec` or `asyncio.to_thread`",
    "subprocess.call": "use `asyncio.create_subprocess_exec` or `asyncio.to_thread`",
    "subprocess.check_call": "use `asyncio.create_subprocess_exec` or `asyncio.to_thread`",
    "subprocess.check_output": "use `asyncio.create_subprocess_exec` or `asyncio.to_thread`",
    "socket.create_connection": "use `asyncio.open_connection`",
    "socket.getaddrinfo": "use `loop.getaddrinfo`",
    "urllib.request.urlopen": "use an async client or `asyncio.to_thread`",
    "requests.get": "use an async client or `asyncio.to_thread`",
    "requests.post": "use an async client or `asyncio.to_thread`",
    "requests.put": "use an async client or `asyncio.to_thread`",
    "requests.delete": "use an async client or `asyncio.to_thread`",
    "requests.head": "use an async client or `asyncio.to_thread`",
    "requests.request": "use an async client or `asyncio.to_thread`",
    "os.system": "use `asyncio.create_subprocess_shell`",
}


@register
class BlockingCallInAsyncRule(Rule):
    id = "ASY002"
    name = "asyncio-blocking-call"
    description = (
        "a blocking call (time.sleep, sync subprocess/socket/HTTP IO) inside "
        "an `async def` stalls the whole event loop — every subtask sharing "
        "it, including barrier alignment and heartbeats"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for fn in iter_functions(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in walk_scope(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name in _BLOCKING_CALLS:
                    out.append(
                        ctx.finding(
                            self, node,
                            f"blocking call {name}() inside async def "
                            f"{fn.name}() — {_BLOCKING_CALLS[name]}",
                        )
                    )
        return out


@register
class AwaitHoldingLockRule(Rule):
    id = "ASY003"
    name = "asyncio-await-holding-lock"
    description = (
        "`await` inside a sync `with <lock>` block: the coroutine suspends "
        "while holding a threading lock, so any other coroutine (or thread) "
        "touching the lock deadlocks the loop — use asyncio.Lock with "
        "`async with`, or keep the critical section await-free"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for fn in iter_functions(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in walk_scope(fn, into_nested=False):
                if not isinstance(node, ast.With):
                    continue
                lock_name = None
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        expr = expr.func
                    name = last_attr(expr)
                    if name is not None and "lock" in name.lower():
                        lock_name = dotted_name(expr) or name
                        break
                if lock_name is None:
                    continue
                for inner in ast.walk(node):
                    if isinstance(inner, ast.Await):
                        out.append(
                            ctx.finding(
                                self, inner,
                                f"await while holding sync lock {lock_name} "
                                f"in async def {fn.name}()",
                            )
                        )
                        break
        return out


def _catches_cancellation(handler: ast.ExceptHandler) -> bool:
    """Bare except, BaseException, or (asyncio.)CancelledError."""
    t = handler.type
    if t is None:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        name = last_attr(node)
        if name in ("BaseException", "CancelledError"):
            return True
    return False


def _is_benign_terminal(handler: ast.ExceptHandler, try_node: ast.Try,
                        fn: ast.AST) -> bool:
    """A handler that only ends the task is idiomatic teardown, not a
    swallow: the try must be the final statement of the enclosing function
    and the handler body must only pass/return/log (no further work can run
    under the swallowed cancellation)."""
    if fn is None or not getattr(fn, "body", None) or fn.body[-1] is not try_node:
        return False
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Return, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            continue  # logging / metrics / cleanup-callback call
        return False
    return True


@register
class SwallowedCancellationRule(Rule):
    id = "ASY004"
    name = "asyncio-swallowed-cancellation"
    description = (
        "an exception handler catches cancellation (bare except, "
        "BaseException, or CancelledError) without re-raising while more "
        "work follows — on barrier/commit/checkpoint paths this converts a "
        "cancelled coroutine into one that keeps running, which is exactly "
        "how sealed sink transactions get stranded"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            fn = ctx.enclosing_function(node)
            for handler in node.handlers:
                if not _catches_cancellation(handler):
                    continue
                if any(isinstance(n, ast.Raise) for n in ast.walk(handler)):
                    continue
                if _is_benign_terminal(handler, node, fn):
                    continue
                what = "bare except" if handler.type is None else (
                    f"except {ast.unparse(handler.type)}"
                )
                out.append(
                    ctx.finding(
                        self, handler,
                        f"{what} swallows cancellation without re-raising "
                        "(add `raise`, or narrow the catch to Exception)",
                    )
                )
        return out
