"""Closed-loop autoscaler (ISSUE 5): policy units, the deterministic
load-step convergence acceptance scenario, signal sampling, histogram tail
quantiles, the queue-gauge staleness regression, and the end-to-end
embedded-cluster automatic rescale with exactly-once output."""

import asyncio
import gc
import json

import pytest

from arroyo_tpu.autoscale import (
    ActuationGate,
    DS2Policy,
    SimJob,
    SimOp,
    Topology,
    make_policy,
    run_scenario,
)
from arroyo_tpu.autoscale.signals import OperatorSignals, SignalSampler
from arroyo_tpu.config import config, update


def chain_job(rate=1000.0, parallelism=1):
    """source(1) -> keyed op(2) -> sink(3)."""
    return SimJob(
        [
            SimOp(1, source=True),
            SimOp(2, rate_per_instance=rate, parallelism=parallelism),
            SimOp(3, sink=True, rate_per_instance=1e9),
        ],
        [(1, 2), (2, 3)],
    )


# -- acceptance: load-step convergence ---------------------------------------


def test_load_step_convergence():
    """Offered rate steps 1x -> 4x -> 1x. The policy must converge to a
    stable parallelism within 5 control periods of each step and never
    oscillate after convergence (decision audit log asserted)."""
    job = chain_job()
    policy = make_policy("ds2")
    steps = [(6, 700.0), (8, 2800.0), (8, 700.0)]
    log = run_scenario(job, policy, config().autoscale, steps)

    # step 1 (1x): stays at 1, no rescale ever decided
    step1 = log[:6]
    assert all(r.parallelism[2] == 1 for r in step1)
    assert all(r.action != "rescale" for r in step1)

    # step 2 (4x, starts at period 6): converges to 3 (ceil(2800/1000))
    # within 5 periods, then holds — no further parallelism changes
    step2 = log[6:14]
    within = step2[:5]
    assert any(r.action == "rescale" for r in within)
    assert within[-1].parallelism[2] == 3
    settled = [r for r in step2 if r.parallelism[2] == 3]
    assert len(settled) >= 4
    first_scaled = next(i for i, r in enumerate(step2)
                        if r.parallelism[2] == 3)
    assert all(r.parallelism[2] == 3 for r in step2[first_scaled:]), \
        "oscillation after convergence in the 4x step"

    # step 3 (back to 1x, starts at period 14): back to 1 within 5
    step3 = log[14:]
    assert step3[4].parallelism[2] == 1
    first_down = next(i for i, r in enumerate(step3)
                      if r.parallelism[2] == 1)
    assert all(r.parallelism[2] == 1 for r in step3[first_down:]), \
        "oscillation after convergence in the scale-down step"

    # audit log: exactly two actuations over the whole trace, with
    # rate-based reasons, and cooldown follows each
    rescales = [r for r in log if r.action == "rescale"]
    assert len(rescales) == 2
    assert "demand" in list(rescales[0].reasons.values())[0]
    assert "busy" in list(rescales[1].reasons.values())[0]
    assert log[rescales[0].period + 1].action == "cooldown"
    # every record carries the signals it was decided from
    assert all(r.signals[2].get("parallelism") for r in log)


def test_convergence_respects_scale_factor_cap():
    """A 16x step cannot be closed in one move with a 4x per-step cap;
    successive decisions (with cooldown between) stair-step up."""
    job = chain_job()
    with update(autoscale={"cooldown_periods": 1, "warmup_periods": 0,
                           "max_parallelism": 32}):
        log = run_scenario(job, make_policy("ds2"), config().autoscale,
                           [(12, 16000.0)])
    pars = [r.parallelism[2] for r in log]
    assert 4 in pars and pars[-1] == 16  # 1 -> 4 -> 16 under the cap
    assert max(pars) == 16


# -- policy units ------------------------------------------------------------


def _topo(current=1):
    return Topology(
        order=[1, 2, 3],
        upstream={1: [], 2: [1], 3: [2]},
        current={1: 1, 2: current, 3: 1},
        scalable={1: False, 2: True, 3: False},
    )


def test_saturation_fallback_under_backpressure():
    """Backpressured upstream + throttled rates (rate ratio says 'hold'):
    the policy must still scale up, geometrically."""
    signals = {
        1: OperatorSignals(node_id=1, parallelism=1, output_rate=2000.0,
                           backpressure=1.0),
        2: OperatorSignals(node_id=2, parallelism=2, observed_rate=2000.0,
                           output_rate=40.0, busy_ratio=1.0,
                           true_rate_per_instance=1000.0),
    }
    d = DS2Policy().decide(_topo(current=2), signals, config().autoscale)
    assert d.targets[2] == 4  # 2 * saturation_step
    assert "saturation" in d.reasons[2]


def test_hysteresis_holds_small_deltas():
    """A rate-based target within the hysteresis band is not actuated."""
    signals = {
        1: OperatorSignals(node_id=1, parallelism=1, output_rate=5300.0),
        2: OperatorSignals(node_id=2, parallelism=5, observed_rate=5300.0,
                           output_rate=5300.0, busy_ratio=0.25,
                           true_rate_per_instance=1000.0),
    }
    # rate target = ceil(5300/1000) = 6, |6-5|/5 = 0.2 <= hysteresis
    d = DS2Policy().decide(_topo(current=5), signals, config().autoscale)
    assert d.targets[2] == 5 and 2 not in d.reasons


def test_min_parallelism_clamp_is_unconditional():
    """min_parallelism above current forces a scale-up with no load
    signal at all — the deterministic trigger the rescale drill uses."""
    signals = {
        1: OperatorSignals(node_id=1, parallelism=1, output_rate=10.0),
        2: OperatorSignals(node_id=2, parallelism=1, observed_rate=10.0,
                           output_rate=10.0, busy_ratio=0.01,
                           true_rate_per_instance=1000.0),
    }
    with update(autoscale={"min_parallelism": 2, "max_parallelism": 2}):
        d = DS2Policy().decide(_topo(), signals, config().autoscale)
    assert d.targets[2] == 2
    assert "clamped" in d.reasons[2]


def test_unscalable_nodes_never_move():
    signals = {
        1: OperatorSignals(node_id=1, parallelism=1, output_rate=9000.0,
                           backpressure=1.0),
        2: OperatorSignals(node_id=2, parallelism=1, observed_rate=9000.0,
                           output_rate=9000.0, busy_ratio=1.0,
                           true_rate_per_instance=100.0),
    }
    topo = _topo()
    topo.scalable[2] = False
    d = DS2Policy().decide(topo, signals, config().autoscale)
    assert d.targets == {1: 1, 2: 1, 3: 1}


def test_actuation_gate_cadence():
    cfg = config().autoscale
    gate = ActuationGate(cfg)
    changed = {2: 4}
    assert gate.check(changed) == "warmup"
    assert gate.check(changed) == "warmup"
    assert gate.check(changed, pinned=True) == "pinned"
    assert gate.check(changed) == "rescale"
    assert gate.check(changed) == "cooldown"
    assert gate.check({}) == "cooldown"
    assert gate.check({}) == "cooldown"
    assert gate.check({}) == "hold"
    assert gate.check(changed) == "rescale"


def test_topology_scalability_from_graph():
    """Keyed-input internal nodes are scalable, plus sources whose
    connector's offset state repartitions (ISSUE 15: impulse/nexmark
    split elasticity). Sinks and nodes fed by unkeyed edges keep their
    planned parallelism."""
    from arroyo_tpu.sql import plan_query

    g = plan_query(
        """
        CREATE TABLE impulse WITH (
          connector = 'impulse', event_rate = '1000',
          message_count = '10', start_time = '0'
        );
        CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
          connector = 'single_file', path = '/tmp/x.json',
          format = 'json', type = 'sink'
        );
        INSERT INTO out
        SELECT k, cnt FROM (
          SELECT counter % 8 as k, tumble(interval '1 millisecond') as w,
                 count(*) as cnt
          FROM impulse GROUP BY 1, 2
        );
        """,
        parallelism=1,
    ).graph
    topo = Topology.from_graph(g)
    scalable = [nid for nid, ok in topo.scalable.items() if ok]
    # exactly the keyed windowed-agg node + the elastic impulse source
    assert len(scalable) == 2
    srcs = [nid for nid in scalable if topo.source.get(nid)]
    assert len(srcs) == 1, "the impulse source is scalable (splits)"
    internal = [nid for nid in scalable if not topo.source.get(nid)]
    assert all(
        e.schema.key_indices for e in g.in_edges(internal[0])
    )
    # a non-elastic source (single_file) stays unscalable
    assert all(
        topo.scalable.get(n.node_id) is False
        for n in g.nodes.values() if n.is_sink
    )


# -- forward-edge degradation on override ------------------------------------


def test_update_parallelism_flips_unbalanced_forward_edges():
    from arroyo_tpu.sql import plan_query
    from arroyo_tpu.graph.logical import EdgeType

    g = plan_query(
        """
        CREATE TABLE impulse WITH (
          connector = 'impulse', event_rate = '1000',
          message_count = '10', start_time = '0'
        );
        CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
          connector = 'single_file', path = '/tmp/x.json',
          format = 'json', type = 'sink'
        );
        INSERT INTO out
        SELECT k, cnt FROM (
          SELECT counter % 8 as k, tumble(interval '1 millisecond') as w,
                 count(*) as cnt
          FROM impulse GROUP BY 1, 2
        );
        """,
        parallelism=1,
    ).graph
    agg = [nid for nid, ok in
           Topology.from_graph(g).scalable.items() if ok][0]
    had_forward = any(e.edge_type == EdgeType.FORWARD
                      for e in g.out_edges(agg))
    g.update_parallelism({agg: 3})
    assert g.nodes[agg].parallelism == 3
    for e in g.edges:
        if e.edge_type == EdgeType.FORWARD:
            assert (g.nodes[e.src].parallelism
                    == g.nodes[e.dst].parallelism), \
                "unbalanced forward edge survived update_parallelism"
    if had_forward:
        assert any(e.edge_type == EdgeType.SHUFFLE
                   for e in g.out_edges(agg))


# -- signal sampling ---------------------------------------------------------


def _snap(recv, sent, busy, job="j1"):
    def entries(vals):
        return [({"job": job, "task": f"2-{i}"}, v)
                for i, v in enumerate(vals)]

    return {
        "arroyo_worker_messages_recv": entries(recv),
        "arroyo_worker_messages_sent": entries(sent),
        "arroyo_worker_busy_seconds": entries(busy),
        "arroyo_worker_backpressure": entries([0.75] * len(recv)),
    }


def test_signal_sampler_rates_and_true_rate():
    from arroyo_tpu.autoscale.signals import merge_snapshots

    s = SignalSampler("j1")
    assert s.sample(merge_snapshots([_snap([0, 0], [0, 0], [0, 0])]),
                    {2: 2}, now=100.0) is None  # baseline
    sigs = s.sample(
        merge_snapshots([_snap([1000, 1000], [200, 200], [0.5, 0.5])]),
        {2: 2}, now=101.0,
    )
    sig = sigs[2]
    assert sig.observed_rate == pytest.approx(2000.0)
    assert sig.output_rate == pytest.approx(400.0)
    assert sig.busy_ratio == pytest.approx(0.5)  # 1 busy-sec / (1s * 2)
    assert sig.true_rate_per_instance == pytest.approx(2000.0)
    assert sig.selectivity == pytest.approx(0.2)
    assert sig.backpressure == pytest.approx(0.75)


def test_signal_sampler_counter_restart_clamps():
    """A replaced worker restarts counters at zero; the delta must clamp
    to the observed value, never go negative."""
    from arroyo_tpu.autoscale.signals import merge_snapshots

    s = SignalSampler("j1")
    s.sample(merge_snapshots([_snap([5000], [5000], [2.0])]), {2: 1},
             now=10.0)
    sigs = s.sample(merge_snapshots([_snap([300], [300], [0.1])]), {2: 1},
                    now=11.0)
    assert sigs[2].observed_rate == pytest.approx(300.0)
    assert sigs[2].busy_ratio == pytest.approx(0.1)


def test_merge_snapshots_unions_identical_embedded_workers():
    from arroyo_tpu.autoscale.signals import merge_snapshots

    snap = _snap([100], [100], [0.5])
    merged = merge_snapshots([snap, snap, snap])  # same-process workers
    assert len(merged["arroyo_worker_messages_recv"]) == 1
    (_, v), = merged["arroyo_worker_messages_recv"].items()
    assert v == 100


# -- histogram tail quantiles (satellite) ------------------------------------


def test_hist_quantiles_interpolation():
    from arroyo_tpu.metrics import REGISTRY, hist_quantiles

    h = REGISTRY.histogram("t_autoscale_q", "t", buckets=(0.1, 0.2, 0.4))
    handle = h.labels(x="1")
    for _ in range(50):
        handle.observe(0.15)  # lands in the (0.1, 0.2] bucket
    for _ in range(50):
        handle.observe(0.35)  # lands in the (0.2, 0.4] bucket
    qs = hist_quantiles(handle.get_hist(), (0.5, 0.95, 0.99))
    # p50 sits at the edge of the second bucket; p95/p99 interpolate
    # inside the third
    assert 0.1 <= qs["p50"] <= 0.2
    assert 0.2 < qs["p95"] <= 0.4
    assert qs["p99"] > qs["p95"] - 1e-9
    assert hist_quantiles(None) == {}
    assert hist_quantiles({"sum": 0, "count": 0, "buckets": {}}) == {}


def test_operator_metric_groups_expose_quantiles():
    """REST flattening emits :p50/:p95/:p99 series beside the mean for
    histogram families (the UI and the autoscaler need tails)."""
    from arroyo_tpu.metrics import BATCH_PROCESSING_SECONDS, hist_quantiles

    handle = BATCH_PROCESSING_SECONDS.labels(job="qjob", task="7-0")
    for v in (0.002, 0.004, 0.008, 0.3):
        handle.observe(v)

    from arroyo_tpu.api.rest import ApiServer

    class FakeReq:
        match_info = {"job_id": "qjob"}

    api = ApiServer.__new__(ApiServer)  # no db needed for this route
    api.controller = None
    resp = asyncio.run(api.operator_metric_groups(FakeReq()))
    data = json.loads(resp.body.decode())["data"]
    groups = {g["name"] for op in data for g in op["metricGroups"]
              if op["operatorId"] == "7"}
    assert "batch_processing_seconds" in groups
    assert {"batch_processing_seconds:p50",
            "batch_processing_seconds:p95",
            "batch_processing_seconds:p99"} <= groups
    want = hist_quantiles(handle.get_hist())
    series = {
        g["name"]: g["subtasks"][0]["metrics"][0]["value"]
        for op in data for g in op["metricGroups"]
        if op["operatorId"] == "7"
    }
    assert series["batch_processing_seconds:p95"] == pytest.approx(
        want["p95"])


# -- queue gauge staleness regression (satellite) ----------------------------


def test_queue_gauges_refresh_at_scrape_time():
    """QUEUE_SIZE/QUEUE_BYTES only updated on the push/pop hot paths; a
    scrape between events must still see live occupancy, and a collected
    queue must unregister its refresher (weakref-holder pattern, same
    class as the PR 1 backpressure fix)."""
    import pyarrow as pa

    from arroyo_tpu import metrics
    from arroyo_tpu.operators.queues import BatchQueue

    name = "t-refresh-q"
    q = BatchQueue(8, 1 << 20, name)
    batch = pa.RecordBatch.from_arrays([pa.array([1, 2, 3])], names=["v"])

    async def fill():
        await q.send(batch)
        await q.send(batch)
        # sabotage the stored sample to prove the scrape recomputes it
        with metrics.QUEUE_SIZE.lock:
            metrics.QUEUE_SIZE.values[(("queue", name),)] = 999.0

    asyncio.run(fill())
    got = {
        tuple(sorted(labels.items())): v
        for labels, v in metrics.REGISTRY.snapshot()[
            "arroyo_worker_queue_size"]
    }
    key = (("queue", name),)
    assert got[key] == 2.0
    assert key in metrics.QUEUE_SIZE.refreshers
    del q, fill
    gc.collect()
    metrics.REGISTRY.snapshot()  # dead refresher drops itself
    assert key not in metrics.QUEUE_SIZE.refreshers


# -- end-to-end: automatic rescale on sustained backpressure -----------------


def test_autoscaler_e2e_backpressure_rescale(tmp_path):
    """Acceptance (ISSUE 5): a windowed-agg job whose aggregation chain
    cannot keep up builds sustained backpressure; the autoscaler detects
    it, triggers an automatic exactly-once rescale through
    stop-with-checkpoint -> override -> restore, the job finishes with
    complete output, and the `{job}/rescale-1` trace is ONE connected
    span tree: decide -> stop-checkpoint -> reschedule -> restore."""
    import pyarrow as pa

    from arroyo_tpu import obs
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState
    from arroyo_tpu.udf import udf

    @udf(pa.int64(), [pa.int64()], name="slow_cnt")
    def slow_cnt(xs):
        import time as _t

        _t.sleep(0.03)  # per emitted window batch: saturates the chain
        return xs

    n = 9000
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '3000',
      message_count = '{n}', start_time = '0', realtime = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{tmp_path}/out.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, slow_cnt(cnt) as cnt FROM (
      SELECT counter % 8 as k, tumble(interval '25 millisecond') as w,
             count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """

    async def go():
        with update(
            pipeline={"checkpointing": {"interval": 0.2}},
            obs={"trace_buffer_spans": 32768},
            autoscale={
                "enabled": True, "period": 0.25, "warmup_periods": 1,
                "cooldown_periods": 2, "max_parallelism": 2,
            },
        ):
            obs.reset()
            c = await ControllerServer(EmbeddedScheduler()).start()
            try:
                await c.submit_job(
                    "au1", sql=sql, storage_url=str(tmp_path / "ck"),
                    n_workers=1, parallelism=1,
                )
                state = await c.wait_for_state(
                    "au1", JobState.FINISHED, JobState.FAILED, timeout=90
                )
                job = c.jobs["au1"]
                return (state, job.rescales, list(job.autoscale_decisions),
                        {nid: nd.parallelism
                         for nid, nd in job.graph.nodes.items()})
            finally:
                await c.stop()

    state, rescales, decisions, parallelism = asyncio.run(go())
    assert state == JobState.FINISHED
    assert rescales >= 1, (
        f"autoscaler never actuated; decisions: {decisions[-8:]}"
    )
    # decision audit log: a rescale decision driven by backpressure
    acted = [d for d in decisions if d["action"] == "rescale"]
    assert acted, decisions
    # some node ran at the scaled-up parallelism: assert the PEAK from
    # the actuated decisions' targets rather than the final graph — on
    # a slow/contended run the autoscaler legitimately scales back DOWN
    # once the source drains, and the final parallelism is 1 again
    peak = max(
        max(int(p) for p in d["targets"].values())
        for d in acted
    )
    assert peak == 2, acted
    reason = " ".join(acted[0]["reasons"].values())
    assert "saturation" in reason or "demand" in reason
    assert acted[0]["signals"], "rescale decision recorded without signals"

    # exactly-once output across the automatic rescale
    counts = {}
    with open(tmp_path / "out.json") as f:
        for line in f:
            if line.strip():
                r = json.loads(line)
                counts[r["k"]] = counts.get(r["k"], 0) + r["cnt"]
    assert sum(counts.values()) == n, counts
    assert counts == {k: n // 8 for k in range(8)}

    # flight recorder: {job}/rescale-1 forms one connected tree with the
    # full decide -> stop-checkpoint -> reschedule -> restore path
    spans = obs.recorder().snapshot(trace_prefix="au1/rescale-1")
    assert spans, "no spans recorded for the rescale trace"
    by_id = {s["span_id"]: s for s in spans}
    roots = [s for s in spans if not s.get("parent_id")]
    assert len(roots) == 1, [s["name"] for s in roots]
    assert roots[0]["name"] == "autoscale.decide"
    # transitive reach from the root
    children = {}
    for s in spans:
        children.setdefault(s.get("parent_id"), []).append(s)
    reached = set()
    stack = [roots[0]["span_id"]]
    while stack:
        sid = stack.pop()
        if sid in reached:
            continue
        reached.add(sid)
        stack += [c["span_id"] for c in children.get(sid, [])]
    reached_names = {by_id[sid]["name"] for sid in reached if sid in by_id}
    for required in ("autoscale.decide", "job.rescale",
                     "rescale.stop_checkpoint", "checkpoint",
                     "task.start"):
        assert required in reached_names, (
            f"{required} not connected to the rescale root; "
            f"reached={sorted(reached_names)}"
        )
    # either path completes the tree: the generation-overlap promote
    # (rescale.overlap — the default on a pooled embedded cluster) or a
    # stop-the-world reschedule (job.schedule)
    assert ("rescale.overlap" in reached_names
            or "job.schedule" in reached_names), sorted(reached_names)


def test_autoscale_rest_surface(tmp_path):
    """GET /api/v1/jobs/{id}/autoscale returns the decision history and
    pin state; PATCH pins/unpins; 404 on unknown jobs."""
    from aiohttp.test_utils import TestClient, TestServer

    from arroyo_tpu.api.rest import build_app
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState

    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '100000',
      message_count = '2000', start_time = '0'
    );
    CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
      connector = 'single_file', path = '{tmp_path}/out.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out SELECT counter FROM impulse;
    """

    async def go():
        controller = await ControllerServer(EmbeddedScheduler()).start()
        app = build_app(controller, db_path=":memory:")
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await controller.submit_job("aj1", sql=sql)
            r = await client.get("/api/v1/jobs/nope/autoscale")
            assert r.status == 404
            r = await client.get("/api/v1/jobs/aj1/autoscale")
            assert r.status == 200
            body = await r.json()
            assert body["pinned"] is False and body["rescales"] == 0
            assert "decisions" in body and "parallelism" in body
            r = await client.patch("/api/v1/jobs/aj1/autoscale",
                                   json={"pinned": True})
            assert (await r.json())["pinned"] is True
            assert controller.jobs["aj1"].autoscale_pinned is True
            r = await client.patch("/api/v1/jobs/aj1/autoscale",
                                   json={"pinned": "yes"})
            assert r.status == 400
            await controller.wait_for_state(
                "aj1", JobState.FINISHED, JobState.FAILED, timeout=30
            )
        finally:
            await client.close()
            await controller.stop()

    asyncio.run(go())
