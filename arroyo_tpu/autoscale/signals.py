"""Signal sampling for the autoscaler: metrics registry -> per-operator rates.

The observe step of the control loop (DS2, Kalavri et al. OSDI '18 §3:
"three steps is all you need" — observe true rates, decide by rate ratios,
actuate). Each control period the sampler takes a registry snapshot
(merged across the job's workers over the GetMetrics rpc — identical
snapshots from embedded same-process workers union to one), diffs the
task-labeled counters against the previous period, and aggregates the
deltas into one `OperatorSignals` per logical node:

  observed_rate            rows/s actually processed (recv counters)
  output_rate              rows/s emitted (sent counters)
  busy_ratio               useful-work seconds / (period * parallelism)
  true_rate_per_instance   rows per busy-second — the DS2 true processing
                           rate, independent of how idle/backpressured the
                           operator currently is
  selectivity              output rows per input row (demand propagation)
  backpressure             fullness of the operator's own output queues
                           (an op is the bottleneck when its UPSTREAMs'
                           backpressure is high)
  watermark_lag            seconds the subtask watermark trails wall clock

Since ISSUE 13 the sampler is backed by the retained metric-history
tier (`obs/history.py`): each control period's merged snapshot is
ingested into a private `MetricHistory` and every rate/delta/quantile
is a WINDOWED query over it — counter-restart clamping (a replaced
worker restarts counters at zero; the delta reads as the post-restart
value, never negative) lives in `history.Series.delta`, the one
rate-computation code path shared with the watchtower SLO engine and
the doctor, instead of ad-hoc `prev`-dict diffing here.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

from ..obs.history import MetricHistory

# metric families the sampler consumes (names, not handles: snapshots may
# come over the wire from another process's registry)
_RECV = "arroyo_worker_messages_recv"
_SENT = "arroyo_worker_messages_sent"
_BUSY = "arroyo_worker_busy_seconds"
_BACKPRESSURE = "arroyo_worker_backpressure"
_WM_LAG = "arroyo_worker_watermark_lag_seconds"
_BATCH_HIST = "arroyo_worker_batch_processing_seconds"


@dataclasses.dataclass
class OperatorSignals:
    """One control period's aggregated view of a logical operator."""

    node_id: int
    parallelism: int
    observed_rate: float = 0.0
    output_rate: float = 0.0
    busy_ratio: Optional[float] = None
    true_rate_per_instance: Optional[float] = None
    selectivity: float = 1.0
    backpressure: float = 0.0
    watermark_lag: float = 0.0
    # tail latency of batch processing (estimated from cumulative buckets;
    # metrics.hist_quantiles) — audit-log context, not a decision input
    batch_p95: Optional[float] = None

    def summary(self) -> dict:
        out = dataclasses.asdict(self)
        return {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in out.items() if v is not None
        }


def merge_snapshots(snapshots: List[dict]) -> Dict[str, Dict[tuple, object]]:
    """Union registry snapshots keyed by (metric, sorted label items).
    Embedded workers share one process registry and return identical
    snapshots — the union collapses them instead of double counting."""
    merged: Dict[str, Dict[tuple, object]] = {}
    for snap in snapshots:
        for name, entries in (snap or {}).items():
            dst = merged.setdefault(name, {})
            for labels, value in entries:
                dst[tuple(sorted(dict(labels).items()))] = value
    return merged


def _task_values(merged: Dict[str, Dict[tuple, object]], metric: str,
                 job_id: str) -> Dict[Tuple[int, int], object]:
    """{(node_id, subtask): value} for a job's task-labeled family."""
    out: Dict[Tuple[int, int], object] = {}
    for labels, value in merged.get(metric, {}).items():
        d = dict(labels)
        if d.get("job") != job_id:
            continue
        task = d.get("task") or ""
        node, _, sub = task.rpartition("-")
        try:
            out[(int(node), int(sub))] = value
        except ValueError:
            continue
    return out


# families the sampler retains in its private history instance — the
# node-aggregated per-control-period view needs nothing else
_SAMPLER_FAMILIES = (_RECV, _SENT, _BUSY, _BACKPRESSURE, _WM_LAG,
                     _BATCH_HIST)


def _node_of(series) -> Optional[int]:
    task = series.label("task")
    node, _, _sub = task.rpartition("-")
    try:
        return int(node)
    except ValueError:
        return None


class SignalSampler:
    """Stateful per-job sampler over the metric-history tier: every
    control period's merged snapshot is ingested, and signals are
    windowed queries over the retained series."""

    def __init__(self, job_id: str,
                 history: Optional[MetricHistory] = None):
        self.job_id = job_id
        # a private, family-pinned history: the autoscaler's `now`
        # timestamps come from its own control loop, not the pump's
        self.history = history or MetricHistory(
            retain=_SAMPLER_FAMILIES)
        self._prev_time: Optional[float] = None

    def reset(self) -> None:
        """Forget history (after a reschedule/rescale the topology and the
        worker set changed; the next sample only re-seeds the baseline)."""
        self.history.reset()
        self._prev_time = None

    def sample(self, merged: Dict[str, Dict[tuple, object]],
               node_parallelism: Dict[int, int],
               now: Optional[float] = None) -> Optional[Dict[int, OperatorSignals]]:
        """Ingest the merged snapshot and read windowed signals covering
        the elapsed control period. Returns None on the first call
        (baseline only — rates need two points)."""
        now = time.monotonic() if now is None else now
        self.history.ingest(merged, now=now)
        prev_time, self._prev_time = self._prev_time, now
        if prev_time is None:
            return None
        window = max(1e-6, now - prev_time)
        return self.from_history(node_parallelism, window, now=now)

    def from_history(self, node_parallelism: Dict[int, int],
                     window: float,
                     now: Optional[float] = None) -> Dict[int, OperatorSignals]:
        """Windowed per-node signals straight from the history tier —
        the one rate code path (`Series.delta`/`rate`/`hist_window`)
        the watchtower and doctor also read. Callable directly against
        a shared history instance (window = the control period)."""
        from ..metrics import hist_quantiles

        now = time.monotonic() if now is None else now

        def node_deltas(family: str) -> Dict[int, float]:
            out: Dict[int, float] = {}
            for s in self.history.get(family, job=self.job_id):
                nid = _node_of(s)
                if nid is None:
                    continue
                d = s.delta(window, now)
                if d is not None:
                    out[nid] = out.get(nid, 0.0) + d
            return out

        def node_latest_max(family: str) -> Dict[int, float]:
            out: Dict[int, float] = {}
            for s in self.history.get(family, job=self.job_id):
                nid = _node_of(s)
                v = s.latest()
                if nid is None or v is None:
                    continue
                out[nid] = max(out.get(nid, 0.0), float(v))
            return out

        recv = node_deltas(_RECV)
        sent = node_deltas(_SENT)
        busy = node_deltas(_BUSY)
        bp = node_latest_max(_BACKPRESSURE)
        lag = node_latest_max(_WM_LAG)

        out: Dict[int, OperatorSignals] = {}
        nodes = set(recv) | set(sent) | set(busy) | set(node_parallelism)
        for nid in nodes:
            dr = recv.get(nid, 0.0)
            ds = sent.get(nid, 0.0)
            db = busy.get(nid, 0.0)
            par = max(1, node_parallelism.get(nid, 1))
            sig = OperatorSignals(node_id=nid, parallelism=par)
            sig.observed_rate = dr / window
            sig.output_rate = ds / window
            if db > 0:
                sig.busy_ratio = min(1.0, db / (window * par))
                if dr > 0:
                    sig.true_rate_per_instance = dr / db
            sig.selectivity = (ds / dr) if dr > 0 else 1.0
            sig.backpressure = bp.get(nid, 0.0)
            sig.watermark_lag = lag.get(nid, 0.0)
            p95s = []
            for s in self.history.get(_BATCH_HIST, job=self.job_id):
                if _node_of(s) != nid:
                    continue
                # windowed tail latency: the cumulative-bucket diff over
                # this control period, not the job's lifetime histogram
                p95 = hist_quantiles(
                    s.hist_window(window, now) or s.latest(), (0.95,)
                ).get("p95")
                if p95 is not None:
                    p95s.append(p95)
            if p95s:
                sig.batch_p95 = max(p95s)
            out[nid] = sig
        return out
