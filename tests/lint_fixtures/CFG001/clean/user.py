"""Must NOT fire CFG001: every read resolves to a declared field."""
from .config import config, update

ENV_OK = "ARROYO__PIPELINE__BATCH_SIZE"
ENV_NESTED = "ARROYO__PIPELINE__CHECKPOINTING__INTERVAL"


def go():
    ok = config().pipeline.batch_size
    nested = config().pipeline.checkpointing.interval
    with update(pipeline={"batch_size": 64, "checkpointing": {"interval": 1}}):
        pass
    return ok, nested
