"""Connectors + formats: serde roundtrips, single_file through SQL with
restore, nexmark generation + a nexmark query, filesystem sink 2PC."""

import asyncio
import json
import os

import pyarrow as pa
import pytest

from arroyo_tpu.config import update
from arroyo_tpu.engine import Engine
from arroyo_tpu.formats.de import BadDataError, Deserializer
from arroyo_tpu.formats.ser import Serializer
from arroyo_tpu.schema import StreamSchema
from arroyo_tpu.sql import plan_query


def run_plan(plan, storage_url=None, job_id="t", timeout=60.0):
    async def go():
        eng = Engine(plan.graph, job_id=job_id, storage_url=storage_url).start()
        await eng.join(timeout)
        return eng

    return asyncio.run(go())


# -- formats ------------------------------------------------------------------


def test_json_deserialize_schema_and_baddata():
    s = StreamSchema.from_fields([("a", pa.int64()), ("b", pa.string())])
    d = Deserializer(s, format="json", bad_data="drop", framing="newline")
    rows = d.deserialize_slice(b'{"a": 1, "b": "x"}\nnot json\n{"a": 2}')
    assert len(rows) == 2
    assert rows[0]["a"] == 1 and rows[0]["b"] == "x"
    assert rows[1]["b"] is None
    d_fail = Deserializer(s, format="json", bad_data="fail")
    with pytest.raises(BadDataError):
        d_fail.deserialize_slice(b"not json")


def test_json_timestamp_parsing_scales():
    s = StreamSchema.from_fields([("t", pa.timestamp("ns"))])
    d = Deserializer(s, format="json", framing="newline")
    rows = d.deserialize_slice(
        b'{"t": 1000000000}\n'  # seconds
        b'{"t": 1000000000000}\n'  # millis
        b'{"t": "2020-01-01T00:00:00Z"}',
        timestamp=0,
    )
    assert rows[0]["t"] == 1_000_000_000 * 1_000_000_000
    assert rows[1]["t"] == 1_000_000_000_000 * 1_000_000
    assert rows[2]["t"] == 1_577_836_800 * 1_000_000_000


def test_serializer_json_and_debezium():
    s = StreamSchema.from_fields([("a", pa.int64())])
    batch = pa.RecordBatch.from_arrays(
        [pa.array([1, 2]), pa.array([0, 0], type=pa.int64()).cast(pa.timestamp("ns"))],
        schema=s.schema,
    )
    recs = list(Serializer("json").serialize(batch))
    assert [json.loads(r) for r in recs] == [{"a": 1}, {"a": 2}]
    dbz = [json.loads(r) for r in Serializer("debezium_json").serialize(batch)]
    assert dbz[0]["op"] == "c" and dbz[0]["after"] == {"a": 1}


def test_avro_roundtrip():
    from arroyo_tpu.formats.avro import AvroDecoder, AvroEncoder, schema_from_arrow

    schema = pa.schema([("x", pa.int64()), ("name", pa.string()),
                        ("score", pa.float64())])
    avro_schema = json.dumps(schema_from_arrow(schema))
    enc = AvroEncoder(avro_schema, schema)
    dec = AvroDecoder(avro_schema)
    row = {"x": 42, "name": "hello", "score": 2.5}
    assert dec.decode(enc.encode(row)) == row
    assert dec.decode(enc.encode({"x": None, "name": "a", "score": 0.0}))["x"] is None


# -- single_file through SQL with checkpoint/restore --------------------------


def make_cars(path, n=200):
    import random

    random.seed(7)
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({
                "timestamp": f"2023-01-01T00:00:{i % 60:02d}.{i:03d}Z",
                "driver_id": 100 + i % 5,
                "event_type": "pickup" if i % 2 else "dropoff",
            }) + "\n")


def sql_for(tmp, out_name="out.json", throttle=""):
    return f"""
    CREATE TABLE cars (
      timestamp TIMESTAMP,
      driver_id BIGINT,
      event_type TEXT
    ) WITH (
      connector = 'single_file',
      path = '{tmp}/cars.json',
      format = 'json',
      type = 'source',
      event_time_field = 'timestamp'{throttle}
    );
    CREATE TABLE out (
      driver_id BIGINT,
      cnt BIGINT
    ) WITH (
      connector = 'single_file',
      path = '{tmp}/{out_name}',
      format = 'json',
      type = 'sink'
    );
    INSERT INTO out
    SELECT driver_id, cnt FROM (
      SELECT driver_id, tumble(interval '1 minute') as w, count(*) as cnt
      FROM cars
      GROUP BY 1, 2
    );
    """


def read_output(path):
    with open(path) as f:
        return sorted(
            (json.loads(line)["driver_id"], json.loads(line)["cnt"])
            for line in f if line.strip()
        )


def test_single_file_sql_roundtrip(tmp_path):
    make_cars(tmp_path / "cars.json")
    plan = plan_query(sql_for(tmp_path))
    run_plan(plan)
    out = read_output(tmp_path / "out.json")
    assert len(out) == 5
    assert sum(c for _, c in out) == 200


def test_single_file_checkpoint_restore_same_output(tmp_path):
    make_cars(tmp_path / "cars.json")
    golden = plan_query(sql_for(tmp_path, "golden.json"))
    run_plan(golden)
    want = read_output(tmp_path / "golden.json")

    url = str(tmp_path / "ckpt")

    async def run_and_stop():
        plan = plan_query(
            sql_for(tmp_path, throttle=",\n      throttle_per_sec = '1000'")
        )
        eng = Engine(plan.graph, job_id="sfr", storage_url=url).start()
        # let some rows flow (throttled to 1k/s), checkpoint-stop mid-stream
        await asyncio.sleep(0.1)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(run_and_stop())

    plan2 = plan_query(sql_for(tmp_path))
    run_plan(plan2, storage_url=url, job_id="sfr")
    assert read_output(tmp_path / "out.json") == want


# -- nexmark ------------------------------------------------------------------


def test_nexmark_generator_proportions():
    from arroyo_tpu.connectors.nexmark import NexmarkGenerator

    g = NexmarkGenerator()
    kinds = [g.kind_of(n) for n in range(5000)]
    assert kinds.count("person") == 100
    assert kinds.count("auction") == 300
    assert kinds.count("bid") == 4600
    # deterministic
    e1 = g.event(77, 123)
    e2 = NexmarkGenerator().event(77, 123)
    assert e1 == e2
    # bids reference existing auctions
    for n in range(4, 50):
        ev = g.event(n, 0)
        if ev["bid"]:
            assert 1000 <= ev["bid"]["auction"] <= g.last_auction_id(n)


def test_nexmark_sql_query():
    """q1-flavored query over the nexmark connector table."""
    results = []
    plan = plan_query(
        """
        CREATE TABLE nexmark WITH (
          connector = 'nexmark',
          event_rate = '100000',
          message_count = '5000',
          start_time = '0'
        );
        SELECT bid.auction as auction, bid.price * 100 as price
        FROM nexmark WHERE bid IS NOT NULL;
        """,
        preview_results=results,
    )
    run_plan(plan)
    assert len(results) == 4600
    assert all(r["price"] % 100 == 0 for r in results)


def test_nexmark_q5_shape():
    """hop-window count grouped by auction (the q5 inner query)."""
    results = []
    plan = plan_query(
        """
        CREATE TABLE nexmark WITH (
          connector = 'nexmark',
          event_rate = '1000000',
          message_count = '50000',
          start_time = '0'
        );
        SELECT auction, num FROM (
          SELECT bid.auction as auction, count(*) AS num,
                 hop(interval '10 millisecond', interval '20 millisecond') as window
          FROM nexmark WHERE bid IS NOT NULL
          GROUP BY 1, window
        );
        """,
        preview_results=results,
    )
    run_plan(plan)
    assert len(results) > 0
    total = sum(r["num"] for r in results)
    # each bid appears in width/slide = 2 windows
    assert total == 2 * 4600 * 10


# -- filesystem sink -----------------------------------------------------------


def test_filesystem_sink_parquet(tmp_path):
    out_dir = tmp_path / "fs_out"
    plan = plan_query(
        f"""
        CREATE TABLE impulse WITH (
          connector = 'impulse', event_rate = '1000000',
          message_count = '1000', start_time = '0'
        );
        CREATE TABLE out (
          counter BIGINT UNSIGNED
        ) WITH (
          connector = 'filesystem',
          path = '{out_dir}',
          format = 'parquet',
          rollover_rows = '400',
          type = 'sink'
        );
        INSERT INTO out SELECT counter FROM impulse;
        """
    )
    run_plan(plan)
    import pyarrow.parquet as pq

    files = [f for f in os.listdir(out_dir) if f.endswith(".parquet")]
    assert len(files) >= 2  # rolled at 400 rows
    total = sum(pq.read_table(out_dir / f).num_rows for f in files)
    assert total == 1000
    assert not [f for f in os.listdir(out_dir) if f.endswith(".tmp")]


def test_connector_registry_metadata():
    from arroyo_tpu.connectors import connectors

    names = {c.name for c in connectors()}
    assert {
        "kafka", "impulse", "nexmark", "single_file", "filesystem", "sse",
        "websocket", "polling_http", "webhook", "redis", "mqtt", "nats",
        "rabbitmq", "kinesis", "fluvio", "stdout", "blackhole", "preview",
        "confluent", "vec",
    } <= names
    for c in connectors():
        md = c.metadata()
        assert md["id"] and isinstance(md["config_schema"], dict)


def test_delta_sink(tmp_path):
    """Delta log written on commit: protocol + metaData at version 0, add
    actions matching the visible parquet files, stats row counts exact."""
    out_dir = tmp_path / "delta_out"
    plan = plan_query(
        f"""
        CREATE TABLE impulse WITH (
          connector = 'impulse', event_rate = '1000000',
          message_count = '1000', start_time = '0'
        );
        CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
          connector = 'delta', path = '{out_dir}',
          rollover_rows = '400', type = 'sink'
        );
        INSERT INTO out SELECT counter FROM impulse;
        """
    )
    run_plan(plan)
    import pyarrow.parquet as pq

    log_dir = out_dir / "_delta_log"
    versions = sorted(log_dir.glob("*.json"))
    assert versions, "no delta log written"
    actions = []
    for v in versions:
        with open(v) as f:
            actions.extend(json.loads(l) for l in f if l.strip())
    protos = [a for a in actions if "protocol" in a]
    metas = [a for a in actions if "metaData" in a]
    adds = [a["add"] for a in actions if "add" in a]
    assert len(protos) == 1 and protos[0]["protocol"]["minReaderVersion"] == 1
    assert len(metas) == 1
    schema = json.loads(metas[0]["metaData"]["schemaString"])
    assert {f["name"] for f in schema["fields"]} == {"counter", "_timestamp"}
    assert {f["name"]: f["type"] for f in schema["fields"]}["counter"] == "long"
    # every visible parquet file is added exactly once; stats are exact
    files = {f for f in os.listdir(out_dir) if f.endswith(".parquet")}
    assert {a["path"] for a in adds} == files and len(adds) == len(files)
    assert sum(json.loads(a["stats"])["numRecords"] for a in adds) == 1000
    assert sum(pq.read_table(out_dir / f).num_rows for f in files) == 1000
    assert not [f for f in os.listdir(out_dir) if f.endswith(".tmp")]


def test_delta_sink_exactly_once_across_restart(tmp_path):
    """Stop-with-checkpoint mid-stream, restart from the checkpoint: the
    table nets exactly one add per file and no duplicated rows."""
    out_dir = tmp_path / "delta_ft"
    url = str(tmp_path / "ck")
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '20000',
      message_count = '4000', start_time = '0', realtime = 'true'
    );
    CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
      connector = 'delta', path = '{out_dir}',
      rollover_rows = '500', type = 'sink'
    );
    INSERT INTO out SELECT counter FROM impulse;
    """

    async def phase1():
        plan = plan_query(sql)
        eng = Engine(plan.graph, job_id="dft", storage_url=url).start()
        await asyncio.sleep(0.08)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(phase1())

    async def phase2():
        plan = plan_query(sql)
        eng = Engine(plan.graph, job_id="dft", storage_url=url).start()
        await eng.join(60)

    asyncio.run(phase2())
    import pyarrow.parquet as pq

    actions = []
    for v in sorted((out_dir / "_delta_log").glob("*.json")):
        with open(v) as f:
            actions.extend(json.loads(l) for l in f if l.strip())
    adds = [a["add"] for a in actions if "add" in a]
    files = {f for f in os.listdir(out_dir) if f.endswith(".parquet")}
    assert {a["path"] for a in adds} == files
    counters = []
    for f in files:
        counters.extend(pq.read_table(out_dir / f).column("counter").to_pylist())
    assert sorted(counters) == list(range(4000))


def test_nexmark_q7_q8():
    """Canonical Nexmark q7 (per-window highest bid) and q8 (person x
    auction same-window join) plan and produce deterministic results on
    the counter-based generator."""
    from bench import QUERIES

    for name, want in [("q7", 1), ("q8", 222)]:
        res = []
        plan = plan_query(
            QUERIES[name].format(rate=5000, events=20000),
            preview_results=res,
        )
        run_plan(plan, timeout=120)
        assert len(res) == want, (name, len(res))


def _iceberg_read_table(table_dir):
    """Walk the committed Iceberg metadata: version-hint -> metadata json
    -> manifest list (avro) -> manifests (avro) -> data files."""
    import pyarrow.parquet as pq

    from arroyo_tpu.formats.avro import read_ocf

    meta_dir = os.path.join(table_dir, "metadata")
    with open(os.path.join(meta_dir, "version-hint.text")) as f:
        v = int(f.read().strip())
    with open(os.path.join(meta_dir, f"v{v}.metadata.json")) as f:
        meta = json.load(f)
    snap = next(
        s for s in meta["snapshots"]
        if s["snapshot-id"] == meta["current-snapshot-id"]
    )
    with open(snap["manifest-list"], "rb") as f:
        _, manifests = read_ocf(f.read())
    data_files = []
    for m in manifests:
        with open(m["manifest_path"], "rb") as f:
            _, entries = read_ocf(f.read())
        data_files.extend(e["data_file"] for e in entries)
    rows = []
    for df in data_files:
        rows.extend(pq.read_table(df["file_path"]).column(
            "counter").to_pylist())
    return meta, manifests, data_files, rows


def test_iceberg_sink(tmp_path):
    """One run commits a spec-valid Iceberg v2 table: metadata json,
    avro manifest list + manifests, field-id'd parquet, exact row counts."""
    out_dir = str(tmp_path / "ice")
    plan = plan_query(
        f"""
        CREATE TABLE impulse WITH (
          connector = 'impulse', event_rate = '1000000',
          message_count = '1000', start_time = '0'
        );
        CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
          connector = 'iceberg', path = '{out_dir}',
          rollover_rows = '400', type = 'sink'
        );
        INSERT INTO out SELECT counter FROM impulse;
        """
    )
    run_plan(plan)
    meta, manifests, data_files, rows = _iceberg_read_table(out_dir)
    assert meta["format-version"] == 2
    schema = meta["schemas"][0]
    assert [f["name"] for f in schema["fields"]] == ["counter"]
    assert schema["fields"][0]["id"] == 1
    assert sorted(rows) == list(range(1000))
    assert all(df["file_format"] == "PARQUET" for df in data_files)
    assert sum(df["record_count"] for df in data_files) == 1000
    # parquet columns carry the iceberg field ids
    import pyarrow.parquet as pq

    sch = pq.read_schema(data_files[0]["file_path"])
    assert sch.field("counter").metadata[b"PARQUET:field_id"] == b"1"
    # the snapshot records the idempotency transaction id
    snap = meta["snapshots"][-1]
    assert snap["summary"]["arroyo-tpu.commit-id"].startswith("tx-")


def test_iceberg_exactly_once_across_restart(tmp_path):
    """Checkpoint mid-stream, stop, restore: the final table state reads
    every row exactly once and each epoch committed exactly one snapshot
    (the replayed commit is skipped by its transaction id)."""
    out_dir = str(tmp_path / "ice_ft")
    url = str(tmp_path / "ck")
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '20000',
      message_count = '4000', start_time = '0', realtime = 'true'
    );
    CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
      connector = 'iceberg', path = '{out_dir}',
      rollover_rows = '500', type = 'sink'
    );
    INSERT INTO out SELECT counter FROM impulse;
    """

    async def phase1():
        plan = plan_query(sql)
        eng = Engine(plan.graph, job_id="ift", storage_url=url).start()
        await asyncio.sleep(0.08)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(phase1())

    async def phase2():
        plan = plan_query(sql)
        eng = Engine(plan.graph, job_id="ift", storage_url=url).start()
        await eng.join(60)

    asyncio.run(phase2())
    meta, manifests, data_files, rows = _iceberg_read_table(out_dir)
    assert sorted(rows) == list(range(4000)), (
        f"{len(rows)} rows surfaced; duplicates or loss across restore"
    )
    # snapshot ids strictly chain parent -> child
    snaps = meta["snapshots"]
    for parent, child in zip(snaps, snaps[1:]):
        assert child["parent-snapshot-id"] == parent["snapshot-id"]
    # distinct transaction ids: no epoch double-committed
    tx_ids = [s["summary"]["arroyo-tpu.commit-id"] for s in snaps]
    assert len(tx_ids) == len(set(tx_ids))


def test_iceberg_rest_catalog(tmp_path):
    """The REST catalog client drives the sink against a stub
    implementing the catalog protocol (create namespace/table, load,
    commit with assert-ref-snapshot-id CAS)."""
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    state = {"table": None}  # metadata owned by the "catalog"
    lock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if "/tables/" in self.path:
                with lock:
                    if state["table"] is None:
                        self._json(404, {"error": "no such table"})
                    else:
                        self._json(200, {"metadata": state["table"]})
            else:
                self._json(404, {})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            if self.path.endswith("/namespaces"):
                self._json(200, {"namespace": body.get("namespace")})
                return
            if self.path.endswith("/tables"):
                with lock:
                    if state["table"] is not None:
                        self._json(409, {"error": "exists"})
                        return
                    meta = {
                        "format-version": 2,
                        "table-uuid": "11111111-2222-3333-4444-555555555555",
                        "location": body["location"],
                        "last-sequence-number": 0,
                        "schemas": [body["schema"]],
                        "partition-specs": [body["partition-spec"]],
                        "current-snapshot-id": None,
                        "snapshots": [],
                        "snapshot-log": [],
                        "refs": {},
                    }
                    state["table"] = meta
                    self._json(200, {"metadata": meta})
                return
            if "/tables/" in self.path:  # commit
                with lock:
                    meta = dict(state["table"])
                    for req in body["requirements"]:
                        if req["type"] == "assert-ref-snapshot-id":
                            cur = meta.get("current-snapshot-id")
                            if cur != req["snapshot-id"]:
                                self._json(409, {"error": "ref moved"})
                                return
                    for upd in body["updates"]:
                        if upd["action"] == "add-snapshot":
                            meta["snapshots"] = meta.get(
                                "snapshots", []) + [upd["snapshot"]]
                            meta["last-sequence-number"] = upd[
                                "snapshot"]["sequence-number"]
                        elif upd["action"] == "set-snapshot-ref":
                            meta["current-snapshot-id"] = upd["snapshot-id"]
                            meta.setdefault("refs", {})[upd["ref-name"]] = {
                                "snapshot-id": upd["snapshot-id"],
                                "type": upd["type"],
                            }
                    state["table"] = meta
                    self._json(200, {"metadata": meta})
                return
            self._json(404, {})

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        out_dir = str(tmp_path / "ice_rest")
        plan = plan_query(
            f"""
            CREATE TABLE impulse WITH (
              connector = 'impulse', event_rate = '1000000',
              message_count = '600', start_time = '0'
            );
            CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
              connector = 'iceberg', path = '{out_dir}',
              catalog = 'rest', rest_url = 'http://127.0.0.1:{port}',
              namespace = 'warehouse.db', table_name = 'events',
              rollover_rows = '250', type = 'sink'
            );
            INSERT INTO out SELECT counter FROM impulse;
            """
        )
        run_plan(plan)
    finally:
        srv.shutdown()
    meta = state["table"]
    assert meta is not None and meta["current-snapshot-id"] is not None
    snap = next(
        s for s in meta["snapshots"]
        if s["snapshot-id"] == meta["current-snapshot-id"]
    )
    # the committed snapshot's manifest list resolves to all 600 rows
    from arroyo_tpu.formats.avro import read_ocf
    import pyarrow.parquet as pq

    with open(snap["manifest-list"], "rb") as f:
        _, manifests = read_ocf(f.read())
    rows = []
    for m in manifests:
        with open(m["manifest_path"], "rb") as f:
            _, entries = read_ocf(f.read())
        for e in entries:
            rows.extend(pq.read_table(
                e["data_file"]["file_path"]).column("counter").to_pylist())
    assert sorted(rows) == list(range(600))


def test_avro_schema_registry_resolution(tmp_path):
    """Confluent-framed avro records resolve their writer schema from the
    registry by id (cached), and the sink side registers + frames
    (reference schema_resolver.rs ConfluentSchemaRegistry)."""
    import struct
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    import pyarrow as pa

    from arroyo_tpu.formats.avro import AvroEncoder
    from arroyo_tpu.formats.de import Deserializer
    from arroyo_tpu.formats.schema_registry import SchemaRegistryClient
    from arroyo_tpu.formats.ser import Serializer
    from arroyo_tpu.schema import StreamSchema, add_timestamp_field

    writer_schema = {
        "type": "record", "name": "ev", "fields": [
            {"name": "id", "type": "long"},
            {"name": "name", "type": "string"},
            {"name": "extra_field", "type": "string"},  # unknown to reader
        ],
    }
    registry_state = {"schemas": {7: writer_schema}, "gets": 0, "next": 41}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path.startswith("/schemas/ids/"):
                registry_state["gets"] += 1
                sid = int(self.path.rsplit("/", 1)[1])
                sch = registry_state["schemas"].get(sid)
                if sch is None:
                    self._json(404, {})
                else:
                    self._json(200, {"schema": json.dumps(sch)})
            else:
                self._json(404, {})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n))
            registry_state["next"] += 1
            sid = registry_state["next"]
            registry_state["schemas"][sid] = json.loads(body["schema"])
            self._json(200, {"id": sid})

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        client = SchemaRegistryClient(f"http://127.0.0.1:{port}",
                                      subject="t-value")
        # ---- decode: framed records resolve writer schema id 7
        reader = StreamSchema(add_timestamp_field(pa.schema(
            [pa.field("id", pa.int64()), pa.field("name", pa.string()),
             pa.field("missing", pa.string())]
        )))
        deser = Deserializer(reader, format="avro", schema_registry=client)
        enc = AvroEncoder(json.dumps(writer_schema), None)
        framed = b"\x00" + struct.pack(">I", 7) + enc.encode(
            {"id": 5, "name": "x", "extra_field": "dropme"}
        )
        rows = deser.deserialize_slice(framed, timestamp=0)
        assert rows[0]["id"] == 5 and rows[0]["name"] == "x"
        assert rows[0]["missing"] is None  # reader field absent in writer
        deser.deserialize_slice(framed, timestamp=0)
        assert registry_state["gets"] == 1, "writer schema must be cached"
        # ---- encode: sink registers its schema and frames records
        ser = Serializer(format="avro", schema_registry=client)
        batch = pa.record_batch(
            [pa.array([1, 2]), pa.array(["a", "b"])], names=["id", "name"]
        )
        recs = list(ser.serialize(batch))
        assert all(r[0] == 0 for r in recs)
        (sid,) = struct.unpack_from(">I", recs[0], 1)
        assert sid == 42 and sid in registry_state["schemas"]
        # framed output round-trips through the registry-aware decoder
        reader2 = StreamSchema(add_timestamp_field(pa.schema(
            [pa.field("id", pa.int64()), pa.field("name", pa.string())]
        )))
        deser2 = Deserializer(reader2, format="avro",
                              schema_registry=client)
        back = deser2.deserialize_slice(recs[1], timestamp=0)
        assert back[0]["id"] == 2 and back[0]["name"] == "b"
    finally:
        srv.shutdown()


def test_filesystem_sink_json_survives_restore_mid_file(tmp_path):
    """A json output file spanning epochs checkpoints its byte offset;
    restore truncates uncheckpointed bytes and resumes the same file —
    no duplicates, no loss (reference filesystem sink v2's checkpointed
    upload state)."""
    out_dir = str(tmp_path / "fsv2")
    url = str(tmp_path / "ck")
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '10000',
      message_count = '20000', start_time = '0', realtime = 'true'
    );
    CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
      connector = 'filesystem', path = '{out_dir}', format = 'json',
      rollover_rows = '1000000', type = 'sink'
    );
    INSERT INTO out SELECT counter FROM impulse;
    """

    async def phase1():
        plan = plan_query(sql)
        eng = Engine(plan.graph, job_id="fsv2", storage_url=url).start()
        await asyncio.sleep(0.05)
        await eng.checkpoint_and_wait()
        await asyncio.sleep(0.05)
        # crash-like stop: no stop-checkpoint; rows written after the
        # last checkpoint must be truncated away by the restore
        await eng.stop(__import__("arroyo_tpu.types", fromlist=["StopMode"]
                                  ).StopMode.IMMEDIATE)
        await eng.join(30)

    asyncio.run(phase1())
    # at least one in-progress .tmp exists with post-checkpoint bytes
    tmps = [f for f in os.listdir(out_dir) if f.endswith(".tmp")]
    assert tmps, "expected an in-progress file spanning the checkpoint"

    async def phase2():
        plan = plan_query(sql)
        eng = Engine(plan.graph, job_id="fsv2", storage_url=url).start()
        await eng.join(60)

    asyncio.run(phase2())
    rows = []
    for f in os.listdir(out_dir):
        if f.endswith(".json"):
            with open(os.path.join(out_dir, f)) as fh:
                rows.extend(json.loads(l)["counter"] for l in fh if l.strip())
    assert sorted(rows) == list(range(20000)), (
        f"{len(rows)} rows; mid-file restore duplicated or lost data"
    )


def test_filesystem_sink_partitioning(tmp_path):
    """partition_fields + time_partition_pattern compose the directory
    layout (reference v2 partitioning)."""
    out_dir = str(tmp_path / "parts")
    plan = plan_query(
        f"""
        CREATE TABLE cars (
          timestamp TIMESTAMP, driver_id BIGINT, event_type TEXT,
          location TEXT
        ) WITH (
          connector = 'single_file',
          path = 'tests/golden/inputs/cars.json',
          format = 'json', type = 'source',
          event_time_field = 'timestamp'
        );
        CREATE TABLE out (event_type TEXT, driver_id BIGINT) WITH (
          connector = 'filesystem', path = '{out_dir}', format = 'json',
          partition_fields = 'event_type',
          time_partition_pattern = '%Y-%m-%d', type = 'sink'
        );
        INSERT INTO out SELECT event_type, driver_id FROM cars;
        """
    )
    run_plan(plan)
    dirs = set()
    n = 0
    for root, _, names in os.walk(out_dir):
        for f in names:
            if f.endswith(".json"):
                dirs.add(os.path.relpath(root, out_dir))
                with open(os.path.join(root, f)) as fh:
                    n += sum(1 for l in fh if l.strip())
    assert dirs == {
        "2023-03-01/event_type=pickup", "2023-03-01/event_type=dropoff"
    }, dirs
    assert n == 400


def test_filesystem_sink_rollover_bytes(tmp_path):
    out_dir = str(tmp_path / "roll")
    plan = plan_query(
        f"""
        CREATE TABLE impulse WITH (
          connector = 'impulse', event_rate = '1000000',
          message_count = '2000', start_time = '0'
        );
        CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
          connector = 'filesystem', path = '{out_dir}', format = 'json',
          rollover_bytes = '2000', type = 'sink'
        );
        INSERT INTO out SELECT counter FROM impulse;
        """
    )
    run_plan(plan)
    files = [f for f in os.listdir(out_dir) if f.endswith(".json")]
    assert len(files) > 5, "byte-based rolling produced too few files"
    sizes = [os.path.getsize(os.path.join(out_dir, f)) for f in files]
    assert max(sizes) < 4000


def test_iceberg_recovery_commits_orphaned_files(tmp_path):
    """Crash between 2PC rename and snapshot commit: visible parquet data
    files unreferenced by any manifest get a recovery snapshot at the next
    start (mirrors DeltaSink's orphan reconciliation)."""
    import pyarrow.parquet as pq

    from arroyo_tpu.connectors.iceberg import IcebergSink

    table_dir = str(tmp_path / "ice_rec")
    data_dir = os.path.join(table_dir, "data")
    os.makedirs(data_dir)
    # a "visible" data file that no manifest references (renamed by the
    # restore's on_start before the commit replay found nothing to do)
    pa_table = __import__("pyarrow").table({"counter": list(range(50))})
    orphan = os.path.join(data_dir, "000-00000-deadbeef.parquet")
    pq.write_table(pa_table, orphan)

    sink = IcebergSink(table_dir)

    class _TaskInfo:
        job_id = "rec"
        node_id = 9
        task_index = 0
        parallelism = 1
        task_id = "9-0"

    class _Ctx:
        table_manager = None
        task_info = _TaskInfo()

    asyncio.run(sink.on_start(_Ctx()))
    meta, manifests, data_files, rows = _iceberg_read_table(table_dir)
    assert [df["file_path"] for df in data_files] == [orphan]
    assert sorted(rows) == list(range(50))
    # a second start is a no-op (file now referenced)
    asyncio.run(sink.on_start(_Ctx()))
    meta2, _, _, _ = _iceberg_read_table(table_dir)
    assert len(meta2["snapshots"]) == len(meta["snapshots"])


def test_nexmark_gen_batch_matches_scalar_generator():
    """The vectorized struct construction (persons/auctions/bids) must be
    row-identical to the scalar event() path for the same sequence
    numbers — the guard that keeps the two generation paths bit-equal."""
    import numpy as np

    from arroyo_tpu.connectors.nexmark import NexmarkGenerator, gen_batch

    g = NexmarkGenerator()
    ns = np.arange(0, 211, dtype=np.int64)  # covers several epochs
    ts = (1_000_000 + ns * 7919).astype(np.int64)
    batch = gen_batch(ns, ts)
    rows = batch.to_pylist()
    for i, n in enumerate(ns.tolist()):
        want = g.event(n, int(ts[i]))
        got = rows[i]
        for side in ("person", "auction", "bid"):
            w = want[side]
            gv = got[side]
            if w is None:
                assert gv is None, (side, n)
                continue
            for k, v in w.items():
                gvv = gv[k]
                if hasattr(gvv, "value"):  # pandas/pa timestamp -> ns
                    gvv = gvv.value
                assert gvv == v, (side, n, k, gvv, v)


def test_filesystem_source_reads_compressed(tmp_path):
    """The filesystem source reads gzip and zstd compressed json files
    transparently by extension, mixed with plain files (reference
    CompressionFormat none|gzip|zstd, filesystem/source.rs)."""
    import gzip

    zstandard = pytest.importorskip("zstandard")

    src = tmp_path / "in"
    src.mkdir()
    with open(src / "a.json", "w") as f:
        for i in range(0, 5):
            f.write(json.dumps({"n": i}) + "\n")
    with gzip.open(src / "b.json.gz", "wt") as f:
        for i in range(5, 10):
            f.write(json.dumps({"n": i}) + "\n")
    with zstandard.open(src / "c.json.zst", "wt") as f:
        for i in range(10, 15):
            f.write(json.dumps({"n": i}) + "\n")
    out = tmp_path / "out.json"
    sql = f"""
    CREATE TABLE src (n BIGINT) WITH (
      connector = 'filesystem', path = '{src}', format = 'json',
      type = 'source'
    );
    CREATE TABLE dst (n BIGINT) WITH (
      connector = 'single_file', path = '{out}', format = 'json',
      type = 'sink'
    );
    INSERT INTO dst SELECT n FROM src;
    """
    plan = plan_query(sql, parallelism=1)

    async def go():
        eng = Engine(plan.graph).start()
        await eng.join(30)

    asyncio.run(go())
    rows = sorted(json.loads(l)["n"] for l in open(out) if l.strip())
    assert rows == list(range(15))
