"""Deterministic fault injection ("chaos") for exactly-once drills.

The engine's riskiest protocols — barrier alignment under data-plane loss,
manifest CAS publication, generation fencing, 2PC sink commits — are
exercised by injecting faults at named points threaded through the
existing seams (SURVEY §2.8/§5.3; ISSUE 2). Usage:

    from arroyo_tpu import chaos
    chaos.install(chaos.FaultPlan.seeded(1234, ["network.drop_connection"]))
    ... run the job ...
    log = chaos.installed().comparable_log()
    chaos.clear()

Every fault point is a no-op unless a plan is installed: the production
hot path pays exactly one `is None` branch per pass (`fire()` below).
Plans can also be installed from config (`chaos.plan` — inline JSON or a
file path — and `chaos.seed`), which `WorkerServer.start` and
`ControllerServer.start` honor, so multi-process clusters pick plans up
through `ARROYO__CHAOS__*` env overrides.

`chaos/drill.py` runs golden queries through the real embedded cluster
under a plan and asserts the sink output is byte-identical to the
fault-free run; `tools/chaos_drill.py` is the CLI.
"""

from __future__ import annotations

from typing import Optional

from .plan import (  # noqa: F401 - public surface
    FAULT_POINTS,
    FaultPlan,
    FaultSpec,
    UnknownFaultPoint,
    check_point,
)

_PLAN: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> FaultPlan:
    """Install `plan` process-wide (replacing any current plan)."""
    global _PLAN
    _PLAN = plan
    return plan


def installed() -> Optional[FaultPlan]:
    return _PLAN


def clear() -> None:
    global _PLAN
    _PLAN = None


def fire(point: str, **ctx) -> Optional[FaultSpec]:
    """The injector seams' entry point: None (fast path, no plan) or the
    FaultSpec that fires on this hit. The seam decides what the fault
    means; `FAULT_POINTS` documents each point's effect."""
    if _PLAN is None:
        return None
    return _PLAN.fire(point, **ctx)


def install_from_config() -> Optional[FaultPlan]:
    """Install a plan from `chaos.plan` config (inline JSON or a JSON file
    path) if one is configured and none is installed yet. Idempotent;
    returns the installed plan (or the existing one).

    Incarnation dedupe (carried robustness bug): a RESPAWNED worker
    process (ARROYO_CHAOS_SPAWN_GEN > 0, stamped by the process
    scheduler) does NOT re-arm the plan — each respawn used to get fresh
    hit/fire counters, turning a heartbeat-hit worker.kill into a kill
    LOOP that ground the job down to a prefix of its output. A plan that
    genuinely wants per-incarnation re-arming opts in with
    `"rearm": true` in its JSON."""
    global _PLAN
    if _PLAN is not None:
        return _PLAN
    import json as _json
    import os as _os

    from ..config import config

    raw = (config().chaos.plan or "").strip()
    if not raw:
        return None
    if raw.lstrip().startswith("{"):
        text = raw
    else:
        with open(raw) as f:
            text = f.read()
    spawn_gen = int(_os.environ.get("ARROYO_CHAOS_SPAWN_GEN", "0") or 0)
    if spawn_gen > 0:
        try:
            rearm = bool(_json.loads(text).get("rearm"))
        except Exception:  # noqa: BLE001 - malformed plans fail below anyway
            rearm = False
        if not rearm:
            from ..utils.logging import get_logger

            get_logger("chaos").warning(
                "chaos plan NOT re-armed in respawned worker "
                "(spawn generation %d); set \"rearm\": true to override",
                spawn_gen,
            )
            return None
    plan = FaultPlan.from_json(text)
    if not plan.seed:
        plan.seed = int(config().chaos.seed or 0)
    return install(plan)
