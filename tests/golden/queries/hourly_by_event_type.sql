CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE group_by_aggregate (
  event_type TEXT,
  minute TIMESTAMP,
  count BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO group_by_aggregate
SELECT event_type, window.start as minute, count
FROM (
  SELECT event_type, TUMBLE(INTERVAL '1' MINUTE) as window, COUNT(distinct driver_id) as count
  FROM cars
  GROUP BY 1, 2
);
