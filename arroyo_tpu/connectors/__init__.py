"""Connector registry + the CONNECTOR_SOURCE / CONNECTOR_SINK factories.

Importing this package registers all built-in connectors (reference:
crates/arroyo-connectors/src/lib.rs:39-65 connectors()).
"""

from __future__ import annotations

from ..engine.construct import register_operator
from ..graph.logical import OperatorName
from .base import (  # noqa: F401
    ConnectionSchema,
    Connector,
    connectors,
    get_connector,
    register_connector,
)

# import order = registry order; each module self-registers
from . import impulse  # noqa: F401,E402
from . import debug  # noqa: F401,E402
from . import single_file  # noqa: F401,E402
from . import nexmark  # noqa: F401,E402
from . import filesystem  # noqa: F401,E402
from . import delta  # noqa: F401,E402
from . import iceberg  # noqa: F401,E402
from . import sse  # noqa: F401,E402
from . import websocket  # noqa: F401,E402
from . import polling_http  # noqa: F401,E402
from . import webhook  # noqa: F401,E402
from . import kafka  # noqa: F401,E402
from . import redis  # noqa: F401,E402
from . import mqtt  # noqa: F401,E402
from . import nats  # noqa: F401,E402
from . import rabbitmq  # noqa: F401,E402
from . import kinesis  # noqa: F401,E402
from . import fluvio  # noqa: F401,E402
from . import shared  # noqa: F401,E402


def _conn_schema(config: dict) -> ConnectionSchema:
    cs = config.get("connection_schema")
    if isinstance(cs, ConnectionSchema):
        return cs
    return ConnectionSchema(
        stream_schema=config.get("schema"),
        format=config.get("format"),
        bad_data=config.get("bad_data", "fail"),
        framing=config.get("framing"),
    )


@register_operator(OperatorName.CONNECTOR_SOURCE)
def _make_source(config: dict):
    conn = get_connector(config["connector"])
    op = conn.make_source(config, _conn_schema(config))
    if getattr(op, "out_schema", None) is None and config.get("schema"):
        op.out_schema = config["schema"]
    return op


@register_operator(OperatorName.CONNECTOR_SINK)
def _make_sink(config: dict):
    conn = get_connector(config["connector"])
    return conn.make_sink(config, _conn_schema(config))
