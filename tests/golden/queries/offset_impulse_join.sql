CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL,
  WATERMARK FOR timestamp
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source'
);
CREATE TABLE delayed_impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL,
  WATERMARK FOR timestamp AS (timestamp - INTERVAL '10 minute')
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source'
);
CREATE TABLE offset_output (
  start TIMESTAMP,
  counter BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO offset_output
SELECT a.window.start, a.counter as counter
FROM (
  SELECT tumble(interval '1 second') as window, counter, count(*)
  FROM impulse_source GROUP BY 1, 2
) a
JOIN (
  SELECT tumble(interval '1 second') as window, counter, count(*)
  FROM delayed_impulse_source GROUP BY 1, 2
) b
ON a.counter = b.counter;
