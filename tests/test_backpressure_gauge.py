"""Backpressure gauge freshness (ADVICE round 5): the sampled gauge must
not hold a stale value once a stream quiesces — expose/snapshot refresh
it through the registered scrape-time refresher, and the refresher
unregisters itself when its collector is garbage-collected."""

import gc

from arroyo_tpu import metrics
from arroyo_tpu.operators.collector import Collector


class _StubQueue:
    def __init__(self):
        self.value = 0.0

    def fullness(self):
        return self.value


class _StubEdge:
    def __init__(self, queues):
        self.queues = queues


def _gauge_value(job, task):
    snap = metrics.REGISTRY.snapshot()["arroyo_worker_backpressure"]
    for labels, v in snap:
        if labels == {"job": job, "task": task}:
            return v
    return None


def test_gauge_refreshes_at_scrape_without_collect():
    q = _StubQueue()
    c = Collector([_StubEdge([q])], task_id="t-bp", job_id="j-bp")
    # no collect() ever ran; occupancy changes while the stream is idle
    q.value = 0.75
    assert _gauge_value("j-bp", "t-bp") == 0.75
    q.value = 0.0
    assert _gauge_value("j-bp", "t-bp") == 0.0
    # expose() path refreshes too
    q.value = 0.5
    assert 'task="t-bp"} 0.5' in metrics.REGISTRY.expose()
    del c


def test_refresher_unregisters_when_collector_collected():
    q = _StubQueue()
    c = Collector([_StubEdge([q])], task_id="t-bp2", job_id="j-bp2")
    q.value = 0.25
    assert _gauge_value("j-bp2", "t-bp2") == 0.25
    del c
    gc.collect()
    q.value = 0.9
    # refresher dropped: the last refreshed value persists, the dead
    # collector's queues are no longer consulted (and not leaked)
    assert _gauge_value("j-bp2", "t-bp2") == 0.25
    assert not any(
        k == (("job", "j-bp2"), ("task", "t-bp2"))
        for k in metrics.BACKPRESSURE.refreshers
    )
