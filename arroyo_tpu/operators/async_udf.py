"""Placeholder: async UDF operator (reference async_udf.rs) lands with the
UDF milestone."""
