"""Mini fault-point registry: every entry has a live call site."""

FAULT_POINTS = {
    "network.drop": "drop the data-plane connection",
}
