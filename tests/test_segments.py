"""Fused segment runtime (ISSUE 14): plan-time fusion, host/vector/jax
execution tiers, the double-buffered staging pipeline, and the barrier
drain — every tier must be value-identical to the unfused per-operator
plan, and the pipeline must be byte-order-identical at any depth."""

import asyncio
import json

import pyarrow as pa
import pytest

from arroyo_tpu import obs
from arroyo_tpu.config import update
from arroyo_tpu.engine import Engine, segments
from arroyo_tpu.engine.segments import (
    FusedSegmentOperator,
    SegmentFusionPass,
    build_program,
    plan_runs,
)
from arroyo_tpu.graph.logical import OperatorName
from arroyo_tpu.metrics import REGISTRY
from arroyo_tpu.sql import plan_query

NEXMARK_DDL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '20000', message_count = '40000',
  start_time = '0'
);
"""

CHAIN_SQL = NEXMARK_DDL + """
CREATE TABLE sink (
  auction BIGINT, price_eur BIGINT, bidder BIGINT
) WITH (connector = 'blackhole', type = 'sink');
INSERT INTO sink
SELECT auction, price_eur, bidder FROM (
  SELECT auction, price_eur - price_eur % 10 AS price_eur, bidder FROM (
    SELECT bid.auction as auction, bid.price * 100 / 121 as price_eur,
           bid.bidder as bidder
    FROM nexmark WHERE bid IS NOT NULL
  )
);
"""

PREVIEW_SQL = NEXMARK_DDL + """
SELECT auction, price_eur - price_eur % 10 AS price_eur, bidder FROM (
  SELECT bid.auction as auction, bid.price * 100 / 121 as price_eur,
         bid.bidder as bidder
  FROM nexmark WHERE bid IS NOT NULL
);
"""


def canon(rows):
    return sorted(json.dumps(r, sort_keys=True, default=str) for r in rows)


def run_engine(sql, results=None, timeout=120):
    plan = plan_query(sql, preview_results=results)

    async def go():
        eng = Engine(plan.graph).start()
        await eng.join(timeout)

    asyncio.run(go())
    return plan


def seg_counts():
    snap = REGISTRY.snapshot()
    disp = sum(
        v for _l, v in snap.get("arroyo_segment_dispatches_total", [])
    )
    batches = sum(
        v for _l, v in snap.get("arroyo_segment_batches_total", [])
    )
    return disp, batches


# -- plan-time fusion --------------------------------------------------------


def test_plan_fuses_stateless_run_into_one_segment():
    with update(engine={"segment_fusion": True}):
        plan = plan_query(CHAIN_SQL)
    segs = [
        op
        for n in plan.graph.nodes.values()
        for op in n.chain
        if op.operator == OperatorName.FUSED_SEGMENT
    ]
    assert len(segs) == 1
    # select + normalize + select + sink_cast
    assert len(segs[0].config["ops"]) == 4
    # no stray members left behind
    assert not any(
        op.config.get("segment_member")
        for n in plan.graph.nodes.values()
        for op in n.chain
    )


def test_fusion_off_annotates_members_for_ab_accounting():
    with update(engine={"segment_fusion": False}):
        plan = plan_query(CHAIN_SQL)
    members = [
        op
        for n in plan.graph.nodes.values()
        for op in n.chain
        if op.config.get("segment_member")
    ]
    leads = [op for op in members if op.config.get("segment_lead")]
    assert len(members) == 4 and len(leads) == 1
    assert not any(
        op.operator == OperatorName.FUSED_SEGMENT
        for n in plan.graph.nodes.values()
        for op in n.chain
    )


def test_single_value_op_runs_are_not_fused():
    from arroyo_tpu.graph.logical import ChainedOp

    chain = [
        ChainedOp(OperatorName.CONNECTOR_SOURCE, {}),
        ChainedOp(OperatorName.ARROW_VALUE, {}),
        ChainedOp(OperatorName.TUMBLING_WINDOW_AGGREGATE, {}),
        ChainedOp(OperatorName.ARROW_VALUE, {}),
        ChainedOp(OperatorName.ARROW_VALUE, {}),
    ]
    assert plan_runs(chain) == [(3, 5)]


def test_segment_config_json_round_trips_nested_op_lists():
    """FUSED_SEGMENT configs nest member op dicts in a LIST — the config
    (un)serializer must recurse through lists (StreamSchema and bytes
    values inside member configs survive the round trip)."""
    from arroyo_tpu.graph.logical import _config_json, _config_unjson
    from arroyo_tpu.schema import StreamSchema

    schema = StreamSchema(
        pa.schema([pa.field("a", pa.int64()), pa.field("b", pa.float64())]),
        (0,),
    )
    cfg = {
        "ops": [
            {"operator": "arrow_value",
             "config": {"schema": schema, "blob": b"\x01\x02"},
             "description": "select"},
            {"operator": "arrow_key", "config": {}, "description": "key"},
        ],
        "schema": schema,
    }
    out = _config_unjson(json.loads(json.dumps(_config_json(cfg))))
    assert out["ops"][0]["config"]["blob"] == b"\x01\x02"
    rt = out["ops"][0]["config"]["schema"]
    assert rt.schema.equals(schema.schema)
    assert tuple(rt.key_indices) == (0,)
    assert out["ops"][1] == {"operator": "arrow_key", "config": {},
                             "description": "key"}


# -- execution tiers ---------------------------------------------------------


def test_fused_output_byte_identical_to_unfused():
    outs = {}
    for fusion in (True, False):
        REGISTRY.reset()
        with update(engine={"segment_fusion": fusion},
                    tpu={"enabled": False}):
            results = []
            run_engine(PREVIEW_SQL, results)
            outs[fusion] = results
    assert len(outs[True]) == len(outs[False]) > 0
    assert canon(outs[True]) == canon(outs[False])


def test_dispatches_per_batch_collapse_at_least_3x():
    dpb = {}
    for fusion in (True, False):
        REGISTRY.reset()
        with update(engine={"segment_fusion": fusion},
                    tpu={"enabled": False}):
            run_engine(CHAIN_SQL)
        disp, batches = seg_counts()
        assert batches > 0
        dpb[fusion] = disp / batches
    assert dpb[True] == pytest.approx(1.0)
    assert dpb[False] / dpb[True] >= 3.0


def test_jax_tier_matches_host_tier():
    """Whole-chain jit: one compiled program, identical output — incl.
    null handling through the bid struct fields (non-bid rows)."""
    outs = {}
    for jax_on in (False, True):
        REGISTRY.reset()
        with update(
            engine={"segment_fusion": True},
            tpu={"enabled": jax_on, "require_accelerator": False},
        ):
            results = []
            run_engine(PREVIEW_SQL, results)
            outs[jax_on] = results
            snap = REGISTRY.snapshot()
            tiers = {
                l.get("tier"): v.get("count", 0)
                for l, v in snap.get("arroyo_segment_dispatch_seconds", [])
            }
        if jax_on:
            assert tiers.get("jax", 0) > 0, tiers
        else:
            assert "jax" not in tiers
    assert canon(outs[True]) == canon(outs[False])


def test_jax_tier_recompiles_once_per_rung_change():
    with update(
        engine={"segment_fusion": True},
        tpu={"enabled": True, "require_accelerator": False},
    ):
        plan = plan_query(CHAIN_SQL)
        node = next(
            n for n in plan.graph.nodes.values()
            if any(op.operator == OperatorName.FUSED_SEGMENT
                   for op in n.chain)
        )
        seg_cfg = next(
            op for op in node.chain
            if op.operator == OperatorName.FUSED_SEGMENT
        )
        op = FusedSegmentOperator(seg_cfg.config["ops"], None, "t")
        prog = op._program()
        assert prog is not None and op._use_jax
        # two batch sizes inside one rung -> one signature; a bigger
        # batch climbs the rung -> exactly one more compile. Real input
        # batches are captured from one engine run.
        batches = []
        orig = FusedSegmentOperator.process_batch

        async def cap(self, batch, ctx, collector, input_index=0):
            batches.append(batch)
            return await orig(self, batch, ctx, collector, input_index)

        FusedSegmentOperator.process_batch = cap
        try:
            run_engine(CHAIN_SQL)
        finally:
            FusedSegmentOperator.process_batch = orig
        assert batches
        b = batches[0]
        seen0 = len(prog.jit.seen) if prog.jit else 0
        r1 = op._dispatch_jax(b.slice(0, min(100, b.num_rows)), prog)
        r2 = op._dispatch_jax(b.slice(0, min(120, b.num_rows)), prog)
        assert r1 is not None and r2 is not None
        after_small = len(prog.jit.seen)
        assert after_small == seen0 + 1  # both fit one rung: ONE signature
        # climb: a batch past the rung compiles exactly once more
        big = pa.concat_tables(
            [pa.Table.from_batches([b])] * 6
        ).combine_chunks().to_batches()[0]
        r3 = op._dispatch_jax(big, prog)
        assert r3 is not None
        assert len(prog.jit.seen) == after_small + 1


def test_vector_tier_filter_late_matches_view_tier():
    """The numpy vector tier (filter-late over unfiltered leaves) must
    equal the lazy-view tier batch for batch, including all-filtered
    and no-predicate-hit batches."""
    with update(engine={"segment_fusion": True}, tpu={"enabled": False}):
        plan = plan_query(CHAIN_SQL)
        node = next(
            n for n in plan.graph.nodes.values()
            if any(op.operator == OperatorName.FUSED_SEGMENT
                   for op in n.chain)
        )
        seg_cfg = next(
            op for op in node.chain
            if op.operator == OperatorName.FUSED_SEGMENT
        )
        op = FusedSegmentOperator(seg_cfg.config["ops"], None, "t")
        prog = op._program()
        assert prog is not None and prog.exact
        batches = []
        orig = FusedSegmentOperator.process_batch

        async def cap(self, batch, ctx, collector, input_index=0):
            batches.append(batch)
            return await orig(self, batch, ctx, collector, input_index)

        FusedSegmentOperator.process_batch = cap
        try:
            run_engine(CHAIN_SQL)
        finally:
            FusedSegmentOperator.process_batch = orig
        assert batches
        for b in batches[:5]:
            view = op._run_host(b)
            vec = op._run_vector(b, prog)
            assert vec is not b, "vector tier unexpectedly fell back"
            if view is None:
                assert vec is None
            else:
                assert view.equals(vec)


# -- pipelining / staging ----------------------------------------------------


def test_pipeline_depths_emit_identical_output():
    """Staging engages on the jax tier (dispatched-but-unmaterialized
    results); every depth must emit the SAME rows in the SAME order."""
    outs = {}
    for depth in (1, 2, 4):
        REGISTRY.reset()
        with update(engine={"segment_fusion": True,
                            "pipeline_depth": depth},
                    tpu={"enabled": True, "require_accelerator": False},
                    pipeline={"source_batch_size": 128}):
            results = []
            run_engine(PREVIEW_SQL, results)
            outs[depth] = [
                json.dumps(r, sort_keys=True, default=str) for r in results
            ]
    # ORDER-identical, not just set-identical: staging is strictly FIFO
    assert outs[1] == outs[2] == outs[4]


def test_windowed_aggregate_downstream_of_segment_is_exact():
    """Watermark hold/release: a tumbling aggregate fed by a fused
    segment must see every pre-watermark row before the watermark (or
    window counts would drop staged rows)."""
    sql = NEXMARK_DDL + """
    CREATE TABLE sink (a BIGINT, c BIGINT)
    WITH (connector = 'blackhole', type = 'sink');
    INSERT INTO sink
    SELECT auction, count(*) FROM (
      SELECT auction, price_eur FROM (
        SELECT bid.auction as auction,
               bid.price * 100 / 121 as price_eur
        FROM nexmark WHERE bid IS NOT NULL
      )
    )
    GROUP BY 1, tumble(interval '5 second');
    """
    outs = {}
    for fusion in (True, False):
        REGISTRY.reset()
        # fused run on the jitted tier (staging + watermark hold really
        # engage); unfused reference on the plain host kernels
        tpu = ({"enabled": True, "require_accelerator": False}
               if fusion else {"enabled": False})
        with update(engine={"segment_fusion": fusion,
                            "pipeline_depth": 2},
                    tpu=tpu,
                    pipeline={"source_batch_size": 128}):
            plan = plan_query(sql)
            segs = [
                op for n in plan.graph.nodes.values() for op in n.chain
                if op.operator == OperatorName.FUSED_SEGMENT
            ]
            if fusion:
                assert segs, "chain did not fuse"
            results = []
            run_engine(sql, results)
            outs[fusion] = results
    assert canon(outs[True]) == canon(outs[False])


def test_barrier_drain_records_pipeline_drain_span(tmp_storage):
    """Checkpoint barriers drain the staging queue before capture and
    record a runner.pipeline_drain span per barrier."""
    from arroyo_tpu.engine.engine import Engine as EmbeddedEngine

    obs.recorder().clear()
    REGISTRY.reset()
    with update(engine={"segment_fusion": True, "pipeline_depth": 2},
                tpu={"enabled": False},
                pipeline={"source_batch_size": 64}):
        sql = NEXMARK_DDL.replace("20000", "4000").replace(
            "40000", "20000") + """
        SELECT auction, price_eur, bidder FROM (
          SELECT auction, price_eur - price_eur % 10 AS price_eur,
                 bidder FROM (
            SELECT bid.auction as auction,
                   bid.price * 100 / 121 as price_eur,
                   bid.bidder as bidder
            FROM nexmark WHERE bid IS NOT NULL
          )
        );
        """
        results = []
        plan = plan_query(sql, preview_results=results)

        async def go():
            eng = EmbeddedEngine(plan.graph, job_id="seg-drain",
                                 storage_url=tmp_storage).start()
            done = asyncio.ensure_future(eng.join(120))
            ck = 0
            while not done.done() and ck < 3:
                await asyncio.sleep(0.3)
                if done.done():
                    break
                try:
                    await eng.checkpoint_and_wait()
                    ck += 1
                except Exception:  # noqa: BLE001 - racing stream end
                    break
            await done

        asyncio.run(go())
    drains = [
        s for s in obs.recorder().snapshot()
        if s.get("name") == "runner.pipeline_drain"
    ]
    assert drains, "no runner.pipeline_drain span recorded at barriers"
    assert all("staged" in s.get("attrs", {}) for s in drains)


# -- metrics / observability -------------------------------------------------


def test_segment_families_and_summary():
    REGISTRY.reset()
    with update(engine={"segment_fusion": True}, tpu={"enabled": False}):
        run_engine(CHAIN_SQL)
    from arroyo_tpu.obs import device as obs_device

    summ = obs_device.summary()
    assert summ["segments"], "device summary carries no segment ledger"
    (name, entry), = list(summ["segments"].items())[:1] or [(None, None)]
    assert name and name.startswith("segment.")
    assert entry.get("fused_ops") == 4
    assert entry.get("host_dispatches", 0) > 0


def test_exposition_includes_segment_families():
    REGISTRY.reset()
    with update(engine={"segment_fusion": True}, tpu={"enabled": False}):
        run_engine(CHAIN_SQL)
    text = REGISTRY.expose()
    assert "arroyo_segment_dispatch_seconds" in text
    assert "arroyo_segment_fused_ops" in text
    assert "arroyo_segment_dispatches_total" in text
