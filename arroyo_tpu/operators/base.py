"""Operator traits.

Capability parity with the reference's ArrowOperator / SourceOperator traits
(/root/reference/crates/arroyo-operator/src/operator.rs:1144-1257, :320-377):
lifecycle hooks, batch processing, watermark handling (return None to hold),
checkpoint state-snapshot hook, 2PC commit hook, periodic tick, and the
state-table declaration. Sources run their own loop and poll the control
queue between emissions (checkpoint barriers are injected at clean points).
"""

from __future__ import annotations

import enum
from typing import Any, Dict, Optional

import pyarrow as pa

from ..types import CheckpointBarrier, Watermark
from .context import OperatorContext, SourceContext


class SourceFinishType(enum.Enum):
    GRACEFUL = "graceful"  # stop requested: propagate Stop, no final watermark
    IMMEDIATE = "immediate"  # tear down without draining
    FINAL = "final"  # source exhausted: final watermark + EndOfData


class Operator:
    """Base class for dataflow operators. Subclasses override the hooks they
    need; `process_batch` is the hot path."""

    # StateServe: keyed operators get a ServeView attached at task start
    # (serve.register_op); None everywhere else keeps the emission-path
    # check a single attribute load
    _serve_view = None

    # conservation ledger (obs/audit.py): declared selectivity class,
    # checked per epoch by the reconciler against the runner's in/out row
    # counts. "exact" = out == in (pure row-wise transforms), "contracting"
    # = out <= in (filters), "buffering"/"any" = unchecked (windows,
    # joins, and anything that holds rows across barriers)
    flow_class = "any"

    def __init__(self, name: str = ""):
        self.name = name or type(self).__name__

    # -- lifecycle ----------------------------------------------------------

    async def on_start(self, ctx: OperatorContext):
        pass

    async def process_batch(
        self,
        batch: pa.RecordBatch,
        ctx: OperatorContext,
        collector: "ChainCollector",
        input_index: int = 0,
    ):
        raise NotImplementedError

    async def handle_watermark(
        self, watermark: Watermark, ctx: OperatorContext, collector
    ) -> Optional[Watermark]:
        """Called when the combined input watermark advances. Return the
        watermark to propagate (possibly modified) or None to hold it."""
        return watermark

    async def handle_checkpoint(
        self, barrier: CheckpointBarrier, ctx: OperatorContext, collector
    ):
        """Snapshot in-memory state into ctx state tables; called after
        barrier alignment, before the table flush."""

    async def handle_commit(
        self, epoch: int, commit_data: Dict[int, list], ctx: OperatorContext
    ):
        """Second phase of 2PC for transactional sinks."""

    async def handle_tick(self, tick: int, ctx: OperatorContext, collector):
        pass

    def tick_interval(self) -> Optional[float]:
        return None

    def future_to_poll(self):
        """Operator-owned async work (reference operator.rs future_to_poll):
        return an awaitable the runner selects on alongside the inputs, or
        None when idle. When it resolves, the runner calls
        handle_future_result and re-queries."""
        return None

    async def handle_future_result(self, ctx: OperatorContext, collector):
        """Called when the awaitable from future_to_poll resolved."""

    async def on_close(
        self, ctx: OperatorContext, collector, is_eod: bool
    ) -> Optional[Watermark]:
        """Called when all inputs finished. May emit final data via the
        collector; a returned watermark is run through the rest of the chain
        and broadcast (the watermark generator returns the end-of-time
        watermark here so windows flush)."""
        return None

    def tables(self) -> Dict[str, Any]:
        """State tables this operator needs: name -> TableConfig."""
        return {}

    def display(self) -> str:
        return self.name


class SourceOperator(Operator):
    """Sources drive their own loop. Implementations must call
    `await ctx.check_control(collector)` regularly (between batches) and
    return when it yields a finish type."""

    async def run(self, ctx: SourceContext, collector) -> SourceFinishType:
        raise NotImplementedError

    def drain_status(self):
        """For bounded sources: (drained, detail) after a FINAL finish —
        whether the source actually emitted its whole assigned range.
        None = unbounded/unknown. The runner attaches this to
        TaskFinishedResp; the controller refuses to FINISH a job whose
        source claims completion undrained (truncated-output guard)."""
        return None

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        raise RuntimeError("sources do not process input batches")

    async def flush_buffer(self, ctx: SourceContext, collector):
        # fleet observatory: take_buffer is the arrow pack moment (row
        # dicts -> RecordBatch) — the host decode/pack cost ROADMAP item
        # 1 wants overlapped with in-flight dispatch
        from .. import obs
        import time as _time

        t0 = _time.perf_counter()
        batch = ctx.take_buffer()
        if batch is not None:
            obs.timeline.note("decode", _time.perf_counter() - t0,
                              task=ctx.task_info.task_id)
            await collector.collect(batch)
        # latency markers stamp at flush cadence (throttled by
        # obs.latency_marker_interval): they leave through the subtask's
        # tail so they traverse real edges, not the in-chain fast path
        marker = ctx.next_latency_marker()
        if marker is not None and ctx._runner is not None:
            from ..types import SignalMessage

            await ctx._runner.tail.forward_marker(
                SignalMessage.marker_of(marker)
            )

    async def poll_async_iter(
        self, ait, ctx, collector, on_message, idle: float = 0.05
    ) -> Optional[SourceFinishType]:
        """Shared client-poll loop for push-style sources (MQTT, RabbitMQ,
        NATS): keeps ONE in-flight `__anext__` across idle ticks — an idle
        subject must not starve control handling (checkpoint barriers,
        stops), and cancelling `__anext__` per tick (as wait_for would)
        orphans many clients' internal queue getters, which then steal
        and drop messages. `on_message(msg)` is awaited per message;
        returns a finish type from control, or None at end-of-stream."""
        import asyncio

        pending = None
        while True:
            finish = await ctx.check_control(collector)
            if finish is not None:
                if pending is not None:
                    pending.cancel()
                return finish
            if pending is None:
                pending = asyncio.ensure_future(ait.__anext__())
            done, _ = await asyncio.wait({pending}, timeout=idle)
            if not done:
                await self.flush_buffer(ctx, collector)
                continue
            task, pending = pending, None
            try:
                msg = task.result()
            except StopAsyncIteration:
                return None
            await on_message(msg)
            if ctx.should_flush():
                await self.flush_buffer(ctx, collector)
