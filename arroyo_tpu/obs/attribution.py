"""Per-job cost attribution on multiplexed workers (ISSUE 11).

PR 10 multiplexed subtasks of 100+ jobs onto one event loop and one JAX
runtime, but every cost signal stayed per-process: nothing said which
tenant was burning the shared CPU/device time. This module threads a
job-id contextvar through the hot paths (runner batch loop, exchange
pumps, checkpoint flushes, `InstrumentedJit`) and accumulates per-job
deltas in plain dicts — the hot path pays one contextvar read and one
dict update, never a metric-registry lock — which a per-worker
accounting pump periodically rolls into the `arroyo_job_attributed_*`
metric families:

* busy seconds (mirrors the per-subtask `arroyo_worker_busy_seconds`
  sites, so attributed busy sums to the worker's measured busy time —
  the fleet harness asserts >= 95% coverage);
* process-CPU seconds (each pump flush apportions the interval's
  process-CPU delta across jobs proportional to attributed busy);
* device seconds + dispatch counts (the per-job dimension of the XLA
  telemetry — jitted programs are cached process-wide across jobs, so
  the per-program families cannot carry a job label themselves);
* bytes, and per-phase wall seconds (the timeline ledger's rollup).

Shared-plan apportioning (ISSUE 16): a shared source scan runs as a
hidden host job `__shared/<fp>`, so its runner notes busy/device time
under a job id no tenant owns. Each flush reassigns the host's pending
deltas across the scan's subscribers pro-rata by the rows each consumed
from the bus in the interval (`SharedChannel.consumed`), sum-preserving
— attributed cost per tenant survives the collapse of N scans into one,
and the fleet harness's >= 95% coverage gate holds over shared fleets
with no `__shared/*` escape bucket.

The pump also samples event-loop lag (sleep-overshoot of a fixed
timer) into `arroyo_worker_loop_lag_seconds` — the signal that
separates "my job is starved" from "a co-resident tenant is hogging
the loop" in the bottleneck doctor.

Everything is gated on `obs.attribution` (independent of `obs.enabled`:
attribution is plain metrics, no spans, so the fleet harness can run it
with the span recorder off).
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# the ambient job id ("" = unattributed); set once per runner/pump task
# at spawn, inherited by tasks it creates (asyncio copies the context)
_JOB: contextvars.ContextVar[str] = contextvars.ContextVar(
    "arroyo_job_attr", default=""
)


def enabled() -> bool:
    from ..config import config

    return bool(config().obs.attribution)


def current_job() -> str:
    return _JOB.get()


def set_job(job_id: str):
    """Bind the ambient job id for the current task's context; returns a
    token for reset. Runner/pump tasks call this once at task start so
    every await-descendant (flushes, storage threads) inherits it."""
    return _JOB.set(job_id)


def reset_job(token) -> None:
    _JOB.reset(token)


@contextlib.contextmanager
def job_scope(job_id: str):
    tok = _JOB.set(job_id)
    try:
        yield
    finally:
        _JOB.reset(tok)


class _Pending:
    """One job's unflushed deltas (plain floats; lock held by Accounting)."""

    __slots__ = ("busy", "device", "dispatches", "bytes", "phases",
                 "first_ts", "last_ts")

    def __init__(self):
        self.busy = 0.0
        self.device = 0.0
        self.dispatches = 0
        self.bytes = 0
        self.phases: Dict[str, float] = {}
        self.first_ts = time.monotonic()
        self.last_ts = self.first_ts


class Accounting:
    """Process-wide attribution accumulator + flush into the metric
    families. Thread-safe: device dispatches can fire from to_thread
    storage work, and the lock is uncontended on the single-loop path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: Dict[str, _Pending] = {}
        # cumulative per-job totals (survive flushes): the doctor and the
        # fleet harness read these without touching the registry
        self._totals: Dict[str, Dict[str, float]] = {}
        # per-job active window [first note, last note] for busy ratios
        self._windows: Dict[str, List[float]] = {}
        self._handles: Dict[str, dict] = {}
        # shared-plan apportioning: fp -> last-seen per-tenant consumed
        # row counts (the deltas weight each interval's split)
        self._shared_marks: Dict[str, Dict[str, int]] = {}
        self._cpu_mark: Optional[float] = None
        # bounded loop-lag sample window (seconds) for p99 without
        # histogram-bucket snapping
        self.lag_samples: deque = deque(maxlen=2048)

    # ---------------------------------------------------------- hot path

    def note(self, *, job: Optional[str] = None, busy: float = 0.0,
             device: float = 0.0, dispatches: int = 0,
             nbytes: int = 0, phase: Optional[str] = None,
             phase_secs: float = 0.0) -> None:
        """Accumulate one site's delta under `job` (default: the ambient
        job id). Unattributed work lands under "" and is surfaced as the
        coverage gap, never silently dropped."""
        if job is None:
            job = _JOB.get()
        with self._lock:
            p = self._pending.get(job)
            if p is None:
                p = self._pending[job] = _Pending()
            p.busy += busy
            p.device += device
            p.dispatches += dispatches
            p.bytes += nbytes
            if phase is not None:
                p.phases[phase] = p.phases.get(phase, 0.0) + phase_secs
            p.last_ts = time.monotonic()

    def note_lag(self, lag: float) -> None:
        from ..metrics import LOOP_LAG_SECONDS

        self.lag_samples.append(lag)
        LOOP_LAG_SECONDS.labels().observe(lag)

    # ------------------------------------------------------------- flush

    def _apportion_shared(self, pending: Dict[str, _Pending]) -> None:
        """Reassign `__shared/<fp>` host-job deltas across the scan's
        subscribers, weighted by the rows each consumed from the bus
        since the last flush (even split across attached readers when no
        rows moved — an idle scan's heartbeat cost is theirs too).
        Sum-preserving: float fields give the last tenant the exact
        remainder, integer fields apportion by floor with the remainder
        on the heaviest consumer. A host with no subscribers keeps its
        own bucket — still attributed, visible as unapportioned scan
        cost. Caller holds self._lock."""
        from ..engine.shared import BUS, HOST_PREFIX

        for host_id in [j for j in pending if j.startswith(HOST_PREFIX)]:
            channel = BUS.get(host_id[len(HOST_PREFIX):])
            if channel is None:
                continue
            consumed = dict(channel.consumed)
            marks = self._shared_marks.get(channel.fingerprint, {})
            self._shared_marks[channel.fingerprint] = consumed
            weights = {
                t: c - marks.get(t, 0)
                for t, c in consumed.items() if c - marks.get(t, 0) > 0
            }
            if not weights:
                weights = {t: 1 for t in channel.cursors}
            if not weights:
                continue
            p = pending.pop(host_id)
            total = sum(weights.values())
            tenants = sorted(weights)

            def split_f(value):
                out, acc = {}, 0.0
                for t in tenants[:-1]:
                    out[t] = value * weights[t] / total
                    acc += out[t]
                out[tenants[-1]] = value - acc
                return out

            def split_i(value):
                out = {t: value * weights[t] // total for t in tenants}
                heaviest = max(tenants, key=lambda t: weights[t])
                out[heaviest] += value - sum(out.values())
                return out

            busy = split_f(p.busy)
            device = split_f(p.device)
            disp = split_i(p.dispatches)
            nbytes = split_i(p.bytes)
            phases = {ph: split_f(s) for ph, s in p.phases.items()}
            for t in tenants:
                q = pending.get(t)
                if q is None:
                    q = pending[t] = _Pending()
                q.busy += busy[t]
                q.device += device[t]
                q.dispatches += disp[t]
                q.bytes += nbytes[t]
                for ph, share in phases.items():
                    q.phases[ph] = q.phases.get(ph, 0.0) + share[t]
                q.first_ts = min(q.first_ts, p.first_ts)
                q.last_ts = max(q.last_ts, p.last_ts)

    def _job_handles(self, job: str) -> dict:
        from ..metrics import (
            JOB_ATTR_BUSY_SECONDS,
            JOB_ATTR_BYTES,
            JOB_ATTR_CPU_SECONDS,
            JOB_ATTR_DEVICE_SECONDS,
            JOB_ATTR_DISPATCHES,
        )

        h = self._handles.get(job)
        if h is None:
            h = self._handles[job] = {
                "busy": JOB_ATTR_BUSY_SECONDS.labels(job=job),
                "cpu": JOB_ATTR_CPU_SECONDS.labels(job=job),
                "device": JOB_ATTR_DEVICE_SECONDS.labels(job=job),
                "dispatches": JOB_ATTR_DISPATCHES.labels(job=job),
                "bytes": JOB_ATTR_BYTES.labels(job=job),
                "phases": {},
            }
        return h

    def flush(self) -> None:
        """Roll pending deltas into the metric families and apportion the
        interval's process-CPU delta across jobs proportional to their
        attributed busy time in the interval. Idempotent; called by the
        pump each interval and by scrape-side readers (doctor, harness)."""
        from ..metrics import JOB_ATTR_PHASE_SECONDS

        with self._lock:
            pending, self._pending = self._pending, {}
            cpu_now = time.process_time()
            cpu_delta = (
                cpu_now - self._cpu_mark if self._cpu_mark is not None
                else 0.0
            )
            self._cpu_mark = cpu_now
            if any(j.startswith("__shared/") for j in pending):
                self._apportion_shared(pending)
        if not pending:
            return
        busy_total = sum(p.busy for p in pending.values())
        for job, p in pending.items():
            h = self._job_handles(job)
            tot = self._totals.setdefault(
                job, {"busy": 0.0, "cpu": 0.0, "device": 0.0,
                      "dispatches": 0, "bytes": 0},
            )
            win = self._windows.setdefault(job, [p.first_ts, p.last_ts])
            win[0] = min(win[0], p.first_ts)
            win[1] = max(win[1], p.last_ts)
            if p.busy:
                h["busy"].inc(p.busy)
                tot["busy"] += p.busy
                # CPU apportioning: the process-CPU delta is split by
                # attributed busy share — exact per-job CPU accounting
                # would need per-batch clock_gettime(THREAD_CPUTIME)
                # pairs, and busy-share tracks it closely on a worker
                # whose loop does the work
                if cpu_delta > 0 and busy_total > 0:
                    share = cpu_delta * (p.busy / busy_total)
                    h["cpu"].inc(share)
                    tot["cpu"] += share
            if p.device:
                h["device"].inc(p.device)
                tot["device"] += p.device
            if p.dispatches:
                h["dispatches"].inc(p.dispatches)
                tot["dispatches"] += p.dispatches
            if p.bytes:
                h["bytes"].inc(p.bytes)
                tot["bytes"] += p.bytes
            for phase, secs in p.phases.items():
                ph = h["phases"].get(phase)
                if ph is None:
                    ph = h["phases"][phase] = JOB_ATTR_PHASE_SECONDS.labels(
                        job=job, phase=phase
                    )
                ph.inc(secs)

    # ----------------------------------------------------------- surface

    def summary(self) -> dict:
        """Structured per-job rollup for /debug/attribution, the doctor,
        and the fleet harness: cumulative attributed totals, active
        windows, coverage vs the unattributed bucket, and loop-lag
        percentiles."""
        self.flush()
        jobs = {}
        with self._lock:
            for job, tot in self._totals.items():
                win = self._windows.get(job)
                jobs[job or "(unattributed)"] = {
                    **{k: round(v, 4) if isinstance(v, float) else v
                       for k, v in tot.items()},
                    "window_s": round(win[1] - win[0], 3) if win else 0.0,
                }
            lags = sorted(self.lag_samples)
        attributed = sum(
            v["busy"] for k, v in jobs.items() if k != "(unattributed)"
        )
        unattributed = jobs.get("(unattributed)", {}).get("busy", 0.0)
        total = attributed + unattributed
        out = {
            "jobs": jobs,
            "attributed_busy_s": round(attributed, 4),
            "unattributed_busy_s": round(unattributed, 4),
            "coverage": round(attributed / total, 4) if total else 1.0,
        }
        if lags:
            out["loop_lag_ms"] = {
                "p50": round(1e3 * lags[len(lags) // 2], 3),
                "p99": round(1e3 * lags[min(len(lags) - 1,
                                            int(0.99 * len(lags)))], 3),
                "max": round(1e3 * lags[-1], 3),
                "samples": len(lags),
            }
        return out

    def job_busy(self, job: str) -> float:
        self.flush()
        with self._lock:
            return self._totals.get(job, {}).get("busy", 0.0)

    def drop_job(self, job_id: str) -> None:
        """Cardinality GC hook (Registry.drop_job path): a torn-down
        job's pending deltas, cached handles, totals and window state
        must not outlive its metric series."""
        with self._lock:
            self._pending.pop(job_id, None)
            self._handles.pop(job_id, None)
            self._totals.pop(job_id, None)
            self._windows.pop(job_id, None)
            if job_id.startswith("__shared/"):
                self._shared_marks.pop(job_id[len("__shared/"):], None)

    def reset(self) -> None:
        with self._lock:
            self._pending.clear()
            self._handles.clear()
            self._totals.clear()
            self._windows.clear()
            self._shared_marks.clear()
            self._cpu_mark = None
            self.lag_samples.clear()


ACCOUNTING = Accounting()


def note(**kw) -> None:
    """Module-level hot-path shim: no-op unless obs.attribution is on."""
    if enabled():
        ACCOUNTING.note(**kw)


# -- the per-worker accounting pump ------------------------------------------

_PUMP_TASK: Optional[asyncio.Task] = None
_PUMP_REFS = 0


async def _pump_loop():
    """Flush cadence + event-loop lag sampler + history scrape. One pump
    per process even when several embedded WorkerServers share the loop
    (refcounted): a second sampler would double-count lag observations.

    The watchtower's per-worker scrape rides this cadence machinery
    (ISSUE 13): each interval the pump offers the live registry to the
    process's metric-history tier; `MetricHistory.sample_registry`'s own
    `watch.sample_interval` guard turns the offer into the configured
    sampling rate (and dedupes against a co-resident controller
    watchtower pumping the same history)."""
    from ..config import config
    from . import history, timeline

    while True:
        cfg = config().obs
        interval = max(0.05, float(cfg.loop_lag_interval or
                                   cfg.attribution_flush_interval or 0.5))
        t0 = time.monotonic()
        await asyncio.sleep(interval)
        if enabled():
            lag = max(0.0, time.monotonic() - t0 - interval)
            if cfg.loop_lag_interval:
                ACCOUNTING.note_lag(lag)
                if lag > 0.001:
                    # visible stalls land in the timeline ledger so
                    # Perfetto dumps and the offline doctor see loop
                    # pressure
                    timeline.note("loop.lag", lag, job="")
            ACCOUNTING.flush()
        history.HISTORY.sample_registry()


def _history_enabled() -> bool:
    from ..config import config

    return bool(config().watch.enabled)


def ensure_pump() -> None:
    """Start (or ref) the process's accounting pump on the running loop.
    Runs when attribution OR the watchtower history tier wants the
    cadence (each part gates itself per iteration)."""
    global _PUMP_TASK, _PUMP_REFS
    if not (enabled() or _history_enabled()):
        return
    _PUMP_REFS += 1
    if _PUMP_TASK is None or _PUMP_TASK.done():
        _PUMP_TASK = asyncio.ensure_future(_pump_loop())


def release_pump() -> None:
    """Drop one pump reference; the last release cancels the task and
    takes a final flush so teardown never strands pending deltas."""
    global _PUMP_TASK, _PUMP_REFS
    if _PUMP_REFS > 0:
        _PUMP_REFS -= 1
    if _PUMP_REFS == 0 and _PUMP_TASK is not None:
        _PUMP_TASK.cancel()
        _PUMP_TASK = None
        ACCOUNTING.flush()
