"""TableManager: per-(subtask, chain-op) state table ownership.

Capability parity with the reference's TableManager
(/root/reference/crates/arroyo-state/src/tables/table_manager.rs:37): owns
the operator's tables, restores them from the backend's restore manifest on
open, flushes dirty state on checkpoint barriers, and swaps file references
after compaction. Restore semantics per table kind:
  * global: union of ALL subtasks' blobs (replication — rescale-aware
    operators re-filter by key range themselves)
  * time_key: read every subtask's live files, filter rows to this
    subtask's key range and retention (rescale = overlap re-read,
    reference parquet.rs + expiring_time_key_map.rs)
"""

from __future__ import annotations

from typing import Dict, Optional

from .. import obs
from ..types import TaskInfo
from ..utils.logging import get_logger
from .backend import StateBackend
from .table_config import TableConfig
from .tables import GlobalTable, TimeKeyTable

logger = get_logger("table_manager")


class TableManager:
    def __init__(self, backend: StateBackend, task_info: TaskInfo, op_idx: int):
        self.backend = backend
        self.task_info = task_info
        self.op_idx = op_idx
        self.tables: Dict[str, object] = {}
        self.configs: Dict[str, TableConfig] = {}

    async def open(self, configs: Dict[str, TableConfig]):
        self.configs = dict(configs)
        for name, cfg in self.configs.items():
            if cfg.kind == "global":
                table = GlobalTable(cfg)
            else:
                table = TimeKeyTable(cfg)
            self.tables[name] = table
        if self.backend.restore_manifest:
            self._restore()

    def _restore(self):
        node_id = self.task_info.node_id
        per_subtask = self.backend.tables_for(node_id, self.op_idx)
        restore_wm = self.backend.restore_watermark(self.task_info.task_id)
        for name, table in self.tables.items():
            cfg = self.configs[name]
            # flight recorder: one span per restored table, staged events
            # per file — a restore failure (e.g. the process-scheduler
            # IndexError in ROADMAP open items) names its table, file and
            # stage in the trace dump instead of just a stack
            with obs.span(
                "state.restore_table", cat="storage", table=name,
                kind=cfg.kind, task=self.task_info.task_id,
                op_idx=self.op_idx,
            ) as sp:
                if cfg.kind == "global":
                    blobs = []
                    for entry in per_subtask:
                        meta = entry["tables"].get(name)
                        if meta and meta.get("path"):
                            blob = self.backend.read_blob(meta["path"])
                            if blob is not None:
                                blobs.append(blob)
                    table.load(blobs)
                    sp.set(blobs=len(blobs))
                else:
                    seen = set()
                    batches = []
                    for entry in per_subtask:
                        meta = entry["tables"].get(name)
                        for f in (meta or {}).get("files", []):
                            if f["path"] in seen:
                                continue
                            seen.add(f["path"])
                            sp.event("read_file", path=f["path"])
                            t = self.backend.read_parquet(f["path"])
                            if t is not None:
                                batches.extend(t.to_batches())
                            table.files.append(dict(f))
                    sp.set(files=len(seen), batches=len(batches))
                    sp.event("load_batches")
                    table.load_batches(
                        batches,
                        key_indices=None,
                        parallelism=self.task_info.parallelism,
                        task_index=self.task_info.task_index,
                    )
                    sp.event("filter_expired", watermark=restore_wm)
                    table.filter_expired(restore_wm)

    async def get_table(self, name: str):
        return self.tables[name]

    async def checkpoint(self, epoch: int, watermark: Optional[int]) -> Dict:
        """Flush dirty state; returns per-table metadata for the manifest.
        One-shot form of capture() + flush_captured()."""
        return self.flush_captured(epoch, self.capture(epoch, watermark))

    def capture(self, epoch: int, watermark: Optional[int]) -> Dict:
        """Synchronously stage this epoch's state at the barrier: global
        blobs are serialized now (cheap — incremental operators keep only
        meta here), time-key deltas are detached from the tables (possibly
        as unresolved thunks whose device->host copy completes later).
        After capture the operator may resume processing; flush_captured
        does the I/O."""
        staged: Dict[str, dict] = {}
        for name, table in self.tables.items():
            cfg = self.configs[name]
            if cfg.kind == "global":
                staged[name] = {"kind": "global", "blob": table.serialize()}
            else:
                dirty = table.take_dirty_staged()
                files = table.live_files(watermark)
                table.expire(watermark)
                staged[name] = {
                    "kind": "time_key",
                    "dirty": dirty,
                    "files": files,
                    "table": table,
                }
        return staged

    def flush_captured(self, epoch: int, staged: Dict) -> Dict:
        """Write captured state to storage; safe to run while the operator
        processes the next epoch (captured data is immutable). Returns the
        manifest metadata."""
        meta: Dict[str, dict] = {}
        ti = self.task_info
        for name, st in staged.items():
            cfg = self.configs[name]
            if st["kind"] == "global":
                blob = st["blob"]
                path = self.backend.write_global_blob(
                    epoch, ti.node_id, self.op_idx, name, ti.task_index, blob
                )
                meta[name] = {
                    "kind": "global", "path": path, "bytes": len(blob)
                }
            else:
                dirty = TimeKeyTable.resolve_staged(st["dirty"])
                files = st["files"]
                if dirty is not None and dirty.num_rows:
                    f = self.backend.write_time_key_file(
                        epoch, ti.node_id, self.op_idx, name, ti.task_index,
                        dirty, timestamp_field=cfg.timestamp_field,
                    )
                    files = files + [f]
                st["table"].files = files
                meta[name] = {"kind": "time_key", "files": files}
        return meta

    async def load_compacted(self, table: str, paths):
        """Swap pre-compaction file references for the compacted file
        (reference ControlMessage::LoadCompacted). In-memory rows already
        hold the data; only restore bookkeeping changes."""
        t = self.tables.get(table)
        if t is None or not hasattr(t, "files"):
            return
        if isinstance(paths, list) and paths and isinstance(paths[0], dict):
            t.files = [dict(f) for f in paths]
