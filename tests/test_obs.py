"""Flight recorder (ISSUE 4): histogram metric kind, span API + ring
buffer, cross-process trace propagation through a real embedded-cluster
checkpoint, and the /metrics + trace export surfaces."""

import asyncio
import json

import pytest

from arroyo_tpu import obs
from arroyo_tpu.metrics import (
    BATCHES_RECV,
    DEFAULT_BUCKETS,
    RateWindow,
    Registry,
    REGISTRY,
)


@pytest.fixture(autouse=True)
def _fresh_recorder():
    obs.reset()
    yield
    obs.reset()


# -- histogram metric kind ---------------------------------------------------


def test_histogram_buckets_and_exposition():
    reg = Registry()
    h = reg.histogram("lat_seconds", "test latency", buckets=(0.01, 0.1, 1.0))
    hd = h.labels(op="x")
    for v in (0.005, 0.05, 0.5, 5.0):
        hd.observe(v)
    text = reg.expose()
    assert 'lat_seconds_bucket{op="x",le="0.01"} 1' in text
    assert 'lat_seconds_bucket{op="x",le="0.1"} 2' in text
    assert 'lat_seconds_bucket{op="x",le="1.0"} 3' in text
    assert 'lat_seconds_bucket{op="x",le="+Inf"} 4' in text
    assert 'lat_seconds_count{op="x"} 4' in text
    assert 'lat_seconds_sum{op="x"} 5.555' in text
    assert "# TYPE lat_seconds histogram" in text


def test_histogram_snapshot_and_handle_view():
    reg = Registry()
    h = reg.histogram("s", "", buckets=(1.0,))
    h.labels(a="1").observe(0.5)
    h.labels(a="1").observe(2.0)
    snap = reg.snapshot()["s"]
    assert snap == [({"a": "1"}, {"sum": 2.5, "count": 2,
                                  "buckets": {"1.0": 1, "+Inf": 2}})]
    assert h.labels(a="1").get_hist()["count"] == 2
    assert h.labels(a="other").get_hist() is None


def test_histogram_boundary_lands_in_its_bucket():
    # Prometheus buckets are <= le: an observation exactly on a boundary
    # counts in that bucket
    reg = Registry()
    h = reg.histogram("b", "", buckets=(0.1, 1.0))
    h.labels().observe(0.1)
    assert h.labels().get_hist()["buckets"]["0.1"] == 1


def test_default_buckets_are_sorted_and_latency_shaped():
    assert list(DEFAULT_BUCKETS) == sorted(DEFAULT_BUCKETS)
    assert DEFAULT_BUCKETS[0] <= 0.001 and DEFAULT_BUCKETS[-1] >= 10


# -- Registry.reset regression (satellite) -----------------------------------


def test_reset_keeps_module_level_handles_visible():
    """Registry.reset() used to drop the _Metric objects from the
    registry while module-level families kept handles to them: increments
    after reset() silently vanished from expose()/snapshot(). reset()
    now clears values in place."""
    handle = BATCHES_RECV.labels(job="rj", task="0-0")
    handle.inc()
    REGISTRY.reset()
    assert handle.get() == 0  # cleared in place
    handle.inc(3)
    assert 'arroyo_worker_batches_recv{job="rj",task="0-0"} 3' in (
        REGISTRY.expose()
    )
    snap = REGISTRY.snapshot()["arroyo_worker_batches_recv"]
    assert ({"job": "rj", "task": "0-0"}, 3.0) in snap
    REGISTRY.reset()


def test_reset_clears_histograms_and_refreshers():
    reg = Registry()
    h = reg.histogram("hh", "")
    h.labels(x="1").observe(1.0)
    g = reg.gauge("gg", "")
    g.labels(x="1").set_refresher(lambda: 42.0)
    reg.reset()
    assert h.labels(x="1").get_hist() is None
    assert "gg 42" not in reg.expose()


# -- RateWindow (satellite) --------------------------------------------------


def test_rate_window_deque_trims_time_and_caps_samples():
    w = RateWindow()
    from collections import deque

    assert isinstance(w.samples, deque)
    w.add(0.0, now=0.0)
    w.add(100.0, now=100.0)
    w.add(400.0, now=400.0)  # pushes the t=0 sample out of the window
    assert w.samples[0][0] == 100.0
    assert w.rate() == pytest.approx(1.0)
    # hard cap regardless of window
    w2 = RateWindow()
    for i in range(RateWindow.MAX_SAMPLES + 50):
        w2.add(float(i), now=100.0 + i * 0.001)
    assert len(w2.samples) == RateWindow.MAX_SAMPLES


# -- span API + ring buffer --------------------------------------------------


def test_span_nesting_parents_and_events():
    with obs.span("root", trace="t/1", cat="a", k=1) as root:
        assert obs.current() == ("t/1", root.span_id)
        with obs.span("child", cat="b") as child:
            assert child.trace_id == "t/1"
            assert child.parent_id == root.span_id
            child.event("marker", n=2)
    spans = obs.recorder().snapshot(trace_id="t/1")
    assert [s["name"] for s in spans] == ["child", "root"]  # finish order
    assert spans[0]["events"][0]["name"] == "marker"
    assert spans[1]["parent_id"] is None


def test_span_without_context_is_null():
    sp = obs.span("floating")
    assert sp is obs.NULL_SPAN
    with sp:
        sp.event("x")
        sp.set(a=1)
    assert len(obs.recorder()) == 0


def test_span_disabled_by_config():
    from arroyo_tpu.config import update

    with update(obs={"enabled": False}):
        assert obs.span("x", trace="t/1") is obs.NULL_SPAN
        obs.event("e")
    assert len(obs.recorder()) == 0


def test_ring_buffer_overflow_drops_oldest():
    rec = obs.reset(capacity=10)
    for i in range(25):
        with obs.span(f"s{i}", trace="t/ring"):
            pass
    assert len(rec) == 10
    assert rec.dropped == 15
    names = [s["name"] for s in rec.snapshot()]
    assert names == [f"s{i}" for i in range(15, 25)]  # oldest dropped


def test_error_in_span_recorded():
    with pytest.raises(ValueError):
        with obs.span("boom", trace="t/err"):
            raise ValueError("nope")
    (sp,) = obs.recorder().snapshot(trace_id="t/err")
    assert "ValueError" in sp["attrs"]["error"]


def test_chrome_trace_export_shape():
    with obs.span("root", trace="t/x", cat="c") as sp:
        sp.event("inst")
    obs.event("lone", cat="chaos")
    doc = obs.chrome_trace(obs.recorder().snapshot())
    phases = {e["ph"] for e in doc["traceEvents"]}
    assert {"X", "i", "M"} <= phases
    x = next(e for e in doc["traceEvents"] if e["ph"] == "X")
    assert x["args"]["trace_id"] == "t/x"
    assert doc["displayTimeUnit"] == "ms"


def test_attach_detach_for_async_hops():
    sp = obs.start_span("hop", trace="t/hop")
    tok = sp.attach()
    try:
        child = obs.span("inner")
        assert child.parent_id == sp.span_id
        child.finish()
    finally:
        sp.detach(tok)
        sp.finish()
    assert obs.current() is None
    assert len(obs.recorder()) == 2


# -- cross-process propagation through a real embedded cluster ---------------


CLUSTER_SQL = """
CREATE TABLE impulse WITH (
  connector = 'impulse', event_rate = '150000',
  message_count = '100000', start_time = '0', realtime = 'true'
);
CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
  connector = 'single_file', path = '{out}',
  format = 'json', type = 'sink'
);
INSERT INTO out
SELECT k, cnt FROM (
  SELECT counter % 8 as k, tumble(interval '1 millisecond') as w,
         count(*) as cnt
  FROM impulse GROUP BY 1, 2
);
"""


def _connected_tree(spans):
    """(single_root, orphans): parent links resolve within the trace."""
    by_id = {s["span_id"]: s for s in spans}
    roots = [s for s in spans if s["parent_id"] is None]
    orphans = [
        s for s in spans
        if s["parent_id"] is not None and s["parent_id"] not in by_id
    ]
    return len(roots) == 1, orphans


def test_checkpoint_trace_tree_spans_cluster(tmp_path):
    """The golden acceptance: a windowed-agg run on the embedded cluster
    (controller + 2 workers over real gRPC + TCP) produces, per
    checkpoint epoch, ONE connected span tree covering controller →
    worker → operator barrier → storage commit — and /metrics exposes
    the new histogram families and watermark-lag gauges."""
    from arroyo_tpu.config import update
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState

    async def go():
        c = await ControllerServer(EmbeddedScheduler()).start()
        with update(pipeline={"checkpointing": {"interval": 0.1}}):
            await c.submit_job(
                "obs1", sql=CLUSTER_SQL.format(out=tmp_path / "out.json"),
                storage_url=str(tmp_path / "ck"), n_workers=2, parallelism=2,
            )
            state = await c.wait_for_state(
                "obs1", JobState.FINISHED, JobState.FAILED, timeout=60
            )
        await c.stop()
        return state

    state = asyncio.run(go())
    assert state == JobState.FINISHED

    spans = obs.recorder().snapshot(trace_prefix="obs1/")
    ck_traces = sorted({
        s["trace_id"] for s in spans if "/ck-" in s["trace_id"]
    })
    assert ck_traces, "no checkpoint trace recorded"
    checked = 0
    for tid in ck_traces:
        tr = [s for s in spans if s["trace_id"] == tid]
        cats = {s["cat"] for s in tr}
        names = {s["name"] for s in tr}
        if "storage" not in cats:
            continue  # a barely-started epoch racing job finish
        single_root, orphans = _connected_tree(tr)
        assert single_root, f"{tid}: multiple roots"
        assert not orphans, f"{tid}: orphans {[s['name'] for s in orphans]}"
        # the acceptance chain: controller → worker → runner → storage
        assert {"controller", "rpc", "worker", "runner", "storage"} <= cats
        assert "checkpoint" in names            # controller root
        assert "worker.checkpoint" in names     # worker fan-out hop
        assert "checkpoint.capture" in names    # operator barrier hop
        assert any(n.startswith("storage.") for n in names)  # state commit
        checked += 1
    assert checked >= 1

    # metric surface: >= 3 histogram families with _bucket/_sum/_count
    # plus the watermark-lag gauge, all live from this run
    text = REGISTRY.expose()
    for fam in ("arroyo_worker_batch_processing_seconds",
                "arroyo_exchange_frame_seconds",
                "arroyo_storage_op_seconds",
                "arroyo_checkpoint_phase_seconds"):
        assert f"{fam}_bucket" in text, fam
        assert f"{fam}_sum" in text, fam
        assert f"{fam}_count" in text, fam
    assert 'arroyo_worker_watermark_lag_seconds{job="obs1"' in text
    assert 'arroyo_worker_barrier_alignment_seconds{job="obs1"' in text
    assert 'phase="capture"' in text and 'phase="flush"' in text


def test_rpc_trace_header_round_trip():
    """The gRPC-analog layer forwards the __trace__ header into a server
    span that parents to the client's call span."""
    from arroyo_tpu.engine.rpc import RpcClient, RpcServer

    seen = {}

    async def go():
        server = RpcServer("127.0.0.1")

        async def method(req):
            seen["ctx"] = obs.current()
            return {"ok": 1}

        server.add_service("TestSvc", {"Do": method})
        port = await server.start()
        client = RpcClient(f"127.0.0.1:{port}")
        with obs.span("origin", trace="t/rpc") as sp:
            await client.call("TestSvc", "Do", {"x": 1})
            origin_id = sp.span_id
        await client.close()
        await server.stop()
        return origin_id

    origin_id = asyncio.run(go())
    assert seen["ctx"][0] == "t/rpc"
    spans = obs.recorder().snapshot(trace_id="t/rpc")
    names = {s["name"]: s for s in spans}
    assert "call.TestSvc.Do" in names
    assert "rpc.TestSvc.Do" in names
    assert names["call.TestSvc.Do"]["parent_id"] == origin_id
    assert names["rpc.TestSvc.Do"]["parent_id"] == (
        names["call.TestSvc.Do"]["span_id"]
    )


def test_trace_report_merge_and_stats(tmp_path):
    import sys

    sys.path.insert(0, "/root/repo/tools")
    try:
        import trace_report
    finally:
        sys.path.remove("/root/repo/tools")

    with obs.span("root", trace="t/m", cat="a"):
        with obs.span("kid", cat="b"):
            pass
    doc = obs.chrome_trace(obs.recorder().snapshot())
    p1 = tmp_path / "d1.json"
    p1.write_text(json.dumps(doc))
    p2 = tmp_path / "d2.json"
    p2.write_text(json.dumps(doc))  # duplicate dump: spans dedupe
    merged = trace_report.merge([str(p1), str(p2)])
    xs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 2  # deduped
    traces = trace_report.group_traces(merged["traceEvents"])
    st = trace_report.tree_stats(traces["t/m"])
    assert st["connected"] and st["spans"] == 2
    assert st["roots"] == ["root"]


def test_admin_debug_trace_endpoint():
    from aiohttp.test_utils import TestClient, TestServer

    from arroyo_tpu.utils.admin import build_admin_app

    with obs.span("adm", trace="t/adm"):
        pass

    async def go():
        app = build_admin_app("test")
        async with TestClient(TestServer(app)) as client:
            resp = await client.get("/debug/trace")
            doc = await resp.json()
            resp2 = await client.get("/debug/trace",
                                     params={"trace": "t/none"})
            doc2 = await resp2.json()
            return doc, doc2

    doc, doc2 = asyncio.run(go())
    assert doc["spanCount"] >= 1
    assert any(e.get("args", {}).get("trace_id") == "t/adm"
               for e in doc["traceEvents"])
    assert doc2["spanCount"] == 0


def test_rest_job_traces_endpoint(tmp_path):
    from aiohttp.test_utils import TestClient, TestServer

    from arroyo_tpu.api.rest import build_app

    with obs.span("ck", trace="jobx/ck-1", cat="controller"):
        pass
    with obs.span("other", trace="joby/ck-1", cat="controller"):
        pass

    async def go():
        app = build_app(db_path=str(tmp_path / "api.db"))
        async with TestClient(TestServer(app)) as client:
            resp = await client.get("/api/v1/jobs/jobx/traces")
            assert resp.status == 200
            return await resp.json()

    doc = asyncio.run(go())
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 1
    assert xs[0]["args"]["trace_id"] == "jobx/ck-1"
    assert doc["spanCount"] == 1


def test_openapi_lists_traces_route(tmp_path):
    from arroyo_tpu.api.openapi import build_spec

    spec = build_spec()
    assert "/api/v1/jobs/{job_id}/traces" in spec["paths"]
    assert "TraceDump" in spec["components"]["schemas"]
