"""Closed-loop autoscaler (ISSUE 5): rate-based parallelism control with
exactly-once automatic rescale.

Three-step loop over the controller's jobs, DS2-shaped (Kalavri et al.,
OSDI '18) with Dhalion-style policy/diagnosis separation (Floratou et al.,
VLDB '17):

  signals.py   observe — registry snapshots -> per-operator true rates,
               busy ratios, backpressure, watermark lag
  policy.py    decide — pluggable Policy protocol; built-in DS2 rate-ratio
               policy with guardrails, hysteresis, clamps
  manager.py   actuate — controller-resident loop driving the proven
               stop-with-checkpoint -> parallelism override -> restore
               path through JobState.RESCALING, fully flight-recorded
               ({job}/rescale-N traces) with a decision audit log
  sim.py       deterministic offline harness: replay load traces through
               the same policy + actuation gate (tools/autoscale_report.py)
"""

from .manager import Autoscaler  # noqa: F401
from .policy import (  # noqa: F401
    ActuationGate,
    DS2Policy,
    Policy,
    PolicyDecision,
    Topology,
    make_policy,
    register_policy,
)
from .signals import OperatorSignals, SignalSampler, merge_snapshots  # noqa: F401
from .sim import SimJob, SimOp, converged_within, run_scenario  # noqa: F401
