"""Protobuf format: encode (sink) and decode (source) via a compiled
FileDescriptorSet, plus the planner's descriptor plumbing."""

import subprocess

import pyarrow as pa
import pytest

from arroyo_tpu.formats.de import Deserializer
from arroyo_tpu.formats.ser import Serializer
from arroyo_tpu.schema import StreamSchema, add_timestamp_field

PROTO = """
syntax = "proto3";
package bench;
message Order {
  int64 id = 1;
  string item = 2;
  double price = 3;
  repeated int64 tags = 4;
}
"""


@pytest.fixture(scope="module")
def descriptor(tmp_path_factory):
    import shutil

    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    d = tmp_path_factory.mktemp("proto")
    (d / "order.proto").write_text(PROTO)
    out = d / "order.desc"
    subprocess.run(
        ["protoc", f"--proto_path={d}", f"--descriptor_set_out={out}",
         "order.proto"],
        check=True,
    )
    return {"descriptor_set": out.read_bytes(),
            "message_name": "bench.Order"}


def test_proto_roundtrip(descriptor):
    schema = StreamSchema(add_timestamp_field(pa.schema([
        ("id", pa.int64()), ("item", pa.string()), ("price", pa.float64()),
        ("tags", pa.list_(pa.int64())),
    ])))
    batch = pa.RecordBatch.from_pylist(
        [
            {"id": 1, "item": "widget", "price": 9.5, "tags": [1, 2],
             "_timestamp": 0},
            {"id": 2, "item": "gadget", "price": 0.25, "tags": [],
             "_timestamp": 0},
        ],
        schema=schema.schema,
    )
    ser = Serializer(format="protobuf", proto_descriptor=descriptor)
    encoded = list(ser.serialize(batch))
    assert len(encoded) == 2 and all(isinstance(b, bytes) for b in encoded)
    de = Deserializer(schema, format="protobuf",
                      proto_descriptor=descriptor)
    rows = []
    for rec in encoded:
        rows.extend(de.deserialize_slice(rec))
    assert [r["id"] for r in rows] == [1, 2]
    assert [r["item"] for r in rows] == ["widget", "gadget"]
    assert rows[0]["price"] == 9.5 and list(rows[0]["tags"]) == [1, 2]
    assert list(rows[1]["tags"]) == []


def test_planner_plumbs_proto_descriptor(descriptor, tmp_path):
    from arroyo_tpu.graph.logical import OperatorName
    from arroyo_tpu.sql import plan_query
    from arroyo_tpu.sql.lexer import SqlError

    desc_file = tmp_path / "order.desc"
    desc_file.write_bytes(descriptor["descriptor_set"])
    plan = plan_query(f"""
        CREATE TABLE impulse WITH (connector = 'impulse',
          event_rate = '1000', message_count = '10', start_time = '0');
        CREATE TABLE sink (id BIGINT) WITH (
          connector = 'kafka', bootstrap_servers = 'localhost:9092',
          topic = 't', format = 'protobuf',
          'proto.descriptor_file' = '{desc_file}',
          'proto.message' = 'bench.Order', type = 'sink'
        );
        INSERT INTO sink SELECT counter as id FROM impulse;
    """)
    sink = next(
        n for n in plan.graph.nodes.values()
        if n.chain[-1].operator == OperatorName.CONNECTOR_SINK
    )
    pd = sink.chain[-1].config["proto_descriptor"]
    assert pd["message_name"] == "bench.Order"
    assert pd["descriptor_set"] == descriptor["descriptor_set"]

    # missing options fail fast
    with pytest.raises(SqlError, match="proto.descriptor_file"):
        plan_query("""
            CREATE TABLE impulse WITH (connector = 'impulse',
              event_rate = '1000', message_count = '10', start_time = '0');
            CREATE TABLE sink (id BIGINT) WITH (
              connector = 'kafka', bootstrap_servers = 'x', topic = 't',
              format = 'protobuf', type = 'sink');
            INSERT INTO sink SELECT counter as id FROM impulse;
        """)

    # newline-framed file connectors cannot carry binary protobuf
    with pytest.raises(SqlError, match="message-framed"):
        plan_query(f"""
            CREATE TABLE impulse WITH (connector = 'impulse',
              event_rate = '1000', message_count = '10', start_time = '0');
            CREATE TABLE sink (id BIGINT) WITH (
              connector = 'single_file', path = '{tmp_path}/o',
              format = 'protobuf',
              'proto.descriptor_file' = '{desc_file}',
              'proto.message' = 'bench.Order', type = 'sink');
            INSERT INTO sink SELECT counter as id FROM impulse;
        """)


NESTED_PROTO = """
syntax = "proto3";
package bench;
message Inner { int64 a = 1; }
message Outer {
  string name = 1;
  Inner one = 2;
  repeated Inner many = 3;
}
"""


def test_proto_nested_roundtrip(tmp_path):
    import shutil

    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    (tmp_path / "nested.proto").write_text(NESTED_PROTO)
    out = tmp_path / "nested.desc"
    subprocess.run(
        ["protoc", f"--proto_path={tmp_path}",
         f"--descriptor_set_out={out}", "nested.proto"],
        check=True,
    )
    desc = {"descriptor_set": out.read_bytes(),
            "message_name": "bench.Outer"}
    from arroyo_tpu.formats.proto import ProtoDecoder, ProtoEncoder

    enc, dec = ProtoEncoder(desc), ProtoDecoder(desc)
    row = {"name": "x", "one": {"a": 7}, "many": [{"a": 1}, {"a": 2}]}
    decoded = dec.decode(enc.encode(row))
    assert decoded == row  # source -> sink round-trips losslessly
    # timestamps land as exact epoch nanos in int64 fields
    import datetime

    ts = datetime.datetime(2026, 7, 29, 1, 2, 3, 456789,
                           tzinfo=datetime.timezone.utc)
    d2 = dec.decode(enc.encode({"name": ts, "one": {"a": ts}}))
    assert d2["one"]["a"] == int(ts.timestamp()) * 10**9 + 456789000
    assert d2["name"] == ts.isoformat()
    # unset singular message fields decode to NULL, not zero-structs
    d3 = dec.decode(enc.encode({"name": "y"}))
    assert d3["one"] is None and d3["many"] == []
