"""Finding reporters: human text and machine JSON."""

from __future__ import annotations

import json
from typing import IO

from .core import get_rule
from .engine import LintResult


def report_text(result: LintResult, out: IO, verbose: bool = False) -> None:
    for f in result.errors:
        out.write(f"{f.path}:{f.line}: [LINT000] {f.message}\n")
    for f in result.findings:
        out.write(f"{f.path}:{f.line}:{f.col}: [{f.rule}] {f.message}\n")
        if verbose:
            try:
                out.write(f"    rule: {get_rule(f.rule).description}\n")
            except KeyError:
                pass
    for e in result.stale_baseline:
        out.write(
            f"LINT_BASELINE: stale entry [{e['rule']}] {e['path']}: "
            f"{e['message']} (fixed or moved — remove it)\n"
        )
    if result.grandfathered:
        out.write(f"{len(result.grandfathered)} grandfathered finding(s) "
                  "suppressed by baseline\n")
    status = "clean" if result.clean else f"{len(result.findings)} finding(s)"
    out.write(
        f"arroyolint: {status} — {result.n_files} files, "
        f"{result.n_rules} rules\n"
    )


def report_json(result: LintResult, out: IO) -> None:
    json.dump(
        {
            "findings": [f.to_dict() for f in result.findings],
            "grandfathered": [f.to_dict() for f in result.grandfathered],
            "stale_baseline": result.stale_baseline,
            "errors": [f.to_dict() for f in result.errors],
            "summary": {
                "files": result.n_files,
                "rules": result.n_rules,
                "clean": result.clean,
            },
        },
        out,
        indent=2,
    )
    out.write("\n")
