"""Confluent schema-registry client: writer-schema resolution + publish.

Capability parity with the reference's schema resolver
(/root/reference/crates/arroyo-rpc/src/schema_resolver.rs:472
ConfluentSchemaRegistry: GET /schemas/ids/{id} with an id-keyed cache,
GET/POST subjects/{subject}/versions). Resolved schemas are cached
process-wide per (endpoint, id); the decode path never re-fetches a
known id, so a registry outage only affects brand-new writer schemas —
same behavior the reference gets from its async cache.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, Optional, Tuple


class SchemaRegistryError(Exception):
    pass


class SchemaRegistryClient:
    def __init__(self, endpoint: str, subject: Optional[str] = None,
                 api_key: Optional[str] = None,
                 api_secret: Optional[str] = None, timeout: float = 10.0):
        self.endpoint = endpoint.rstrip("/")
        self.subject = subject
        self.auth = (api_key, api_secret) if api_key else None
        self.timeout = timeout
        self._by_id: Dict[int, dict] = {}
        self._lock = threading.Lock()

    # -- http -----------------------------------------------------------

    def _get(self, path: str) -> dict:
        import requests

        r = requests.get(f"{self.endpoint}{path}", auth=self.auth,
                         timeout=self.timeout)
        if r.status_code == 404:
            raise SchemaRegistryError(f"not found: {path}")
        if r.status_code != 200:
            raise SchemaRegistryError(
                f"registry GET {path}: {r.status_code} {r.text[:200]}"
            )
        return r.json()

    def _post(self, path: str, body: dict) -> dict:
        import requests

        r = requests.post(
            f"{self.endpoint}{path}", json=body, auth=self.auth,
            timeout=self.timeout,
            headers={
                "Content-Type": "application/vnd.schemaregistry.v1+json"
            },
        )
        if r.status_code not in (200, 201):
            raise SchemaRegistryError(
                f"registry POST {path}: {r.status_code} {r.text[:200]}"
            )
        return r.json()

    # -- resolver surface ------------------------------------------------

    def get_schema_for_id(self, schema_id: int) -> dict:
        """Writer schema by registry id (the 4-byte Confluent framing id),
        cached forever — registry ids are immutable."""
        with self._lock:
            hit = self._by_id.get(schema_id)
        if hit is not None:
            return hit
        resp = self._get(f"/schemas/ids/{schema_id}")
        schema = json.loads(resp["schema"])
        with self._lock:
            self._by_id[schema_id] = schema
        return schema

    def get_subject_latest(
        self, subject: Optional[str] = None
    ) -> Tuple[int, dict]:
        subject = subject or self.subject
        if not subject:
            raise SchemaRegistryError("no subject configured")
        resp = self._get(f"/subjects/{subject}/versions/latest")
        return resp["id"], json.loads(resp["schema"])

    def write_schema(self, schema: Any,
                     subject: Optional[str] = None,
                     schema_type: str = "AVRO") -> int:
        """Register (or find) a schema under the subject; returns its id
        (reference schema_resolver.rs write_schema)."""
        subject = subject or self.subject
        if not subject:
            raise SchemaRegistryError("no subject configured")
        if not isinstance(schema, str):
            schema = json.dumps(schema)
        resp = self._post(
            f"/subjects/{subject}/versions",
            {"schema": schema, "schemaType": schema_type},
        )
        return resp["id"]


class FixedSchemaResolver:
    """Test/static resolver: always returns one schema (reference
    FixedSchemaResolver, schema_resolver.rs:51)."""

    def __init__(self, schema_id: int, schema: dict):
        self.schema_id = schema_id
        self.schema = schema

    def get_schema_for_id(self, schema_id: int) -> dict:
        return self.schema
