--pk=id
CREATE TABLE debezium_source (
  id BIGINT PRIMARY KEY,
  customer_name TEXT,
  product_name TEXT,
  quantity BIGINT,
  price DOUBLE,
  status TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/aggregate_updates.json',
  format = 'debezium_json',
  type = 'source'
);
CREATE TABLE output (
  id TEXT,
  c BIGINT,
  d BIGINT,
  q BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO output
SELECT concat('p_', product_name), count(*), count(DISTINCT customer_name),
       sum(quantity + 5) + 10
FROM debezium_source
GROUP BY concat('p_', product_name);
