"""Watchtower (ISSUE 13): metric-history tier, SLO engine, breach
bundles, REST/debug surfaces, and the satellites (trace-drop counter,
decaying serve slow-read window, hist_quantiles edge cases)."""

import asyncio
import json
import time

import pytest

from arroyo_tpu import obs
from arroyo_tpu.config import update
from arroyo_tpu.metrics import REGISTRY, hist_quantiles
from arroyo_tpu.obs.history import HISTORY, MetricHistory


def _hist_snap(buckets, count=None, total=None):
    """Build a cumulative-bucket snapshot like _hist_dict produces."""
    cum = {}
    running = 0
    for le, c in buckets:
        running += c
        cum[str(le)] = running
    n = count if count is not None else running
    cum["+Inf"] = n
    return {"sum": total if total is not None else 0.0, "count": n,
            "buckets": cum}


# -- the history tier --------------------------------------------------------


def test_series_delta_rate_and_restart_clamp():
    h = MetricHistory(retain=("arroyo_worker_messages_recv",))
    snap = lambda v: {  # noqa: E731
        "arroyo_worker_messages_recv": [({"job": "j", "task": "2-0"}, v)]
    }
    h.ingest(snap(100), now=10.0)
    h.ingest(snap(600), now=11.0)
    h.ingest(snap(1100), now=12.0)
    (s,) = h.get("arroyo_worker_messages_recv", job="j")
    assert s.delta(2.0, now=12.0) == pytest.approx(1000.0)
    assert s.rate(2.0, now=12.0) == pytest.approx(500.0)
    # window base: the sample AT the window edge seeds the first diff
    assert s.delta(1.0, now=12.0) == pytest.approx(500.0)
    # counter restart (replaced worker): post-restart value, never
    # negative — the clamp that used to live ad hoc in autoscale/signals
    h.ingest(snap(40), now=13.0)
    assert s.delta(1.0, now=13.0) == pytest.approx(40.0)
    assert s.delta(3.0, now=13.0) == pytest.approx(1040.0)
    # a single covering sample means "no judgement", not zero
    fresh = MetricHistory(retain=("arroyo_worker_messages_recv",))
    fresh.ingest(snap(5), now=1.0)
    (f,) = fresh.get("arroyo_worker_messages_recv", job="j")
    assert f.delta(10.0, now=2.0) is None


def test_series_gauge_window_and_change_age():
    h = MetricHistory(retain=("arroyo_worker_watermark_lag_seconds",))
    snap = lambda v: {  # noqa: E731
        "arroyo_worker_watermark_lag_seconds": [({"job": "j"}, v)]
    }
    for i, v in enumerate([0.1, 5.0, 0.2]):
        h.ingest(snap(v), now=10.0 + i)
    (s,) = h.get("arroyo_worker_watermark_lag_seconds", job="j")
    assert s.latest() == pytest.approx(0.2)
    assert s.window_max(5.0, now=12.0) == pytest.approx(5.0)
    # gauge windows exclude the pre-window base sample: a stale value
    # from before the window is not part of the window
    assert s.window_max(0.9, now=12.0) == pytest.approx(0.2)
    # last_change_age: the epoch-stall signal
    h2 = MetricHistory(retain=("arroyo_job_published_epoch",))
    esnap = lambda v: {  # noqa: E731
        "arroyo_job_published_epoch": [({"job": "j"}, v)]
    }
    h2.ingest(esnap(3), now=1.0)
    h2.ingest(esnap(4), now=2.0)
    h2.ingest(esnap(4), now=9.0)
    (e,) = h2.get("arroyo_job_published_epoch", job="j")
    assert e.last_change_age(now=10.0) == pytest.approx(8.0)


def test_history_caps_and_job_gc():
    h = MetricHistory(retain=("arroyo_worker_messages_recv",),
                      capacity=4, max_series=2)
    for j in ("a", "b", "c"):
        h.ingest({"arroyo_worker_messages_recv": [({"job": j}, 1)]},
                 now=1.0)
    assert h.stats()["series"] == 2  # cap held
    assert h.dropped_series == 1
    for i in range(10):
        h.ingest({"arroyo_worker_messages_recv": [({"job": "a"}, i)]},
                 now=2.0 + i)
    (s,) = h.get("arroyo_worker_messages_recv", job="a")
    assert len(s.samples) == 4  # ring bounded
    assert h.drop_job("a") == 1
    assert h.get("arroyo_worker_messages_recv", job="a") == []


def test_sample_registry_guard_and_allowlist():
    obs.reset()
    c = REGISTRY.counter("arroyo_worker_messages_recv", "t")
    c.labels(job="g1", task="2-0").inc(5)
    unretained = REGISTRY.counter("arroyo_not_retained_total", "t")
    unretained.labels(job="g1").inc(1)
    with update(watch={"sample_interval": 10.0}):
        n1 = HISTORY.sample_registry(now=100.0)
        assert n1 > 0
        # guarded: a co-resident pump inside the interval is a no-op
        assert HISTORY.sample_registry(now=101.0) == 0
        assert HISTORY.sample_registry(now=110.0) > 0
    assert HISTORY.get("arroyo_worker_messages_recv", job="g1")
    assert HISTORY.get("arroyo_not_retained_total") == []
    with update(watch={"enabled": False, "sample_interval": 10.0}):
        assert HISTORY.sample_registry(now=200.0) == 0
    obs.reset()


def test_hist_window_diff_and_reset():
    h = MetricHistory(retain=("arroyo_serve_request_seconds",))
    snap = lambda s: {  # noqa: E731
        "arroyo_serve_request_seconds": [({"job": "j"}, s)]
    }
    h.ingest(snap(_hist_snap([(0.1, 100), (0.2, 0)], total=5.0)),
             now=1.0)
    h.ingest(snap(_hist_snap([(0.1, 100), (0.2, 50)], total=14.0)),
             now=2.0)
    (s,) = h.get("arroyo_serve_request_seconds", job="j")
    win = s.hist_window(1.0, now=2.0)
    # the window's OWN distribution: 50 samples, all in the (0.1, 0.2]
    # bucket — a lifetime-cumulative histogram could never say that
    assert win["count"] == 50
    assert win["sum"] == pytest.approx(9.0)
    q = hist_quantiles(win)
    assert 0.1 < q["p50"] <= 0.2
    # counter reset between scrapes: the post-restart snapshot IS the
    # window's contribution
    h.ingest(snap(_hist_snap([(0.1, 3), (0.2, 0)], total=0.1)), now=3.0)
    win = s.hist_window(1.0, now=3.0)
    assert win["count"] == 3


# -- hist_quantiles edge cases (satellite) -----------------------------------


def test_hist_quantiles_empty_and_missing():
    assert hist_quantiles(None) == {}
    assert hist_quantiles({}) == {}
    assert hist_quantiles({"sum": 0.0, "count": 0, "buckets": {}}) == {}


def test_hist_quantiles_all_mass_in_inf_bucket():
    # every observation above the highest finite edge: quantiles can
    # only floor at that edge (Prometheus behaves the same)
    snap = {"sum": 500.0, "count": 10,
            "buckets": {"0.1": 0, "0.5": 0, "+Inf": 10}}
    q = hist_quantiles(snap)
    assert q["p50"] == pytest.approx(0.5)
    assert q["p99"] == pytest.approx(0.5)


def test_hist_quantiles_single_bucket():
    snap = {"sum": 1.0, "count": 40, "buckets": {"0.25": 40, "+Inf": 40}}
    q = hist_quantiles(snap, (0.5, 0.99))
    # interpolation inside the only bucket: rank-proportional from 0
    assert 0.0 < q["p50"] <= 0.25
    assert q["p99"] <= 0.25
    assert q["p50"] <= q["p99"]


def test_hist_quantiles_counter_reset_between_scrapes():
    """A replaced worker's histogram restarts: the windowed diff must
    pin to the post-restart distribution, never a negative count."""
    h = MetricHistory(retain=("arroyo_worker_e2e_latency_seconds",))
    snap = lambda s: {  # noqa: E731
        "arroyo_worker_e2e_latency_seconds": [({"job": "j"}, s)]
    }
    h.ingest(snap(_hist_snap([(0.1, 1000), (1.0, 0)])), now=1.0)
    h.ingest(snap(_hist_snap([(0.1, 0), (1.0, 8)])), now=2.0)
    (s,) = h.get("arroyo_worker_e2e_latency_seconds", job="j")
    win = s.hist_window(1.5, now=2.0)
    assert win["count"] == 8
    q = hist_quantiles(win)
    assert 0.1 < q["p99"] <= 1.0  # post-restart mass, not the old 0.1s


# -- SLO engine hysteresis ---------------------------------------------------


def _lag_history(values, family="arroyo_worker_watermark_lag_seconds",
                 job="vic", t0=100.0, dt=1.0):
    h = MetricHistory(retain=(family,))
    for i, v in enumerate(values):
        h.ingest({family: [({"job": job, "task": "2-0"}, v)]},
                 now=t0 + i * dt)
    return h


class _Job:
    """Minimal JobHandle stand-in for standalone evaluation."""

    def __init__(self, job_id, tenant="t0", backend=object()):
        self.job_id = job_id
        self.tenant = tenant
        self.backend = backend
        self.graph = None


def _evaluate_seq(wt, job, values, t0=100.0, dt=1.0,
                  family="arroyo_worker_watermark_lag_seconds"):
    for i, v in enumerate(values):
        now = t0 + i * dt
        wt.history.ingest(
            {family: [({"job": job.job_id, "task": "2-0"}, v)]}, now=now)
        wt.evaluate(now=now, jobs=[(job.job_id, job.tenant, job)])


def test_slo_hysteresis_fire_and_clear(tmp_path):
    from arroyo_tpu.obs.watchtower import Watchtower

    with update(watch={"freshness_lag_s": 3.0, "sustain": 2.0,
                       "clear_sustain": 2.0, "clear_ratio": 0.5,
                       "spool_dir": str(tmp_path / "spool")}):
        wt = Watchtower(history=MetricHistory(
            retain=("arroyo_worker_watermark_lag_seconds",)))
        job = _Job("vic")
        # breach must SUSTAIN: 2 ticks above threshold, then firing
        _evaluate_seq(wt, job, [0.1, 5.0, 6.0, 7.0, 8.0])
        st = wt.alerts[("vic", "freshness")]
        assert st.state == "firing"
        firing = [e for e in wt.ledger if e["event"] == "firing"]
        assert len(firing) == 1
        assert firing[0]["job"] == "vic"
        assert firing[0]["rule"] == "freshness"
        # fires at the first evaluation where the sustain window is met
        # (t+3: 2.0s since the t+1 breach), with THAT tick's value
        assert firing[0]["value"] == pytest.approx(7.0)
        # the cause series rides the event
        assert any(
            c["name"] == "arroyo_worker_watermark_lag_seconds"
            for c in firing[0]["cause"]
        )
        # above clear threshold (1.5 = 3.0 * 0.5): firing holds
        _evaluate_seq(wt, job, [2.0, 2.0], t0=110.0)
        assert wt.alerts[("vic", "freshness")].state == "firing"
        # below clear, sustained: cleared
        _evaluate_seq(wt, job, [0.5, 0.4, 0.3, 0.2], t0=120.0)
        assert wt.alerts[("vic", "freshness")].state == "ok"
        cleared = [e for e in wt.ledger if e["event"] == "cleared"]
        assert len(cleared) == 1
        # alert-transition metric minted
        snap = REGISTRY.snapshot()
        events = {
            (d["rule"], d["event"]): v
            for d, v in snap.get("arroyo_watch_alerts_total", [])
            if d.get("job") == "vic"
        }
        assert events[("freshness", "firing")] == 1
        assert events[("freshness", "cleared")] == 1
    REGISTRY.drop_job("vic")


def test_slo_wobble_never_fires(tmp_path):
    from arroyo_tpu.obs.watchtower import Watchtower

    with update(watch={"freshness_lag_s": 3.0, "sustain": 2.0,
                       "spool_dir": str(tmp_path / "spool")}):
        wt = Watchtower(history=MetricHistory(
            retain=("arroyo_worker_watermark_lag_seconds",)))
        job = _Job("wob")
        # flapping on the threshold: each dip resets the sustain clock
        _evaluate_seq(wt, job, [5.0, 0.1, 5.0, 0.1, 5.0, 0.1, 5.0])
        assert wt.alerts[("wob", "freshness")].state in ("ok", "pending")
        assert not [e for e in wt.ledger if e["event"] == "firing"]
    REGISTRY.drop_job("wob")


def test_slo_overrides_per_tenant_and_job(tmp_path):
    from arroyo_tpu.obs.watchtower import build_rules

    ov = {
        "tenant:gold": {"freshness": {"threshold": 1.0, "sustain": 0.5}},
        "job:j9": {"freshness": {"disabled": True},
                   "checkpoint": {"threshold": 120.0}},
    }
    with update(watch={"overrides": json.dumps(ov)}):
        default = {r.name: r for r in build_rules()}
        gold = {r.name: r for r in build_rules(tenant="gold")}
        j9 = {r.name: r for r in build_rules(tenant="gold", job_id="j9")}
    assert default["freshness"].threshold == 30.0
    assert gold["freshness"].threshold == 1.0
    assert gold["freshness"].sustain == 0.5
    assert "freshness" not in j9  # job override wins over tenant
    assert j9["checkpoint"].threshold == 120.0
    # overrides from a FILE path
    p = tmp_path / "ov.json"
    p.write_text(json.dumps(ov))
    with update(watch={"overrides": str(p)}):
        assert {r.name: r for r in build_rules(tenant="gold")}[
            "freshness"].threshold == 1.0


def test_breach_bundle_capture_and_bounded_spool(tmp_path):
    from arroyo_tpu.obs.watchtower import Watchtower

    obs.reset()
    with update(watch={"freshness_lag_s": 3.0, "sustain": 1.0,
                       "clear_sustain": 1.0, "spool_bundles": 2,
                       "spool_dir": str(tmp_path / "spool")}):
        wt = Watchtower(history=MetricHistory(
            retain=("arroyo_worker_watermark_lag_seconds",)))
        # spans the bundle's flight recording should capture
        with obs.span("ck", trace="vicb/ck-1", cat="controller"):
            pass
        jobs = []
        for i in range(3):
            job = _Job(f"vicb{'' if i == 0 else i}")
            jobs.append(job)
            _evaluate_seq(wt, job, [0.1, 9.0, 9.0, 9.0],
                          t0=100.0 + 10 * i)
        assert wt._bundle_seq == 3
        # bounded spool: only the newest 2 remain, oldest file deleted
        assert len(wt.bundle_index) == 2
        assert {m["job"] for m in wt.bundle_index} == {"vicb1", "vicb2"}
        import os

        spool_files = os.listdir(tmp_path / "spool")
        assert len(spool_files) == 2
        # bundle content: doctor verdict + flight recording + perfetto +
        # history window + ledger
        bundle = wt.bundle(wt.bundle_index[0]["n"])
        assert bundle["rule"] == "freshness"
        assert "verdict" in bundle["doctor"]
        assert "traceEvents" in bundle["perfetto"]
        lag = [s for s in bundle["history"]
               if s["name"] == "arroyo_worker_watermark_lag_seconds"]
        # synthetic ingest times sit outside the live bundle window, so
        # the breach value survives via the base sample / latest
        assert lag and (lag[0].get("max")
                        or lag[0]["latest"]) == pytest.approx(9.0)
        assert bundle["ledger"]
        # the first (evicted) bundle is gone
        assert wt.bundle(0) is None
        for j in jobs:
            REGISTRY.drop_job(j.job_id)
    obs.reset()


def test_watchtower_expunge_drops_alert_state(tmp_path):
    from arroyo_tpu.obs.watchtower import Watchtower

    with update(watch={"freshness_lag_s": 3.0, "sustain": 1.0,
                       "spool_dir": str(tmp_path / "spool")}):
        wt = Watchtower(history=MetricHistory(
            retain=("arroyo_worker_watermark_lag_seconds",)))
        job = _Job("gone")
        _evaluate_seq(wt, job, [9.0, 9.0, 9.0])
        assert ("gone", "freshness") in wt.alerts
        wt.expunge_job("gone")
        assert not [k for k in wt.alerts if k[0] == "gone"]
        # ledger events survive as diagnostics of the past
        assert [e for e in wt.ledger if e["job"] == "gone"]
    REGISTRY.drop_job("gone")


# -- autoscaler/doctor on the history tier -----------------------------------


def test_signal_sampler_windowed_batch_p95():
    """The sampler's batch_p95 is the WINDOW's distribution, not the
    lifetime cumulative: old fast batches must not dilute a recent
    slowdown."""
    from arroyo_tpu.autoscale.signals import SignalSampler

    s = SignalSampler("j1")
    fast = _hist_snap([(0.01, 1000), (10.0, 0)])
    slow = _hist_snap([(0.01, 1000), (10.0, 50)])
    base = {
        "arroyo_worker_messages_recv": [({"job": "j1", "task": "2-0"},
                                         1000)],
        "arroyo_worker_batch_processing_seconds": [
            ({"job": "j1", "task": "2-0"}, fast)],
    }
    s.sample(base, {2: 1}, now=10.0)
    nxt = {
        "arroyo_worker_messages_recv": [({"job": "j1", "task": "2-0"},
                                         1050)],
        "arroyo_worker_batch_processing_seconds": [
            ({"job": "j1", "task": "2-0"}, slow)],
    }
    sigs = s.sample(nxt, {2: 1}, now=11.0)
    # all 50 window observations sit in the (0.01, 10] bucket
    assert sigs[2].batch_p95 > 0.01


def test_doctor_windowed_overlay_prefers_recent_shares():
    """Cumulative attribution says job A dominated the worker's LIFE;
    the history window says B is hogging NOW — the doctor must name B."""
    from arroyo_tpu.obs import attribution, doctor

    obs.reset()
    # lifetime: A burned 100s long ago; recent window: B burns
    attribution.note(job="oldhog", busy=100.0)
    attribution.note(job="victimw", busy=0.01)
    attribution.ACCOUNTING.flush()
    now = time.monotonic()
    fam = "arroyo_job_attributed_busy_seconds"
    for i, t in enumerate((now - 8.0, now - 4.0, now - 0.5)):
        HISTORY.ingest({fam: [
            ({"job": "oldhog"}, 100.0),           # flat: idle now
            ({"job": "newhog"}, 100.0 + 4.0 * i),  # climbing: hot now
            ({"job": "victimw"}, 0.01),
        ]}, now=t)
    sig = doctor.collect("victimw")
    assert sig.get("windowed") is True
    assert sig["neighbors"][0]["job"] == "newhog"
    assert sig["neighbor_top_share"] > 0.9
    obs.reset()
    for j in ("oldhog", "newhog", "victimw"):
        REGISTRY.drop_job(j)


# -- satellites: trace drops, serve slow-read window -------------------------


def test_trace_drop_counter_metric():
    from arroyo_tpu.obs.trace import TraceRecorder

    before = 0
    for labels, v in REGISTRY.snapshot().get(
            "arroyo_trace_dropped_spans_total", []):
        before += v
    rec = TraceRecorder(capacity=2)
    for i in range(5):
        rec.record({"trace_id": f"t/{i}", "span_id": str(i), "name": "s",
                    "cat": "t", "ts": 0, "dur": 1, "attrs": {},
                    "events": []})
    assert rec.dropped == 3
    after = sum(
        v for _l, v in REGISTRY.snapshot().get(
            "arroyo_trace_dropped_spans_total", [])
    )
    assert after - before == 3


def test_trace_drop_rule_fires_on_sustained_drops(tmp_path):
    from arroyo_tpu.obs.watchtower import Watchtower

    fam = "arroyo_trace_dropped_spans_total"
    with update(watch={"trace_drop_rate": 1.0, "sustain": 2.0,
                       "window": 10.0,
                       "spool_dir": str(tmp_path / "spool")}):
        wt = Watchtower(history=MetricHistory(retain=(fam,)))
        job = _Job("tdrop")
        # 50 drops/s sustained — process-wide series (no job label)
        for i, v in enumerate([0, 50, 100, 150, 200]):
            now = 100.0 + i
            wt.history.ingest({fam: [({}, v)]}, now=now)
            wt.evaluate(now=now,
                        jobs=[(job.job_id, job.tenant, job)])
        assert wt.alerts[("tdrop", "trace_drops")].state == "firing"
    REGISTRY.drop_job("tdrop")


def test_serve_slowest_read_decays_and_clears():
    from arroyo_tpu.serve.gateway import StateGateway

    gw = StateGateway(None)
    with update(serve={"slow_read_window": 0.3}):
        gw._note_slow(0.250, "j1", "t", 4, "ok")
        got = gw.slowest_read()
        assert got["ms"] == pytest.approx(250.0)
        assert got["job"] == "j1"
        time.sleep(0.35)
        # the outlier aged out instead of pinning forever
        assert gw.slowest_read() is None
        gw._note_slow(0.005, "j2", "t", 1, "ok")
        assert gw.slowest_read()["ms"] == pytest.approx(5.0)
        gw.clear_slow()
        assert gw.slowest_read() is None


def test_serve_slowest_read_window_max_survives_flood():
    from arroyo_tpu.serve.gateway import StateGateway

    gw = StateGateway(None)
    with update(serve={"slow_read_window": 300.0}):
        gw._note_slow(0.9, "slow", "t", 1, "ok")
        for _ in range(2000):  # a read flood must not evict the max
            gw._note_slow(0.001, "fast", "t", 1, "ok")
        assert gw.slowest_read()["job"] == "slow"


# -- REST + debug surfaces ---------------------------------------------------


def test_rest_watch_routes_without_controller(tmp_path):
    from aiohttp.test_utils import TestClient, TestServer

    from arroyo_tpu.api.rest import build_app

    obs.reset()
    c = REGISTRY.counter("arroyo_worker_messages_recv", "t")
    c.labels(job="rw1", task="2-0").inc(7)
    with update(watch={"sample_interval": 0.0}):
        HISTORY.sample_registry(now=time.monotonic())

    async def go():
        app = build_app(db_path=str(tmp_path / "api.db"))
        async with TestClient(TestServer(app)) as client:
            alerts = await (await client.get(
                "/api/v1/jobs/rw1/alerts")).json()
            hist = await (await client.get(
                "/api/v1/jobs/rw1/metrics/history",
                params={"series": "arroyo_worker_messages_recv",
                        "window": "60"})).json()
            bundles = await (await client.get(
                "/api/v1/jobs/rw1/bundles")).json()
            missing = await client.get("/api/v1/jobs/rw1/bundles/99")
            openapi = await (await client.get(
                "/api/v1/openapi.json")).json()
        return alerts, hist, bundles, missing.status, openapi

    alerts, hist, bundles, missing, openapi = asyncio.run(go())
    assert alerts == {"job": "rw1", "alerts": {}, "firing": [],
                      "ledger": []}
    assert hist["series"][0]["name"] == "arroyo_worker_messages_recv"
    assert hist["series"][0]["labels"]["job"] == "rw1"
    assert bundles == {"data": []}
    assert missing == 404
    for path in ("/jobs/{job_id}/alerts",
                 "/jobs/{job_id}/metrics/history",
                 "/jobs/{job_id}/bundles",
                 "/jobs/{job_id}/bundles/{n}"):
        assert f"/api/v1{path}" in openapi["paths"], path
    assert "AlertReport" in openapi["components"]["schemas"]
    assert "Bundle" in openapi["components"]["schemas"]
    REGISTRY.drop_job("rw1")
    obs.reset()


def test_admin_debug_history_route():
    from aiohttp.test_utils import TestClient, TestServer

    from arroyo_tpu.utils.admin import build_admin_app

    obs.reset()
    REGISTRY.counter("arroyo_worker_messages_recv", "t").labels(
        job="dh1", task="1-0").inc(3)
    with update(watch={"sample_interval": 0.0}):
        HISTORY.sample_registry(now=time.monotonic())

    async def go():
        admin = build_admin_app("test")
        async with TestClient(TestServer(admin)) as client:
            plain = await (await client.get("/debug/history")).json()
            scoped = await (await client.get(
                "/debug/history", params={"job": "dh1"})).json()
        return plain, scoped

    plain, scoped = asyncio.run(go())
    assert plain["history"]["series"] >= 1
    assert "arroyo_worker_messages_recv" in plain["families"]
    assert any(s["labels"].get("job") == "dh1"
               for s in scoped["series"])
    REGISTRY.drop_job("dh1")
    obs.reset()


# -- the offline report tool -------------------------------------------------


def test_watch_report_renders_timeline_and_bundle(tmp_path, capsys):
    import sys

    sys.path.insert(0, "/root/repo/tools")
    try:
        import watch_report
    finally:
        sys.path.remove("/root/repo/tools")

    report = {
        "watch_victim": "vic", "watch_healthy_observed": 3,
        "watch_fired": 1, "watch_fire_s": 7.5,
        "watch_victim_rules": ["freshness"],
        "watch_bundle_ok": 1, "watch_cleared_ok": 1,
        "watch_false_positive_count": 0,
        "watch_ledger": [
            {"ts": 1000.0, "event": "firing", "job": "vic",
             "rule": "freshness", "value": 9.1, "threshold": 3.0,
             "unit": "s", "sustained_s": 1.2},
            {"ts": 1030.0, "event": "cleared", "job": "vic",
             "rule": "freshness", "value": 0.4, "threshold": 3.0,
             "unit": "s", "fired_for_s": 30.0},
        ],
    }
    p = tmp_path / "report.json"
    p.write_text(json.dumps(report))
    bundle = {
        "n": 0, "job": "vic", "tenant": "t", "rule": "freshness",
        "captured_at": 1001.0,
        "alert": {"value": 9.1, "threshold": 3.0, "unit": "s"},
        "doctor": {"verdict": {"cause": "starved", "operator": "2-0",
                               "confidence": 0.9}},
        "flight_recorder": [{}] * 5,
        "perfetto": {"traceEvents": [{}] * 7},
        "history": [{"name": "arroyo_worker_watermark_lag_seconds",
                     "labels": {"job": "vic"}, "kind": "scalar",
                     "samples": [[1000.0, 9.1]], "max": 9.1}],
    }
    b = tmp_path / "bundle.json"
    b.write_text(json.dumps(bundle))
    rc = watch_report.main([str(p), "--bundle", str(b)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "FIRING" in out and "CLEARED" in out
    assert "[ok] zero false positives" in out
    assert "5 spans" in out and "1 series" in out
    # a failed drill renders FAIL and returns nonzero
    report["watch_false_positive_count"] = 2
    p.write_text(json.dumps(report))
    assert watch_report.main([str(p)]) == 1


# -- e2e: a real embedded job breaches freshness and bundles -----------------


def test_watchtower_e2e_breach_and_bundle(tmp_path):
    """A real durable pipeline on an embedded cluster: chaos storage
    latency on its checkpoint data files stalls it, the watchtower
    fires freshness naming the job, a bundle lands with the breach in
    its history window, and REST serves alerts + bundle. (The full
    drill — 10 healthy co-tenants, zero false positives, post-recovery
    clear — runs in the fleet harness --watch scenario / nightly CI.)"""
    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    from arroyo_tpu import chaos
    from arroyo_tpu.api.rest import build_app
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState

    obs.reset()
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '2000',
      message_count = '1000000000', realtime = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{tmp_path}/out.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % 8 as k, tumble(interval '100 millisecond') as w,
             count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """

    async def go():
        async with _watch_cluster(tmp_path) as (controller, client):
            await controller.submit_job("watchjob", sql=sql,
                                        n_workers=1, parallelism=1)
            await controller.wait_for_state(
                "watchjob", JobState.RUNNING, timeout=60)
            wt = controller.watchtower
            deadline = time.monotonic() + 30
            while not wt.history.get(
                    "arroyo_worker_watermark_lag_seconds",
                    job="watchjob"):
                assert time.monotonic() < deadline, "no lag series"
                await asyncio.sleep(0.2)
            plan = chaos.FaultPlan(seed=7)
            plan.add("runner.stall", at_hits=list(range(1, 100000)),
                     match={"job": "watchjob"},
                     params={"delay": 0.5}, max_fires=100000)
            chaos.install(plan)
            stall_wall = time.time()
            try:
                deadline = time.monotonic() + 40
                doc = {}
                while time.monotonic() < deadline:
                    doc = await (await client.get(
                        "/api/v1/jobs/watchjob/alerts")).json()
                    if "freshness" in doc.get("firing", []):
                        break
                    await asyncio.sleep(0.25)
                assert "freshness" in doc.get("firing", []), doc
                firing = [e for e in doc["ledger"]
                          if e["event"] == "firing"
                          and e["rule"] == "freshness"]
                assert firing and firing[0]["job"] == "watchjob"
                idx = (await (await client.get(
                    "/api/v1/jobs/watchjob/bundles")).json())["data"]
                assert idx, "no bundle captured on breach"
                # the throughput rule may legitimately fire first on the
                # same backlog; assert the FRESHNESS bundle specifically
                meta = next((m for m in idx if m["rule"] == "freshness"),
                            idx[0])
                bundle = await (await client.get(
                    f"/api/v1/jobs/watchjob/bundles/{meta['n']}"
                )).json()
                lag = [s for s in bundle["history"]
                       if s["name"]
                       == "arroyo_worker_watermark_lag_seconds"]
                assert lag and max(
                    s.get("max", 0.0) for s in lag) >= 3.0
                assert any(s.get("ts", 0) >= stall_wall * 1e6
                           for s in bundle["flight_recorder"])
                assert bundle["doctor"].get("verdict")
                hist = await (await client.get(
                    "/api/v1/jobs/watchjob/metrics/history",
                    params={"series":
                            "arroyo_worker_watermark_lag_seconds"}
                )).json()
                assert hist["series"], hist
            finally:
                chaos.clear()
            await controller.stop_job("watchjob", "immediate")

    asyncio.run(go())
    obs.reset()


class _watch_cluster:
    """Embedded controller + REST client under drill-speed watch
    config."""

    def __init__(self, tmp_path):
        self.tmp_path = tmp_path

    async def __aenter__(self):
        from aiohttp.test_utils import TestClient, TestServer

        from arroyo_tpu.api.rest import build_app
        from arroyo_tpu.controller.controller import ControllerServer
        from arroyo_tpu.controller.scheduler import EmbeddedScheduler

        self._cm = update(
            pipeline={"checkpointing": {
                "interval": 0.5,
                "storage_url": f"{self.tmp_path}/ck"}},
            watch={"sample_interval": 0.25, "eval_interval": 0.25,
                   "window": 10.0, "sustain": 1.0,
                   "clear_sustain": 1.5, "freshness_lag_s": 3.0,
                   "checkpoint_age_s": 8.0, "loop_lag_s": 30.0,
                   "trace_drop_rate": 1e9,
                   "spool_dir": f"{self.tmp_path}/bundles"},
            obs={"latency_marker_interval": 0.0},
        )
        self._cm.__enter__()
        self.controller = await ControllerServer(
            EmbeddedScheduler()).start()
        app = build_app(self.controller,
                        db_path=f"{self.tmp_path}/api.db")
        self.client = TestClient(TestServer(app))
        await self.client.start_server()
        return self.controller, self.client

    async def __aexit__(self, *exc):
        await self.client.close()
        await self.controller.stop()
        self._cm.__exit__(*exc)
        return False
