"""Task-local chain cache: same-worker restarts skip the storage round-trip.

Hot-standby failover (ISSUE 17) restores and then continuously tails
delta-chain blobs. The blobs a worker READS at restore/tail time are very
often blobs the same process WROTE at flush time an epoch earlier — a
restarted or promoted incarnation landing on the same worker would
otherwise pay a full storage round-trip per chain entry for bytes it just
uploaded. This cache keeps the last published chains' blobs in process
memory, keyed by their storage path (paths are generation-stamped and
written exactly once, so an entry can never go stale — only unreferenced).

Sizing and invalidation:
  * LRU with a byte cap (`failover.cache_max_bytes`) — eviction is the
    normal lifecycle.
  * `invalidate_below(job_id, epoch)` drops entries for checkpoint epochs
    a newer manifest no longer references (rebase truncated the chain, or
    GC retired the epoch) — called when tailing observes the chain floor
    moving.
  * `invalidate_job(job_id)` on job expunge.

The cache is process-global (workers multiplex many jobs on one loop) and
gated by `config().failover.local_chain_cache`; with the gate off every
call is a cheap no-op and reads fall through to storage.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Optional, Tuple

from ..config import config
from ..metrics import REGISTRY

CHAIN_CACHE_HITS = REGISTRY.counter(
    "arroyo_chain_cache_hits",
    "task-local chain cache hits (storage reads skipped)",
)
CHAIN_CACHE_MISSES = REGISTRY.counter(
    "arroyo_chain_cache_misses",
    "task-local chain cache misses (read fell through to storage)",
)

_EPOCH_RE = re.compile(r"checkpoint-(\d+)")


class ChainCache:
    def __init__(self):
        # (storage url, path) -> bytes; OrderedDict gives LRU order
        self._entries: "OrderedDict[Tuple[str, str], bytes]" = OrderedDict()
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._lock = threading.Lock()  # flushes run in to_thread workers

    @staticmethod
    def _enabled() -> bool:
        return bool(config().failover.local_chain_cache)

    @staticmethod
    def _job_of(path: str) -> str:
        return path.split("/", 1)[0]

    @staticmethod
    def _epoch_of(path: str) -> Optional[int]:
        m = _EPOCH_RE.search(path)
        return int(m.group(1)) if m else None

    def put(self, storage_url: str, path: str, blob: bytes):
        if not self._enabled() or blob is None:
            return
        cap = int(config().failover.cache_max_bytes)
        if len(blob) > cap:
            return
        with self._lock:
            key = (storage_url, path)
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._entries[key] = blob
            self._bytes += len(blob)
            while self._bytes > cap and self._entries:
                _k, v = self._entries.popitem(last=False)
                self._bytes -= len(v)

    def get(self, storage_url: str, path: str) -> Optional[bytes]:
        if not self._enabled():
            return None
        with self._lock:
            blob = self._entries.get((storage_url, path))
            if blob is not None:
                self._entries.move_to_end((storage_url, path))
        job = self._job_of(path)
        if blob is not None:
            self._hits += 1
            CHAIN_CACHE_HITS.labels(job=job).inc()
        else:
            self._misses += 1
            CHAIN_CACHE_MISSES.labels(job=job).inc()
        return blob

    def invalidate_below(self, job_id: str, epoch: int):
        """Drop cached blobs of `job_id` whose checkpoint epoch is below
        `epoch` — the tailed manifest's chain floor moved past them."""
        with self._lock:
            for key in list(self._entries):
                path = key[1]
                if self._job_of(path) != job_id:
                    continue
                e = self._epoch_of(path)
                if e is not None and e < epoch:
                    self._bytes -= len(self._entries.pop(key))

    def invalidate_job(self, job_id: str):
        with self._lock:
            for key in list(self._entries):
                if self._job_of(key[1]) == job_id:
                    self._bytes -= len(self._entries.pop(key))

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self._hits, "misses": self._misses}


CACHE = ChainCache()
