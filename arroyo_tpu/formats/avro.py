"""Avro binary codec: full recursive type support + Object Container Files.

Capability parity target: the reference decodes Avro with apache-avro and
resolves writer schemas from a Confluent schema registry
(/root/reference/crates/arroyo-formats/src/avro/*). This is a dependency-
free implementation of the Avro 1.11 binary encoding covering records,
arrays, maps, unions, enums, fixed, and all primitives, plus:

  * the Confluent wire framing (magic 0 + 4-byte schema id), used by the
    schema-registry integration in formats/de.py;
  * Object Container Files (magic ``Obj\\x01``, metadata map, sync-marker
    delimited blocks, null codec) — the on-disk format of Iceberg
    manifests and manifest lists (connectors/iceberg.py).
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Dict, Iterable, List, Optional, Tuple

import pyarrow as pa

PRIMITIVES = {
    "null", "boolean", "int", "long", "float", "double", "string", "bytes"
}


def _zigzag_encode(n: int) -> bytes:
    n = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def bytes_(self) -> bytes:
        n = self.long()
        out = self.data[self.pos: self.pos + n]
        self.pos += n
        return out

    def fixed(self, n: int) -> bytes:
        out = self.data[self.pos: self.pos + n]
        self.pos += n
        return out

    def float_(self) -> float:
        (v,) = struct.unpack_from("<f", self.data, self.pos)
        self.pos += 4
        return v

    def double(self) -> float:
        (v,) = struct.unpack_from("<d", self.data, self.pos)
        self.pos += 8
        return v

    def boolean(self) -> bool:
        v = self.data[self.pos] == 1
        self.pos += 1
        return v

    @property
    def eof(self) -> bool:
        return self.pos >= len(self.data)


class _Names:
    """Named-type registry: records/enums/fixed referenced by name later
    in the same schema (Iceberg manifest schemas use this)."""

    def __init__(self):
        self.types: Dict[str, dict] = {}

    def register(self, t: dict):
        name = t.get("name")
        if name:
            ns = t.get("namespace")
            self.types[name] = t
            if ns:
                self.types[f"{ns}.{name}"] = t

    def resolve(self, t):
        if isinstance(t, str) and t not in PRIMITIVES:
            if t not in self.types:
                raise ValueError(f"unknown avro named type {t!r}")
            return self.types[t]
        return t


def _collect_names(t, names: _Names):
    if isinstance(t, dict):
        if t.get("type") in ("record", "enum", "fixed", "error"):
            names.register(t)
        for f in t.get("fields", []) or []:
            _collect_names(f.get("type"), names)
        for k in ("items", "values"):
            if k in t:
                _collect_names(t[k], names)
    elif isinstance(t, list):
        for b in t:
            _collect_names(b, names)


def write_datum(out: bytearray, t, v, names: _Names):
    t = names.resolve(t)
    if isinstance(t, list):  # union: pick the matching branch
        idx = _union_branch(t, v, names)
        out += _zigzag_encode(idx)
        write_datum(out, t[idx], v, names)
        return
    if isinstance(t, dict):
        kind = t["type"]
        if kind == "record":
            for f in t["fields"]:
                fv = v.get(f["name"], f.get("default")) if isinstance(
                    v, dict
                ) else getattr(v, f["name"])
                write_datum(out, f["type"], fv, names)
            return
        if kind == "array":
            v = list(v or [])
            if v:
                out += _zigzag_encode(len(v))
                for item in v:
                    write_datum(out, t["items"], item, names)
            out += _zigzag_encode(0)
            return
        if kind == "map":
            v = dict(v or {})
            if v:
                out += _zigzag_encode(len(v))
                for k, mv in v.items():
                    b = str(k).encode()
                    out += _zigzag_encode(len(b)) + b
                    write_datum(out, t["values"], mv, names)
            out += _zigzag_encode(0)
            return
        if kind == "enum":
            out += _zigzag_encode(t["symbols"].index(v))
            return
        if kind == "fixed":
            if len(v) != t["size"]:
                raise ValueError(
                    f"fixed {t.get('name')} needs {t['size']} bytes"
                )
            out += v
            return
        t = kind  # primitive with annotations (logicalType etc.)
    if t == "null":
        return
    if t == "boolean":
        out.append(1 if v else 0)
    elif t in ("int", "long"):
        out += _zigzag_encode(int(v))
    elif t == "float":
        out += struct.pack("<f", float(v))
    elif t == "double":
        out += struct.pack("<d", float(v))
    elif t == "string":
        b = v.encode() if isinstance(v, str) else str(v).encode()
        out += _zigzag_encode(len(b)) + b
    elif t == "bytes":
        out += _zigzag_encode(len(v)) + bytes(v)
    else:
        raise ValueError(f"unsupported avro type {t!r}")


def _union_branch(branches: list, v, names: _Names) -> int:
    def matches(b) -> bool:
        b = names.resolve(b)
        kind = b["type"] if isinstance(b, dict) else b
        if v is None:
            return kind == "null"
        if isinstance(v, bool):
            return kind == "boolean"
        if isinstance(v, int):
            return kind in ("int", "long")
        if isinstance(v, float):
            return kind in ("double", "float")
        if isinstance(v, str):
            return kind in ("string", "enum")
        if isinstance(v, (bytes, bytearray)):
            return kind in ("bytes", "fixed")
        if isinstance(v, dict):
            return kind in ("record", "map")
        if isinstance(v, (list, tuple)):
            return kind == "array"
        return False

    for i, b in enumerate(branches):
        if matches(b):
            return i
    # lenient pass: ints coerce into a float/double branch, and anything
    # stringifiable lands in a string branch (the write path coerces)
    for i, b in enumerate(branches):
        kind = b["type"] if isinstance(b, dict) else b
        if isinstance(v, int) and kind in ("double", "float"):
            return i
    for i, b in enumerate(branches):
        kind = b["type"] if isinstance(b, dict) else b
        if kind == "string" and v is not None:
            return i
    raise ValueError(f"no union branch for {type(v).__name__} in {branches}")


def read_datum(r: _Reader, t, names: _Names) -> Any:
    t = names.resolve(t)
    if isinstance(t, list):
        return read_datum(r, t[r.long()], names)
    if isinstance(t, dict):
        kind = t["type"]
        if kind == "record":
            return {
                f["name"]: read_datum(r, f["type"], names)
                for f in t["fields"]
            }
        if kind == "array":
            out = []
            while True:
                n = r.long()
                if n == 0:
                    break
                if n < 0:  # block with byte size prefix
                    n = -n
                    r.long()
                for _ in range(n):
                    out.append(read_datum(r, t["items"], names))
            return out
        if kind == "map":
            out = {}
            while True:
                n = r.long()
                if n == 0:
                    break
                if n < 0:
                    n = -n
                    r.long()
                for _ in range(n):
                    k = r.bytes_().decode()
                    out[k] = read_datum(r, t["values"], names)
            return out
        if kind == "enum":
            return t["symbols"][r.long()]
        if kind == "fixed":
            return r.fixed(t["size"])
        t = kind
    if t == "null":
        return None
    if t == "boolean":
        return r.boolean()
    if t in ("int", "long"):
        return r.long()
    if t == "float":
        return r.float_()
    if t == "double":
        return r.double()
    if t == "string":
        return r.bytes_().decode()
    if t == "bytes":
        return r.bytes_()
    raise ValueError(f"unsupported avro type {t!r}")


class AvroDecoder:
    def __init__(self, schema_json: Optional[str]):
        if not schema_json:
            raise ValueError("avro format requires avro.schema option")
        self.schema = json.loads(schema_json) if isinstance(
            schema_json, str
        ) else schema_json
        assert self.schema["type"] == "record"
        self.names = _Names()
        _collect_names(self.schema, self.names)
        self.fields: List[Dict] = self.schema["fields"]

    def decode(self, record: bytes) -> Dict[str, Any]:
        if len(record) > 5 and record[0] == 0:
            # Confluent wire format: magic 0 + schema id
            record = record[5:]
        return self.decode_raw(record)

    def decode_raw(self, record: bytes) -> Dict[str, Any]:
        """Decode an UNframed record body. Callers that already stripped
        the Confluent framing must use this — decode()'s heuristic would
        re-strip payloads whose first field encodes to a 0x00 byte."""
        r = _Reader(record)
        return {
            f["name"]: read_datum(r, f["type"], self.names)
            for f in self.fields
        }


class AvroEncoder:
    def __init__(self, schema_json: Optional[str], arrow_schema: pa.Schema):
        if schema_json:
            self.schema = json.loads(schema_json) if isinstance(
                schema_json, str
            ) else schema_json
        else:
            self.schema = schema_from_arrow(arrow_schema)
        self.names = _Names()
        _collect_names(self.schema, self.names)
        self.fields = self.schema["fields"]

    def encode(self, row: Dict[str, Any]) -> bytes:
        out = bytearray()
        for f in self.fields:
            write_datum(out, f["type"], row.get(f["name"]), self.names)
        return bytes(out)


# ---------------------------------------------------------------------------
# Object Container Files (Iceberg manifests / manifest lists ride on these)
# ---------------------------------------------------------------------------

OCF_MAGIC = b"Obj\x01"


def write_ocf(schema: dict, rows: Iterable[dict],
              metadata: Optional[Dict[str, str]] = None) -> bytes:
    """Serialize rows into an Avro Object Container File (null codec)."""
    names = _Names()
    _collect_names(schema, names)
    sync = os.urandom(16)
    out = bytearray(OCF_MAGIC)
    meta = {"avro.schema": json.dumps(schema), "avro.codec": "null"}
    meta.update(metadata or {})
    write_datum(
        out,
        {"type": "map", "values": "bytes"},
        {k: v.encode() if isinstance(v, str) else v for k, v in meta.items()},
        names,
    )
    out += sync
    body = bytearray()
    count = 0
    for row in rows:
        write_datum(body, schema, row, names)
        count += 1
    if count:
        out += _zigzag_encode(count)
        out += _zigzag_encode(len(body))
        out += body
        out += sync
    return bytes(out)


def read_ocf(data: bytes) -> Tuple[dict, List[dict]]:
    """Parse an Object Container File; returns (schema, rows)."""
    if data[:4] != OCF_MAGIC:
        raise ValueError("not an avro object container file")
    r = _Reader(data)
    r.pos = 4
    names = _Names()
    meta = read_datum(r, {"type": "map", "values": "bytes"}, names)
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    schema = json.loads(meta["avro.schema"].decode())
    _collect_names(schema, names)
    sync = r.fixed(16)
    rows: List[dict] = []
    while not r.eof:
        count = r.long()
        size = r.long()
        block = r.fixed(size)
        if codec == "deflate":
            import zlib

            block = zlib.decompress(block, -15)
        br = _Reader(block)
        for _ in range(count):
            rows.append(read_datum(br, schema, names))
        if r.fixed(16) != sync:
            raise ValueError("avro container sync marker mismatch")
    return schema, rows


def schema_from_arrow(schema: pa.Schema, name: str = "Record") -> dict:
    """Arrow schema -> Avro record schema (sink schema generator,
    reference ser.rs:89-101)."""
    fields = []
    for f in schema:
        if f.name.startswith("_"):
            continue
        t = _avro_type_from_arrow(f.type)
        fields.append(
            {"name": f.name, "type": ["null", t] if f.nullable else t}
        )
    return {"type": "record", "name": name, "fields": fields}


def _avro_type_from_arrow(at: pa.DataType):
    if pa.types.is_boolean(at):
        return "boolean"
    if pa.types.is_integer(at):
        return "long"
    if pa.types.is_float32(at):
        return "float"
    if pa.types.is_floating(at):
        return "double"
    if pa.types.is_binary(at):
        return "bytes"
    if pa.types.is_timestamp(at):
        return {"type": "long", "logicalType": "timestamp-micros"}
    if pa.types.is_list(at):
        return {"type": "array", "items": _avro_type_from_arrow(
            at.value_type)}
    if pa.types.is_struct(at):
        import hashlib

        # deterministic record name: python hash() is salted per process,
        # which would rename the record on every restart and trip registry
        # compatibility checks
        digest = hashlib.sha256(str(at).encode()).hexdigest()[:8]
        return {
            "type": "record",
            "name": f"r_{digest}",
            "fields": [
                {"name": f.name, "type": _avro_type_from_arrow(f.type)}
                for f in at
            ],
        }
    return "string"
