from .rest import build_app, serve_api  # noqa: F401
