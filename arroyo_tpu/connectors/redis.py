"""Redis connector: sink (string/list/hash targets) + lookup join source.

Capability parity with the reference's redis connector
(/root/reference/crates/arroyo-connectors/src/redis/, 994 LoC): sink writes
each row under a key built from `target.key_prefix` + key column to a
string/list/hash target; the LookupConnector side serves lookup joins with
an optional TTL'd cache. Client gated on the `redis` library.
"""

from __future__ import annotations

import time
from typing import Optional

from ..operators.base import Operator
from ..formats.ser import Serializer
from ._gated import require_client
from .base import ConnectionSchema, Connector, register_connector


class RedisSink(Operator):
    def __init__(self, address: str, target: str, key_prefix: str,
                 key_field: Optional[str], format: str):
        super().__init__("redis_sink")
        self.address = address
        self.target = target  # string | list | hash
        self.key_prefix = key_prefix
        self.key_field = key_field
        self.serializer = Serializer(format=format or "json")
        self.client = None
        self._seq = 0  # unique hash-field counter (survives across batches)

    async def on_start(self, ctx):
        redis = require_client("redis")
        self.client = redis.Redis.from_url(self.address)

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        keys = (
            batch.column(batch.schema.names.index(self.key_field)).to_pylist()
            if self.key_field and self.key_field in batch.schema.names
            else None
        )
        pipe = self.client.pipeline()
        for i, rec in enumerate(self.serializer.serialize(batch)):
            key = self.key_prefix + (str(keys[i]) if keys is not None else "")
            if self.target == "list":
                pipe.rpush(key, rec)
            elif self.target == "hash":
                field = str(keys[i]) if keys is not None else str(self._seq)
                self._seq += 1
                pipe.hset(key, field, rec)
            else:
                pipe.set(key, rec)
        pipe.execute()


class RedisLookup:
    """LookupConnector for lookup joins (reference connector.rs:421),
    with a TTL'd local cache."""

    def __init__(self, address: str, key_prefix: str, ttl: float = 60.0):
        redis = require_client("redis")
        self.client = redis.Redis.from_url(address)
        self.key_prefix = key_prefix
        self.ttl = ttl
        self.cache = {}

    def lookup(self, key: str) -> Optional[bytes]:
        now = time.monotonic()
        hit = self.cache.get(key)
        if hit is not None and now - hit[1] < self.ttl:
            return hit[0]
        val = self.client.get(self.key_prefix + key)
        self.cache[key] = (val, now)
        return val


@register_connector
class RedisConnector(Connector):
    name = "redis"
    description = "Redis sink and lookup-join source"
    sink = True
    config_schema = {
        "address": {"type": "string", "required": True},
        "target": {"type": "string", "enum": ["string", "list", "hash"]},
        "target.key_prefix": {"type": "string"},
        "target.key_column": {"type": "string"},
    }

    def validate_options(self, options, schema):
        if "address" not in options:
            raise ValueError("redis requires an address option")
        return {
            "address": options["address"],
            "target": options.get("target", "string"),
            "key_prefix": options.get("target.key_prefix", ""),
            "key_field": options.get("target.key_column"),
        }

    def make_sink(self, config, schema: ConnectionSchema):
        return RedisSink(
            config["address"], config.get("target", "string"),
            config.get("key_prefix", ""), config.get("key_field"),
            config.get("format"),
        )

    def make_lookup(self, config) -> RedisLookup:
        return RedisLookup(config["address"], config.get("key_prefix", ""))

    def test(self, config):
        try:
            require_client("redis")
        except RuntimeError as e:
            return False, str(e)
        return True, "ok"
