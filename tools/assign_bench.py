#!/usr/bin/env python
"""Per-batch slot-assignment cost across the three directory tiers:

  python  — host dict over batch-unique (bin, key) pairs (ops/directory.py)
  native  — C++ open-addressing table (native/slotdir.cpp)
  device  — device-resident sorted hash table, jitted searchsorted
            (ops/device_directory.py, tpu.device_directory flag)

Scenario mirrors a window operator in steady state: a fixed key universe
cycling through bins — after a bin's first batch every key is a repeat
hit, which is where the device tier's "no host hash-table work" pays.
Run under JAX_PLATFORMS=cpu for the CPU number; the probe daemon's grant
workload gives the TPU number.

Usage: python tools/assign_bench.py [--rows 8192] [--keys 20000] [--iters 60]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_dir(kind):
    if kind == "python":
        from arroyo_tpu.ops.directory import SlotDirectory

        return SlotDirectory()
    if kind == "native":
        from arroyo_tpu.ops.native import NativeSlotDirectory, load_native

        mod = load_native()
        if mod is None:
            return None
        return NativeSlotDirectory(mod, n_keys=1)
    from arroyo_tpu.ops.device_directory import DeviceSlotDirectory

    return DeviceSlotDirectory(n_keys=1)


def bench(kind, rows, keys, iters):
    d = make_dir(kind)
    if d is None:
        return None
    rng = np.random.default_rng(7)
    batches = [
        (np.full(rows, i // 8, dtype=np.int64),
         rng.integers(0, keys, rows))
        for i in range(iters)
    ]
    # drain the way the window operators do: the vectorized array path
    # when the directory offers it, tuples otherwise
    drain = getattr(d, "take_bin_arrays", d.take_bin)
    # warmup: populate a bin, roll it over, drain it — compiles the
    # device lookup/merge/remove programs before the timed region
    d.assign(np.full(rows, -2, dtype=np.int64), [batches[0][1]])
    d.assign(np.full(rows, -1, dtype=np.int64), [batches[0][1]])
    drain(-2)
    drain(-1)
    t0 = time.perf_counter()
    cur_bin = None
    for bins, kc in batches:
        d.assign(bins, [kc])
        # watermark-style emission: a bin that rolled over is drained,
        # freeing its slots (keeps every tier's live set bounded, like
        # the window operators do)
        if cur_bin is not None and bins[0] != cur_bin:
            drain(cur_bin)
        cur_bin = int(bins[0])
    dt = time.perf_counter() - t0
    per_batch_us = dt / iters * 1e6
    return per_batch_us, rows * iters / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=8192)
    ap.add_argument("--keys", type=int, default=20000)
    ap.add_argument("--iters", type=int, default=60)
    args = ap.parse_args()
    for kind in ("python", "native", "device"):
        r = bench(kind, args.rows, args.keys, args.iters)
        if r is None:
            print(f"{kind:7s}  unavailable")
            continue
        us, rps = r
        print(f"{kind:7s}  {us:9.0f} us/batch   {rps / 1e6:6.2f} M rows/s")


if __name__ == "__main__":
    main()
