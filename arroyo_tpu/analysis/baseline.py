"""Committed lint baseline: grandfathered findings with justifications.

The baseline exists so a new rule can land while its pre-existing findings
are being burned down — but the project policy (ISSUE 3) is that real
findings get FIXED, so the committed file stays empty. Entries match on
(rule, path, message) — not line numbers — so code motion doesn't churn
them, and every entry must carry a human-written `justification` for
`--strict` to accept it.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from .core import Finding

DEFAULT_BASELINE = "LINT_BASELINE.json"


class Baseline:
    def __init__(self, entries: List[dict] = None):
        self.entries = list(entries or [])

    @staticmethod
    def _key(rule: str, path: str, message: str) -> Tuple[str, str, str]:
        return (rule, path.replace("\\", "/"), message)

    @classmethod
    def load(cls, path) -> "Baseline":
        p = Path(path)
        if not p.exists():
            return cls()
        data = json.loads(p.read_text() or "{}")
        return cls(data.get("findings", []))

    def save(self, path) -> None:
        Path(path).write_text(
            json.dumps(
                {"version": 1, "findings": self.entries},
                indent=2,
                sort_keys=True,
            )
            + "\n"
        )

    @classmethod
    def from_findings(cls, findings: Sequence[Finding],
                      justification: str = "TODO: justify") -> "Baseline":
        entries = [
            {
                "rule": f.rule,
                "path": f.path,
                "message": f.message,
                "justification": justification,
            }
            for f in findings
        ]
        return cls(entries)

    def split(self, findings: Sequence[Finding]):
        """Partition findings into (new, grandfathered) and report stale
        baseline entries that no longer match anything."""
        index: Dict[Tuple[str, str, str], dict] = {
            self._key(e["rule"], e["path"], e["message"]): e
            for e in self.entries
        }
        matched = set()
        new: List[Finding] = []
        old: List[Finding] = []
        for f in findings:
            k = self._key(f.rule, f.path, f.message)
            if k in index:
                matched.add(k)
                old.append(f)
            else:
                new.append(f)
        stale = [e for k, e in index.items() if k not in matched]
        return new, old, stale

    def unjustified(self) -> List[dict]:
        return [
            e for e in self.entries
            if not str(e.get("justification", "")).strip()
            or str(e.get("justification", "")).startswith("TODO")
        ]
