"""Mini layered config tree for the key-resolution fixtures."""
import dataclasses


@dataclasses.dataclass
class CheckpointConfig:
    interval: float = 10.0  # seconds between checkpoints


@dataclasses.dataclass
class PipelineConfig:
    batch_size: int = 512  # rows per source batch
    # nested checkpointing section
    checkpointing: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig
    )


@dataclasses.dataclass
class Config:
    """Sections: pipeline."""

    pipeline: PipelineConfig = dataclasses.field(default_factory=PipelineConfig)


def config() -> Config:
    return Config()


def update(**sections):
    pass
