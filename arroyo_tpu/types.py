"""Core substrate types: ids, time, watermarks, signals, checkpoint barriers.

Capability parity with the reference's `arroyo-types` crate
(/root/reference/crates/arroyo-types/src/lib.rs): Watermark (:176),
SignalMessage (:188), CheckpointBarrier (:500), TaskInfo (:391),
hash→partition range mapping (:640-661). Re-designed for a Python/JAX host
runtime: messages are lightweight dataclasses, data payloads are pyarrow
RecordBatches, and the hash-range math is vectorized with numpy so the same
partitioning is computable on host (shuffle) and on device (mesh shuffle).
"""

from __future__ import annotations

import dataclasses
import enum
import time as _time
import uuid
from typing import Optional, Union

import numpy as np

# ---------------------------------------------------------------------------
# Ids
# ---------------------------------------------------------------------------


def gen_id(prefix: str) -> str:
    return f"{prefix}_{uuid.uuid4().hex[:12]}"


@dataclasses.dataclass(frozen=True)
class JobId:
    id: str

    def __str__(self) -> str:
        return self.id


@dataclasses.dataclass(frozen=True)
class WorkerId:
    id: int

    def __str__(self) -> str:
        return str(self.id)


# ---------------------------------------------------------------------------
# Time — event time is int64 nanoseconds since the unix epoch, matching the
# reference's TimestampNanosecond `_timestamp` column.
# ---------------------------------------------------------------------------

NANOS_PER_SEC = 1_000_000_000
NANOS_PER_MILLI = 1_000_000
NANOS_PER_MICRO = 1_000


def now_nanos() -> int:
    return _time.time_ns()


def to_nanos(seconds: float) -> int:
    return int(round(seconds * NANOS_PER_SEC))


def from_nanos(nanos: int) -> float:
    return nanos / NANOS_PER_SEC


def to_millis(nanos: int) -> int:
    return nanos // NANOS_PER_MILLI


# ---------------------------------------------------------------------------
# Watermarks & signals
# ---------------------------------------------------------------------------


class WatermarkKind(enum.Enum):
    EVENT_TIME = "event_time"
    IDLE = "idle"


@dataclasses.dataclass(frozen=True)
class Watermark:
    """Event-time watermark. `IDLE` marks a quiet input that should not hold
    back the min-merge (reference: arroyo-types Watermark::Idle)."""

    kind: WatermarkKind
    timestamp: Optional[int] = None  # nanos; None for IDLE

    @staticmethod
    def event_time(ts: int) -> "Watermark":
        return Watermark(WatermarkKind.EVENT_TIME, ts)

    @staticmethod
    def idle() -> "Watermark":
        return Watermark(WatermarkKind.IDLE, None)

    def is_idle(self) -> bool:
        return self.kind == WatermarkKind.IDLE


# u64::MAX analogue: the "end of time" watermark emitted on EndOfData so that
# all windows flush (reference: watermark_generator.rs on_close).
WATERMARK_END = (1 << 63) - 1


@dataclasses.dataclass(frozen=True)
class CheckpointBarrier:
    epoch: int
    min_epoch: int
    timestamp: int  # nanos when initiated
    then_stop: bool = False
    # flight-recorder trace context (obs): the controller mints one trace
    # per epoch; span_id is rewritten at each hop (worker fan-out, subtask
    # re-broadcast) so downstream alignment spans parent to their causal
    # predecessor. Empty strings = untraced barrier (obs disabled).
    trace_id: str = ""
    span_id: str = ""

    def with_span(self, span_id: str) -> "CheckpointBarrier":
        """The barrier re-broadcast downstream, parented to this hop."""
        if not self.trace_id:
            return self
        return dataclasses.replace(self, span_id=span_id)


class SignalKind(enum.Enum):
    BARRIER = "barrier"
    WATERMARK = "watermark"
    STOP = "stop"
    END_OF_DATA = "end_of_data"
    LATENCY_MARKER = "latency_marker"


@dataclasses.dataclass(frozen=True)
class LatencyMarker:
    """Flink-style latency marker (flink FLIP-27 LatencyMarker): sources
    stamp one periodically with their wall clock; it flows through queues
    and the TCP exchange like a watermark but never blocks barrier
    alignment and never touches event time. Every operator (and the sink)
    records `now - stamp_ns` into its latency histogram, so the marker's
    transit time IS the end-to-end record latency up to that operator."""

    source_task: str  # task_id of the stamping source subtask
    seq: int
    stamp_ns: int  # wall-clock nanos at the stamping source


@dataclasses.dataclass(frozen=True)
class SignalMessage:
    """Control signals that flow *in-band* through the dataflow edges,
    interleaved with data batches (reference: arroyo-types SignalMessage)."""

    kind: SignalKind
    watermark: Optional[Watermark] = None
    barrier: Optional[CheckpointBarrier] = None
    marker: Optional[LatencyMarker] = None

    @staticmethod
    def barrier_of(b: CheckpointBarrier) -> "SignalMessage":
        return SignalMessage(SignalKind.BARRIER, barrier=b)

    @staticmethod
    def watermark_of(w: Watermark) -> "SignalMessage":
        return SignalMessage(SignalKind.WATERMARK, watermark=w)

    @staticmethod
    def marker_of(m: LatencyMarker) -> "SignalMessage":
        return SignalMessage(SignalKind.LATENCY_MARKER, marker=m)

    @staticmethod
    def stop() -> "SignalMessage":
        return SignalMessage(SignalKind.STOP)

    @staticmethod
    def end_of_data() -> "SignalMessage":
        return SignalMessage(SignalKind.END_OF_DATA)


# A message on a dataflow edge is either data (pyarrow.RecordBatch) or a
# signal. We avoid a wrapper class on the data path — isinstance dispatch on
# the hot loop is cheaper than an envelope object per batch.
ArrowMessage = Union["pyarrow.RecordBatch", SignalMessage]  # noqa: F821


# ---------------------------------------------------------------------------
# Task identity
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TaskInfo:
    job_id: str
    node_id: int  # logical node id
    operator_name: str
    task_index: int  # subtask index within the logical node
    parallelism: int

    @property
    def task_id(self) -> str:
        return f"{self.node_id}-{self.task_index}"

    def key_range(self) -> range:
        """The hash-range this subtask owns (for state sharding)."""
        lo, hi = range_for_server(self.task_index, self.parallelism)
        return range(lo, hi)


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    FULL = "full"


class StopMode(enum.Enum):
    GRACEFUL = "graceful"  # stop signal flows through the dataflow
    IMMEDIATE = "immediate"  # tear down now


# ---------------------------------------------------------------------------
# Hash-range partitioning.
#
# The u64 hash space is divided into `n` equal consecutive ranges; both the
# keyed shuffle and state sharding use the same mapping, so rescaling is a
# restore-time re-read of overlapping ranges (reference:
# arroyo-types/src/lib.rs:640-661 server_for_hash / range_for_server).
# ---------------------------------------------------------------------------

_U64 = 1 << 64


def _range_size(n: int) -> int:
    return (_U64 + n - 1) // n  # ceil(2^64 / n)


def range_for_server(i: int, n: int) -> tuple[int, int]:
    """[start, end) of the hash range owned by partition i of n."""
    size = _range_size(n)
    start = i * size
    end = _U64 if i == n - 1 else min((i + 1) * size, _U64)
    return start, end


def server_for_hash(h: int, n: int) -> int:
    return min(int(h) // _range_size(n), n - 1)


def server_for_hash_array(hashes: np.ndarray, n: int) -> np.ndarray:
    """Vectorized hash→partition mapping for a uint64 hash column."""
    if n == 1:
        return np.zeros(len(hashes), dtype=np.int64)
    size = _range_size(n)
    out = (hashes // np.uint64(size)).astype(np.int64)
    np.minimum(out, n - 1, out=out)
    return out


# ---------------------------------------------------------------------------
# Hashing of key columns. One canonical 64-bit hash used by the shuffle, the
# state key-ranges and the device-side kernels. We use the splitmix64-style
# finalizer over per-column hashes, combined with multiply-rotate; columns of
# string/binary type are hashed via pandas' vectorized siphash
# (pandas.util.hash_array) which is deterministic for a fixed hash_key.
# ---------------------------------------------------------------------------

HASH_SEED = np.uint64(0x243F6A8885A308D3)  # fixed so checkpoints are portable

_PANDAS_HASH_KEY = "arroyo_tpu_hash0"  # must be exactly 16 bytes


def _splitmix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x


def hash_arrays(columns: list[np.ndarray]) -> np.ndarray:
    """Combine pre-hashed (uint64) per-column arrays into one hash column."""
    out = np.full(len(columns[0]), HASH_SEED, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for col in columns:
            out = _splitmix64(out ^ col)
    return out


def hash_column(values) -> np.ndarray:
    """Hash one column (numpy array or list) to uint64."""
    arr = np.asarray(values)
    if arr.dtype.kind in ("i", "u", "b"):
        return _splitmix64(arr.astype(np.uint64, copy=False))
    if arr.dtype.kind == "f":
        # normalize -0.0 == 0.0 before bit-hashing
        arr = arr + 0.0
        return _splitmix64(arr.view(np.uint64) if arr.dtype == np.float64
                           else arr.astype(np.float64).view(np.uint64))
    if arr.dtype.kind == "M":  # datetime64
        return _splitmix64(arr.view("i8").astype(np.uint64))
    # only object/string columns need pandas; importing it eagerly cost
    # ~0.3s INSIDE the first shuffle of integer-keyed pipelines
    import pandas.util  # local import: pandas is heavy

    return pandas.util.hash_array(
        arr.astype(object), hash_key=_PANDAS_HASH_KEY, categorize=False
    ).astype(np.uint64)
