"""Helper for connectors whose client libraries are absent in this
environment (no egress, no broker clients baked in): the connector surface
(validation, planning, API metadata) works; operators raise a clear error
when started."""

from __future__ import annotations


def require_client(*modules: str):
    import importlib

    errors = []
    for m in modules:
        try:
            return importlib.import_module(m)
        except ImportError as e:
            errors.append(str(e))
    raise RuntimeError(
        f"this connector requires one of the client libraries {modules}, "
        f"none of which is available in this environment: {errors}"
    )
