"""Mesh-sharded window state: the multi-chip execution path.

The reference scales keyed aggregation by running parallel subtasks wired
with a TCP shuffle (/root/reference/crates/arroyo-worker/src/
network_manager.rs). The TPU-native equivalent keeps ALL key shards'
accumulator state resident on a device mesh and replaces the network
shuffle with one `jax.lax.all_to_all` over ICI inside the jitted step:

    host: rows -> (device_owner, local_slot) routing  [hash-range mapping]
    device (shard_map over 1-D "keys" mesh):
        all_to_all route rows to their owning shard -> scatter-reduce into
        the local accumulator shard
    emission: gather per-shard slots (device->host once per watermark)

One jitted step per batch; state never leaves HBM between batches. The
same `server_for_hash` ranges used by the host shuffle assign keys to
devices, so host-parallel and mesh-parallel run produce identical
partitioning.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Tuple

import numpy as np

from ..ops.aggregates import AggSpec, _neutral, _np_dtype
from ..ops.directory import SlotDirectory
from ..types import server_for_hash_array


class ShardedAccumulator:
    """Accumulator slots sharded across a 1-D device mesh; updates route
    rows to their owning device with an in-step all_to_all."""

    def __init__(self, specs: List[AggSpec], mesh, capacity_per_shard: int = 4096,
                 rows_per_shard: int = 1024):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        jax.config.update("jax_enable_x64", True)
        self.specs = specs
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_shards = mesh.devices.size
        self.capacity = capacity_per_shard  # last slot of each shard = scratch
        self.rows_per_shard = rows_per_shard
        self.phys: List[Tuple[str, str, str, int]] = []
        for si, spec in enumerate(specs):
            for op, dtype, src in spec.phys():
                self.phys.append((op, dtype, src, si))
        sharding = NamedSharding(mesh, P(self.axis, None))
        self.state = [
            jax.device_put(
                jnp.full((self.n_shards, capacity_per_shard),
                         _neutral(op, dt), dtype=_np_dtype(dt)),
                sharding,
            )
            for op, dt, _, _ in self.phys
        ]
        # per-shard host directories (bin,key)->local slot
        self.dirs = [SlotDirectory() for _ in range(self.n_shards)]
        self._step = self._make_step()

    # -- routing (host) -----------------------------------------------------

    def route(self, srcs: np.ndarray, owners: np.ndarray, bins: np.ndarray,
              key_rows: List[np.ndarray],
              cols: Dict[int, np.ndarray]) -> Tuple[np.ndarray, np.ndarray, list]:
        """Pack rows into the [src_shard, dst_shard, rows] all_to_all
        layout. Rows are attributed to source shards round-robin by the
        caller (on real multi-host hardware each device's input partition
        IS the source dimension); destination shards' host directories
        assign the local slots."""
        S, R = self.n_shards, self.rows_per_shard
        slots = np.full((S, S, R), self.capacity - 1, dtype=np.int64)
        valid = np.zeros((S, S, R), dtype=np.int64)
        vals = {
            c: np.zeros((S, S, R), dtype=v.dtype) for c, v in cols.items()
        }
        for dst in range(S):
            rows_d = np.nonzero(owners == dst)[0]
            if len(rows_d) == 0:
                continue
            local = self.dirs[dst].assign(
                bins[rows_d], [k[rows_d] for k in key_rows]
            )
            if self.dirs[dst].required_capacity() > self.capacity - 1:
                raise ValueError("shard accumulator capacity exceeded")
            for s in range(S):
                sel = srcs[rows_d] == s
                cnt = int(sel.sum())
                if cnt == 0:
                    continue
                if cnt > R:
                    raise ValueError(
                        f"route ({s}->{dst}) got {cnt} rows > "
                        f"rows_per_shard={R}"
                    )
                slots[s, dst, :cnt] = local[sel]
                valid[s, dst, :cnt] = 1
                for c in vals:
                    vals[c][s, dst, :cnt] = cols[c][rows_d][sel]
        return slots, valid, vals

    # -- jitted sharded step ------------------------------------------------

    def _make_step(self):
        import jax
        import jax.numpy as jnp
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        phys = list(self.phys)
        axis = self.axis

        def local_update(state_shards, slots, valid, *vals):
            # local views: state [1, cap]; slots/valid/vals [1, S, R] where
            # dim1 indexes the destination shard. all_to_all over the mesh
            # axis exchanges those blocks (the ICI shuffle): afterwards
            # [S, R] holds the rows every source shard sent to THIS shard.
            def exchange(x):
                return jax.lax.all_to_all(x[0], axis, 0, 0, tiled=True)

            slots_r = exchange(slots)
            valid_r = exchange(valid)
            vals_r = [exchange(v) for v in vals]
            flat_slots = slots_r.reshape(-1)
            out = []
            vi = 0
            for (op, dt, src, si), s in zip(phys, state_shards):
                row = s[0]
                if src == "one":
                    v = valid_r.reshape(-1).astype(row.dtype)
                else:
                    v = vals_r[vi].reshape(-1)
                    vi += 1
                    if op == "add":
                        v = v * valid_r.reshape(-1).astype(v.dtype)
                    else:
                        v = jnp.where(
                            valid_r.reshape(-1) > 0, v, _neutral(op, dt)
                        )
                if op == "add":
                    row = row.at[flat_slots].add(v.astype(row.dtype))
                elif op == "min":
                    row = row.at[flat_slots].min(v.astype(row.dtype))
                else:
                    row = row.at[flat_slots].max(v.astype(row.dtype))
                out.append(row[None, :])
            return tuple(out)

        n_state = len(self.phys)

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, slots, valid, *vals):
            f = shard_map(
                local_update,
                mesh=self.mesh,
                in_specs=(
                    tuple(P(axis, None) for _ in range(n_state)),
                    P(axis, None),
                    P(axis, None),
                )
                + tuple(P(axis, None) for _ in vals),
                out_specs=tuple(P(axis, None) for _ in range(n_state)),
            )
            return list(f(tuple(state), slots, valid, *vals))

        return step

    def update(self, hashes, bins, key_rows, cols):
        # the all_to_all layout holds at most rows_per_shard rows per
        # (src, dst) pair; skewed batches split into multiple steps, with
        # chunk membership assigned per bucket so no chunk overflows
        n = len(hashes)
        owners = server_for_hash_array(hashes, self.n_shards)
        srcs = np.arange(n) % self.n_shards
        bucket = srcs * self.n_shards + owners
        order = np.argsort(bucket, kind="stable")
        sorted_bucket = bucket[order]
        starts = np.searchsorted(sorted_bucket, sorted_bucket, side="left")
        pos_in_bucket = np.arange(n) - starts  # position within each bucket
        chunk_sorted = pos_in_bucket // self.rows_per_shard
        chunk = np.empty(n, dtype=np.int64)
        chunk[order] = chunk_sorted
        for c in range(int(chunk.max()) + 1 if n else 0):
            sel = chunk == c
            self._update_one(
                hashes[sel], srcs[sel], owners[sel], bins[sel],
                [k[sel] for k in key_rows],
                {col: v[sel] for col, v in cols.items()},
            )

    def _update_one(self, hashes, srcs, owners, bins, key_rows, cols):
        import jax.numpy as jnp

        slots, valid, vals = self.route(srcs, owners, bins, key_rows, cols)
        # one value array per col-sourced physical accumulator, in phys order
        ordered = [
            jnp.asarray(vals[self.specs[si].col])
            for op, dt, src, si in self.phys
            if src == "col"
        ]
        self.state = self._step(
            self.state, jnp.asarray(slots), jnp.asarray(valid), *ordered
        )

    # -- drain --------------------------------------------------------------

    def drain(self, bins: List[int]) -> Dict[int, Tuple[List[tuple], List[np.ndarray]]]:
        """Emit a set of completed bins: ONE device->host state copy for the
        whole emission cycle, then per-bin slicing; freed slots are reset on
        device (one scatter) so their reuse starts from neutral."""
        import jax.numpy as jnp

        state_np = [np.asarray(s) for s in self.state]
        out: Dict[int, Tuple[List[tuple], List[np.ndarray]]] = {}
        freed_shards: List[np.ndarray] = []
        freed_slots: List[np.ndarray] = []
        for b in bins:
            keys_out: List[tuple] = []
            per_phys: List[List[np.ndarray]] = [[] for _ in self.phys]
            for shard in range(self.n_shards):
                if not self.dirs[shard].peek_bin(b):
                    continue
                keys, slots = self.dirs[shard].take_bin(b)
                keys_out.extend(keys)
                freed_shards.append(np.full(len(slots), shard, dtype=np.int64))
                freed_slots.append(slots)
                for pi, s in enumerate(state_np):
                    per_phys[pi].append(s[shard, slots])
            out[b] = (
                keys_out,
                [
                    np.concatenate(chunks) if chunks else np.empty(0)
                    for chunks in per_phys
                ],
            )
        if freed_slots:
            sh = jnp.asarray(np.concatenate(freed_shards))
            sl = jnp.asarray(np.concatenate(freed_slots))
            self.state = [
                s.at[sh, sl].set(_neutral(op, dt))
                for s, (op, dt, _, _) in zip(self.state, self.phys)
            ]
        return out

    def gather_bin(self, b: int) -> Tuple[List[tuple], List[np.ndarray]]:
        """Single-bin convenience wrapper over drain()."""
        return self.drain([b])[b]
