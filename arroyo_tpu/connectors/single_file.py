"""Placeholder: single_file connector lands with the connector milestone."""
