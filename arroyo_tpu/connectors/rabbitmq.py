"""Placeholder: rabbitmq connector lands with the connector milestone."""
