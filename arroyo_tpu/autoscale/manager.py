"""The controller-resident autoscaler: observe -> decide -> actuate.

One `Autoscaler` lives in each ControllerServer (started when
`autoscale.enabled`). Every `autoscale.period` seconds it ticks each
RUNNING job that has durable state:

  observe   merge registry snapshots from the job's workers (GetMetrics
            rpc; embedded workers share a registry and union to one) and
            diff them into per-operator signals (signals.SignalSampler);
  decide    run the configured policy (policy.make_policy) over the job's
            topology, then gate through warmup/cooldown/pin
            (policy.ActuationGate);
  actuate   mint the `{job}/rescale-N` flight-recorder trace with the
            decision as its root span and hand the parallelism overrides
            to the controller's state-machine driver, which runs the
            proven stop-with-checkpoint -> override -> restore path
            (controller._rescale, JobState.RESCALING).

Every period appends one entry to the job's decision audit log
(JobHandle.autoscale_decisions), surfaced via
GET /api/v1/jobs/{id}/autoscale and /debug/autoscale. Jobs WITHOUT a
storage_url are observed but never actuated: rescaling them would drop
state, so exactly-once wins over elasticity.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional

from .. import obs
from ..config import config
from ..utils.logging import get_logger
from .policy import ActuationGate, Topology, make_policy
from .signals import SignalSampler, merge_snapshots

logger = get_logger("autoscale")


class _JobScaleState:
    def __init__(self, job_id: str, cfg):
        self.sampler = SignalSampler(job_id)
        self.gate = ActuationGate(cfg)
        self.gen: Optional[tuple] = None
        self.seq = 0


class Autoscaler:
    def __init__(self, controller):
        self.controller = controller
        self._jobs: Dict[str, _JobScaleState] = {}
        self._task: Optional[asyncio.Task] = None
        self.policy = make_policy(config().autoscale.policy)

    # -- lifecycle ----------------------------------------------------------

    def maybe_start(self) -> bool:
        if not config().autoscale.enabled or self._task is not None:
            return False
        self._task = asyncio.ensure_future(self._loop())
        logger.info(
            "autoscaler on: policy=%s period=%.1fs parallelism=[%d, %d]",
            config().autoscale.policy, config().autoscale.period,
            config().autoscale.min_parallelism,
            config().autoscale.max_parallelism,
        )
        return True

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None

    async def _loop(self):
        while True:
            await asyncio.sleep(config().autoscale.period)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.exception("autoscale tick failed")

    # -- control loop -------------------------------------------------------

    async def tick(self):
        """One control period over every running job."""
        from ..controller.state_machine import JobState

        for job in list(self.controller.jobs.values()):
            if job.state != JobState.RUNNING:
                continue
            try:
                await self._tick_job(job)
            except Exception:  # noqa: BLE001 - one job must not stall others
                logger.exception("autoscale tick for job %s failed",
                                 job.job_id)

    async def _tick_job(self, job):
        cfg = config().autoscale
        st = self._jobs.get(job.job_id)
        if st is None:
            st = self._jobs[job.job_id] = _JobScaleState(job.job_id, cfg)
        gen = (job.restarts, job.rescales)
        if st.gen != gen:
            # fresh topology (schedule, recovery, or our own rescale):
            # rate history is stale and counters may have restarted
            st.gen = gen
            st.sampler.reset()
            st.gate.reset(cfg.warmup_periods)
        merged = await self._job_snapshot(job)
        topo = Topology.from_graph(job.graph)
        signals = st.sampler.sample(merged, topo.current)
        st.seq += 1
        if signals is None:
            self._record(job, st, "baseline", {}, {}, {})
            return
        decision = self.policy.decide(topo, signals, cfg)
        changed = decision.changed(topo.current)
        if changed and (job.backend is None or job.rescale_requested):
            # observed-only job (no durable state) or an actuation already
            # in flight: report the demand, never actuate
            self._record(job, st, "unactuatable", changed,
                         decision.reasons, signals)
            return
        action = st.gate.check(changed, pinned=job.autoscale_pinned)
        if action != "rescale":
            self._record(job, st, action, changed, decision.reasons,
                         signals)
            return
        # multi-tenant arbitration (ROADMAP item 3): jobs sharing a
        # saturated worker pool must not all win their DS2 scale-ups —
        # clamp this decision against the pool's free slots so tenants
        # degrade gracefully instead of thrashing rescale loops
        changed, clamp_note = self._arbitrate(job, changed)
        if not changed:
            reasons = dict(decision.reasons)
            reasons["_pool"] = clamp_note or "clamped to zero headroom"
            self._record(job, st, "arbitrated", {}, reasons, signals)
            return
        if clamp_note:
            decision.reasons["_pool"] = clamp_note
        # actuate: mint the rescale trace with the decision as its root
        # span; controller._rescale (stop-checkpoint -> override ->
        # restore) and the subsequent schedule parent under it, so the
        # whole rescale reads as ONE connected tree in the flight recorder
        with obs.span(
            "autoscale.decide",
            trace=obs.new_trace(job.job_id, f"rescale-{job.rescales + 1}"),
            cat="autoscale", job=job.job_id,
            targets=str(changed), reasons=str(decision.reasons)[:300],
        ) as sp:
            job.rescale_trace = (sp.trace_id, sp.span_id)
        self._record(job, st, "rescale", changed, decision.reasons, signals)
        logger.info("autoscale: job %s rescaling %s (%s)", job.job_id,
                    changed, decision.reasons)
        job.rescale_requested = dict(changed)

    def _arbitrate(self, job, changed: Dict[int, int]):
        """Clamp a rescale decision against the shared pool's free slots
        (Flink slot-sharing accounting: a job's slot need is its max
        operator parallelism). Jobs keep what they hold; a scale-up may
        grow a job's max parallelism by at most the pool's free slots.
        Returns (possibly-clamped targets, note-or-None); empty targets
        mean the decision was arbitrated down to a no-op."""
        ctrl = self.controller
        admission = getattr(ctrl, "admission", None)
        if (admission is None or not ctrl._pool_mode()
                or not config().admission.enabled
                or admission.capacity() <= 0):
            return changed, None
        current = {n.node_id: n.parallelism
                   for n in job.graph.nodes.values()}
        cur_slots = max(current.values(), default=1)
        new_slots = max(
            [changed.get(nid, p) for nid, p in current.items()], default=1
        )
        allowed = cur_slots + max(admission.free_slots(), 0)
        if new_slots <= allowed:
            return changed, None
        clamped = {
            nid: min(t, allowed)
            for nid, t in changed.items()
            if min(t, allowed) != current.get(nid)
        }
        note = (f"scale-up clamped to {allowed} slots "
                f"({admission.free_slots()} free in the shared pool)")
        return clamped, note

    async def _job_snapshot(self, job) -> Dict[str, Dict[tuple, object]]:
        """Union of the workers' registry snapshots; falls back to this
        process's registry when no worker answers (pure-embedded runs).
        When the controller watchtower's scrape pump (ISSUE 13) holds a
        fresh remote merge, reuse it instead of a second GetMetrics
        round per control period."""
        wt = getattr(self.controller, "watchtower", None)
        if wt is not None:
            snap = wt.fresh_remote_snapshot(
                max_age=float(config().autoscale.period))
            if snap:
                return snap
        snaps = []
        for w in list(job.workers):
            try:
                resp = await asyncio.wait_for(
                    w.client.call("WorkerGrpc", "GetMetrics", {}), 5.0
                )
                snaps.append(resp.get("snapshot") or {})
            except Exception as e:  # noqa: BLE001 - dead/slow worker
                logger.debug("autoscale: GetMetrics from worker %s "
                             "failed: %s", w.worker_id, e)
        if not snaps:
            from ..metrics import REGISTRY

            snaps = [REGISTRY.snapshot()]
        return merge_snapshots(snaps)

    def _record(self, job, st: _JobScaleState, action: str,
                changed: Dict[int, int], reasons: Dict[int, str],
                signals: dict) -> None:
        cfg = config().autoscale
        entry = {
            "time": time.time(),
            "seq": st.seq,
            "action": action,
            "restarts": job.restarts,
            "rescales": job.rescales,
            "pinned": job.autoscale_pinned,
            "current": {
                n.node_id: n.parallelism for n in job.graph.nodes.values()
            },
            "targets": dict(changed),
            "reasons": dict(reasons),
            "signals": {
                nid: s.summary() for nid, s in (signals or {}).items()
            },
        }
        job.autoscale_decisions.append(entry)
        del job.autoscale_decisions[:-cfg.decision_history]

    def status(self) -> dict:
        """/debug/autoscale payload: per-job decision history."""
        return {
            "enabled": bool(config().autoscale.enabled
                            and self._task is not None),
            "policy": config().autoscale.policy,
            "period": config().autoscale.period,
            "jobs": {
                job.job_id: {
                    "state": job.state.value,
                    "pinned": job.autoscale_pinned,
                    "rescales": job.rescales,
                    "decisions": list(job.autoscale_decisions),
                }
                for job in self.controller.jobs.values()
            },
        }
