"""Multi-tenant control plane (ISSUE 10): worker multiplexing, the
event-driven controller, admission control, and metrics cardinality GC.

The fast tier proves the tentpole invariants at small scale: ~25
concurrent tiny impulse pipelines multiplexed onto a 2-worker shared
pool with create/stop churn and one mid-run worker SIGKILL, every
surviving job's output byte-identical to its solo run; a parked RUNNING
job costs ZERO controller wakeups over a poll interval; terminal jobs'
metric series are dropped so churn can't grow /metrics unboundedly; the
admission queue grants fair-share across tenants. The slow tier scales
the churn harness to 200 jobs."""

import asyncio
import json
import subprocess
import sys
import time
from types import SimpleNamespace

import pytest

from arroyo_tpu.config import update
from arroyo_tpu.controller.controller import ControllerServer, TimerWheel
from arroyo_tpu.controller.scheduler import (
    EmbeddedScheduler,
    multiplexing_active,
)
from arroyo_tpu.controller.state_machine import JobState


def bounded_sql(tmp, tag, j, n=3000, rate=1_000_000, realtime=False):
    """Deterministic event-time pipeline (byte-identical across runs).
    `realtime` uses the impulse REPLAY mode (wall-paced arrival,
    synthetic timestamps): a slow wall-paced fleet run and a fast solo
    run produce the same bytes, so churn/kills can land mid-run."""
    rt = ", realtime = 'true', replay = 'true'" if realtime else ""
    return f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '{rate}',
      message_count = '{n}', start_time = '0'{rt}
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{tmp}/{tag}-{j}.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % 8 as k, tumble(interval '1 millisecond') as w,
             count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """


def parked_sql(tmp, j):
    return f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '0.05',
      message_count = '1000000', start_time = '0', realtime = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{tmp}/parked-{j}.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % 4 as k, tumble(interval '1 second') as w,
             count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """


def canonical(path):
    import os

    if not os.path.exists(path):
        return []
    with open(path) as f:
        return sorted(
            json.dumps(json.loads(line), sort_keys=True)
            for line in f if line.strip()
        )


def test_multiplexing_gates():
    """Multiplexing engages for embedded/process under the controller-
    resident job control loop, and falls back for worker-leader mode,
    multi-process meshes, other schedulers, and the off switch."""
    assert multiplexing_active("embedded")
    assert multiplexing_active("process")
    assert not multiplexing_active("node")
    assert not multiplexing_active("manual")
    with update(cluster={"multiplexing": "off"}):
        assert not multiplexing_active("embedded")
    with update(controller={"job_controller_mode": "worker"}):
        assert not multiplexing_active("embedded")
    with update(tpu={"mesh_processes": 2}):
        assert not multiplexing_active("process")


def test_multiplexed_fleet_exactly_once(tmp_path):
    """~25 tiny durable pipelines share a 2-worker pool under create/stop
    churn and one mid-run worker SIGKILL; every job that ran to
    completion produces output byte-identical to its solo run (the
    exactly-once machinery holds per job while co-scheduled)."""
    N = 25

    async def fleet():
        with update(
            # 1s cadence: 25 durable jobs checkpoint 25x/s at 0.5s on this
            # one-core host, which saturates the loop into heartbeat noise
            pipeline={"checkpointing": {"interval": 1.0}},
            cluster={"worker_pool_size": 2, "metrics_ttl": 0.0},
            # generous heartbeat window: 25 starting jobs can stall the
            # shared event loop for seconds on this host, and spurious
            # timeouts would burn restart budget (the registry self-heals
            # either way, but churn is noise here)
            controller={"heartbeat_timeout": 6.0},
            # slots sized for tiny-job density: 25 one-slot jobs need 13
            # slots per pool worker to all be admitted CONCURRENTLY
            worker={"heartbeat_interval": 0.2, "task_slots": 16},
        ):
            sched = EmbeddedScheduler()
            c = await ControllerServer(sched, max_restarts=8).start()
            # replay-mode impulse stretches each job past the kill while
            # event time stays deterministic (byte-identical output)
            for j in range(N):
                await c.submit_job(
                    f"fl{j}",
                    sql=bounded_sql(tmp_path, "fleet", j, n=3000,
                                    rate=700, realtime=True),
                    storage_url=str(tmp_path / f"ck-{j}"),
                    n_workers=2, parallelism=1,
                    tenant=f"t{j % 3}",
                )
            # every job multiplexed onto the same 2 pool workers
            await asyncio.sleep(0.1)
            assert len(sched.pool) == 2
            for jid in (f"fl{j}" for j in range(N)):
                await c.wait_for_state(jid, JobState.RUNNING,
                                       JobState.FINISHED, JobState.FAILED,
                                       timeout=60)
            hosted = {
                w.worker_id: len(w._jobs)
                for w, _t in sched.pool
            }
            # churn: stop a few jobs mid-run (their partial output is not
            # compared; the point is that co-resident jobs don't notice)
            stopped = {f"fl{j}" for j in range(0, N, 7)}
            for jid in stopped:
                await c.stop_job(jid, "immediate")
            # one mid-run SIGKILL-equivalent on a pool worker: every job
            # with subtasks there recovers independently from checkpoints
            await asyncio.sleep(1.0)
            victim = next(
                w for w, _t in sched.pool
                if not getattr(w, "_shutdown_started", False)
            )
            await victim.shutdown()
            for j in range(N):
                state = await c.wait_for_state(
                    f"fl{j}", JobState.FINISHED, JobState.STOPPED,
                    JobState.FAILED, timeout=120,
                )
                if f"fl{j}" not in stopped:
                    assert state == JobState.FINISHED, (
                        f"fl{j}: {state} ({c.jobs[f'fl{j}'].failure})"
                    )
            await c.stop()
            return hosted, stopped

    hosted, stopped = asyncio.run(fleet())
    # multiplexing really happened: each pool worker hosted many jobs
    assert all(n >= N // 2 for n in hosted.values()), hosted

    async def solo(j):
        with update(pipeline={"checkpointing": {"interval": 0.5}},
                    cluster={"worker_pool_size": 2}):
            c = await ControllerServer(EmbeddedScheduler()).start()
            # IDENTICAL query to the fleet run (event_rate shapes the
            # synthetic timestamps, so it must match): replay mode makes
            # the solo bytes independent of wall-clock conditions
            await c.submit_job(
                f"solo{j}",
                sql=bounded_sql(tmp_path, "solo", j, n=3000, rate=700,
                                realtime=True),
                storage_url=str(tmp_path / f"solo-ck-{j}"),
                n_workers=2, parallelism=1,
            )
            state = await c.wait_for_state(
                f"solo{j}", JobState.FINISHED, JobState.FAILED, timeout=60
            )
            await c.stop()
            return state

    # byte-identical vs solo for a sample of the completed jobs (every
    # job ran the same deterministic impulse; three cover the placement
    # spread without tripling fast-tier runtime)
    for j in (1, 2, 3):
        assert f"fl{j}" not in stopped
        assert asyncio.run(solo(j)) == JobState.FINISHED
        fleet_rows = canonical(tmp_path / f"fleet-{j}.json")
        solo_rows = canonical(tmp_path / f"solo-{j}.json")
        assert fleet_rows and fleet_rows == solo_rows, f"job fl{j} differs"


def test_parked_running_job_zero_wakeups(tmp_path):
    """Satellite regression: a parked RUNNING job (trickle source, no
    cadence due, nothing finishing) must cost ZERO controller driver
    wakeups over a poll interval — the old loops burned one per 20 ms
    per caller. A wait_for_state watcher parks alongside without
    polling either."""

    async def go():
        with update(cluster={"worker_pool_size": 1}):
            c = await ControllerServer(EmbeddedScheduler()).start()
            await c.submit_job(
                "parked", sql=parked_sql(tmp_path, 0), n_workers=1
            )
            await c.wait_for_state("parked", JobState.RUNNING, timeout=30)
            # a state watcher parks on the same per-job kick list
            watcher = asyncio.ensure_future(
                c.wait_for_state("parked", JobState.STOPPED, timeout=30)
            )
            await asyncio.sleep(0.5)  # let startup events settle
            job = c.jobs["parked"]
            before = job.wakeups
            await asyncio.sleep(1.0)  # 50 wakeups under the old 50 Hz loop
            delta = job.wakeups - before
            await c.stop_job("parked", "immediate")
            await c.wait_for_state("parked", JobState.STOPPED, timeout=30)
            await watcher
            await c.stop()
            return delta

    assert asyncio.run(go()) == 0


def test_metrics_cardinality_gc(tmp_path):
    """Satellite: churning N jobs must return /metrics exposition to
    ~baseline — per-job series (task counters, queue gauges with weakref
    refreshers, latency histograms, and the ISSUE 11
    arroyo_job_attributed_* attribution families) are dropped at
    terminal states, and the observatory side state (trace-ring spans,
    timeline phase instants, attribution accumulators) is expunged on
    the same path."""
    from arroyo_tpu import obs
    from arroyo_tpu.metrics import REGISTRY
    from arroyo_tpu.obs import attribution, timeline

    async def churn(tag, n):
        with update(cluster={"worker_pool_size": 2, "metrics_ttl": 0.0}):
            c = await ControllerServer(EmbeddedScheduler()).start()
            for j in range(n):
                await c.submit_job(
                    f"{tag}{j}",
                    sql=bounded_sql(tmp_path, tag, j, n=1500),
                    n_workers=2,
                )
            # serving-tier GC (ISSUE 12): mint job-labeled
            # arroyo_serve_* series + gateway routing/cache state for
            # every churned job so the assertions below prove the serve
            # tier rides the same expunge path as the rest
            for j in range(n):
                await c.serve.read(f"{tag}{j}", "tumbling_window", [0])
            # conservation-ledger GC (ISSUE 19): mint a reconciler and
            # its job-labeled arroyo_audit_* series per churned job —
            # expunged with the job, same path
            from arroyo_tpu.obs import audit
            for j in range(n):
                audit.reconciler(f"{tag}{j}").reconcile(
                    1, {"t": {"tx": {"e": [1, 2]}, "rx": {"e": [1, 2]},
                              "ops": {}, "flow": {}}},
                )
            # replica-tier GC (ISSUE 20): mint the job-labeled
            # arroyo_replica_* families (tail counts, served-epoch /
            # lag gauges) per churned job — Registry.drop_job on the
            # expunge path must take them with the rest (these bounded
            # jobs finish before a follower could mount, so the series
            # are minted directly like the audit ones above)
            from arroyo_tpu.metrics import (
                REPLICA_LAG_EPOCHS,
                REPLICA_SERVED_EPOCH,
                REPLICA_TAILS,
            )
            for j in range(n):
                REPLICA_TAILS.labels(job=f"{tag}{j}").inc()
                REPLICA_SERVED_EPOCH.labels(job=f"{tag}{j}").set(1.0)
                REPLICA_LAG_EPOCHS.labels(job=f"{tag}{j}").set(0.0)
            for j in range(n):
                await c.wait_for_state(
                    f"{tag}{j}", JobState.FINISHED, JobState.FAILED,
                    timeout=60,
                )
            await c.stop()

    asyncio.run(churn("warm", 1))  # register every family once
    # the warm job actually exercised the attribution families (they are
    # part of the baseline length being asserted below), and the serve
    # read minted job-labeled arroyo_serve_* series
    assert "arroyo_job_attributed_busy_seconds" in REGISTRY.expose()
    assert "arroyo_serve_requests_total" in REGISTRY.expose()
    assert "arroyo_audit_epochs_reconciled_total" in REGISTRY.expose()
    assert "arroyo_replica_tails_total" in REGISTRY.expose()
    baseline = len(REGISTRY.expose())
    asyncio.run(churn("gc", 6))
    after = len(REGISTRY.expose())
    # families/help text persist; per-job series must not accumulate
    assert after <= baseline * 1.25 + 2000, (baseline, after)
    # and the dropped jobs are really gone from the exposition — the
    # attributed families included
    text = REGISTRY.expose()
    assert 'job="gc0"' not in text and 'job="gc5"' not in text
    # the serve families are job-labeled too: Registry.drop_job took the
    # per-job serve series (request counts, cache hits) with the rest
    from arroyo_tpu.obs import audit
    for j in range(6):
        # spans of torn-down jobs no longer linger until ring overwrite
        assert obs.recorder().snapshot(trace_prefix=f"gc{j}/") == []
        assert timeline.snapshot(f"gc{j}") == []
        assert f"gc{j}" not in attribution.ACCOUNTING.summary()["jobs"]
        # the job's conservation reconciler went with it too
        assert audit.peek(f"gc{j}") is None


def _stub_admission(slots_per_worker=2, n_workers=2):
    from arroyo_tpu.controller.admission import AdmissionController

    workers = {
        i: SimpleNamespace(worker_id=i, slots=slots_per_worker,
                           pooled=True, last_heartbeat=time.monotonic())
        for i in range(n_workers)
    }
    ctl = SimpleNamespace(
        workers=workers,
        wheel=TimerWheel(),
        _pool_mode=lambda: True,
        _worker_stale=lambda w: False,
    )
    return AdmissionController(ctl), ctl


def _job(jid, tenant, par=2):
    return SimpleNamespace(
        job_id=jid, tenant=tenant,
        graph=SimpleNamespace(nodes={0: SimpleNamespace(parallelism=par)}),
    )


def test_admission_fair_share_and_quota():
    """Fair slot scheduling: grants go to the tenant holding the least,
    not to the longest-queued; a tenant at quota waits while others are
    admitted; queue timeouts surface as TimeoutError."""

    async def go():
        adm, ctl = _stub_admission()  # capacity 4
        ctl.wheel.start()
        try:
            await adm.acquire(_job("a1", "a"))   # holds 2
            await adm.acquire(_job("a2", "a"))   # holds 4 -> full
            assert adm.free_slots() == 0
            # tenant a queues FIRST, tenant b second
            qa = asyncio.ensure_future(adm.acquire(_job("a3", "a")))
            await asyncio.sleep(0.05)
            qb = asyncio.ensure_future(adm.acquire(_job("b1", "b")))
            await asyncio.sleep(0.05)
            assert not qa.done() and not qb.done()
            adm.release(_job("a1", "a"))  # 2 slots free
            await asyncio.sleep(0.05)
            # fair share: b (holding 0) wins over the earlier-queued a
            assert qb.done() and not qa.done()
            adm.release(_job("b1", "b"))
            await asyncio.sleep(0.05)
            assert qa.done()
            # quota: a tenant at tenant_quota_slots queues despite free
            with update(admission={"tenant_quota_slots": 2}):
                adm2, ctl2 = _stub_admission(slots_per_worker=4)
                ctl2.wheel.start()
                try:
                    await adm2.acquire(_job("q1", "a"))
                    assert adm2.free_slots() >= 2
                    blocked = asyncio.ensure_future(
                        adm2.acquire(_job("q2", "a"))
                    )
                    await asyncio.sleep(0.05)
                    assert not blocked.done()  # at quota
                    await adm2.acquire(_job("q3", "b"))  # other tenant ok
                    adm2.release(_job("q1", "a"))
                    await asyncio.sleep(0.05)
                    assert blocked.done()
                finally:
                    await ctl2.wheel.stop()
            # timeout: a job that never fits fails with TimeoutError
            with update(admission={"queue_timeout": 0.2}):
                adm3, ctl3 = _stub_admission()
                ctl3.wheel.start()
                try:
                    await adm3.acquire(_job("t1", "a", par=4))  # all slots
                    with pytest.raises(TimeoutError):
                        await adm3.acquire(_job("t2", "b", par=4))
                finally:
                    await ctl3.wheel.stop()
        finally:
            await ctl.wheel.stop()

    asyncio.run(go())


def test_admission_bootstrap_and_oversized():
    """Progress guarantees: the first job is admitted before any worker
    registered (capacity 0 — acquire precedes pool spawn), and a job
    larger than total capacity runs alone rather than wedging."""

    async def go():
        adm, ctl = _stub_admission(n_workers=0)
        ctl.wheel.start()
        try:
            await adm.acquire(_job("boot", "a", par=8))  # capacity 0
            assert "boot" in adm.held
        finally:
            await ctl.wheel.stop()
        adm2, ctl2 = _stub_admission()  # capacity 4
        ctl2.wheel.start()
        try:
            await adm2.acquire(_job("big", "a", par=64))
            assert adm2.held["big"][1] <= adm2.capacity()
        finally:
            await ctl2.wheel.stop()

    asyncio.run(go())


@pytest.mark.slow
def test_fleet_harness_200_jobs(tmp_path):
    """Slow tier: the churn harness at 200 concurrent jobs on one
    controller + 2-worker pool, exactly-once sample intact."""
    out = subprocess.run(
        [sys.executable, "tools/fleet_harness.py", "--jobs", "200",
         "--pool", "2", "--sample", "4", "--churn", "20",
         "--idle-seconds", "8", "--workdir", str(tmp_path)],
        capture_output=True, text=True, timeout=800, cwd="/root/repo",
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["fleet_jobs_per_controller"] >= 200
    assert report["fleet_exactly_once_ok"] == 1
