"""StandbyManager: arm, tail, and promote hot-standby generations.

Lifecycle per durable job (all driven off the controller's event loop):

  arm      — pick pool workers (preferring ones NOT hosting the primary),
             send the PR 15 staged StartExecution with `standby: true`:
             runners spawn and restore table state from the last published
             manifest under the PRIMARY's generation (read-only — claiming
             a generation at arm time would fence the primary!), but every
             operator's on_start defers until promotion. The standby's
             data namespace uses ordinal `job.schedules + 1` WITHOUT
             bumping the job's counter — the serving tier keeps routing by
             the primary's namespace until promotion syncs it.

  tail     — on each manifest publish, ship the new epoch to the standby
             workers; they replay only the delta-chain SUFFIX onto the
             open tables (TableManager.tail_chains), staying within one
             epoch of the primary at delta cost, not restore cost.

  promote  — on heartbeat loss: claim a FRESH generation (re-resolving
             the LATEST published manifest — see the
             promote_while_primary_alive model mutant), catch the standby
             up to it, ship the new generation + release the gates
             (StartProcessing{promote}), and swap the controller's job
             bookkeeping. RUNNING stays RUNNING: no SCHEDULING pass. The
             fenced zombie primary cannot publish (generation CAS) and
             its straggler workers get a best-effort StopJob.

  discard  — on recovery/rescale/stop/expunge, or when the standby itself
             fails: tear the staged runtimes down (staged_only — a worker
             hosting BOTH primary and standby keeps the primary) and
             re-arm later. Promotion storms (a poisoned job failing over
             repeatedly) fall back to cold recovery, whose restart budget
             bounds them.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

from .. import obs
from ..analysis.model.effects import protocol_effect
from ..analysis.races.sanitizer import set_task_root
from ..config import config
from ..state.backend import StateBackend
from ..utils.logging import get_logger

logger = get_logger("failover")

# promotions within this window before refusing and falling back to cold
# recovery (which consumes the bounded restart budget)
_STORM_WINDOW = 60.0
_STORM_LIMIT = 3
_REARM_BACKOFF = 5.0


class _Standby:
    """One armed standby incarnation's controller-side record."""

    def __init__(self, workers: list, assignments: dict, counts: dict,
                 ns_ordinal: int, epoch: int):
        self.workers = workers
        self.assignments = assignments
        self.counts = counts
        # data-plane namespace ordinal reserved for this incarnation
        # (job.schedules + 1 at arm time; promotion syncs the counter)
        self.ns_ordinal = ns_ordinal
        self.epoch = epoch  # highest manifest epoch tailed so far
        self.armed_at = time.monotonic()
        self.promoting = False


class StandbyManager:
    def __init__(self, ctrl):
        self.ctrl = ctrl
        self._standbys: Dict[str, _Standby] = {}
        self._arm_tasks: Dict[str, asyncio.Task] = {}
        self._tail_tasks: Dict[str, asyncio.Task] = {}
        self._tail_pending: Dict[str, int] = {}
        self._next_arm: Dict[str, float] = {}
        self._grace_until: Dict[str, float] = {}
        self._promote_times: Dict[str, List[float]] = {}
        self._discard_tasks: set = set()  # retained: GC'd mid-teardown
        self.promotions = 0

    # -- eligibility / arming ------------------------------------------------

    def eligible(self, job) -> bool:
        cfg = config()
        return (
            cfg.failover.enabled
            and job.backend is not None
            and job.mount is None  # tenants ride their host's data plane
            # worker-leader cadence owns publish on the primary; the
            # interaction with a promoted generation is unmodeled — skip
            and cfg.controller.job_controller_mode != "worker"
            and self.ctrl._pool_mode()
            and bool(job.workers)
            and all(w.pooled for w in job.workers)
            and not job.stop_requested
        )

    def note_running(self, job):
        """Called on every _run pass: keep exactly one standby armed (or
        one arm attempt in flight) per eligible job. Cheap no-op guard on
        the non-failover path."""
        if not self.eligible(job):
            return
        jid = job.job_id
        if jid in self._standbys or jid in self._arm_tasks:
            return
        if time.monotonic() < self._next_arm.get(jid, 0.0):
            return
        self._arm_tasks[jid] = asyncio.ensure_future(self._arm_guard(job))

    def wake_deadline(self, job) -> Optional[float]:
        """A timer-wheel horizon for _run's park: when an eligible job has
        no standby (arm failed and is backing off), wake at the backoff
        deadline so re-arming isn't starved on a quiet job."""
        if not self.eligible(job):
            return None
        jid = job.job_id
        if jid in self._standbys or jid in self._arm_tasks:
            return None
        return max(time.monotonic(), self._next_arm.get(jid, 0.0)) + 0.05

    async def _arm_guard(self, job):
        jid = job.job_id
        set_task_root(f"failover-arm:{jid}")
        try:
            await self._arm(job)
        except Exception as e:  # noqa: BLE001 - arming is best-effort
            logger.warning("standby arm for %s failed: %r", jid, e)
            self._next_arm[jid] = time.monotonic() + _REARM_BACKOFF
        finally:
            self._arm_tasks.pop(jid, None)
            job.kick()

    @protocol_effect("failover.arm")
    async def _arm(self, job):
        """Stage a standby incarnation: restore runs NOW under the
        primary's generation (read-only), sources and on_start park until
        promotion."""
        ctrl = self.ctrl
        n = len(job.workers)
        live = ctrl._live_pool_workers()
        others = sorted(
            (w for w in live if w not in job.workers),
            key=lambda w: (sum(w.assigned.values()), w.worker_id),
        )
        primary = [w for w in live if w in job.workers]
        # prefer disjoint placement (a primary kill should not take the
        # standby with it); co-locate only when the pool is too small —
        # the standby-also-dies drill covers that fate
        chosen = (others + primary)[:n]
        if len(chosen) < n:
            raise RuntimeError(
                f"only {len(chosen)}/{n} live pool workers for standby"
            )
        assignments, counts = ctrl._assign_subtasks(job, chosen)
        epoch = int(job.published_epoch or 0)
        ns_ordinal = job.schedules + 1
        req = ctrl._start_request(job, chosen, assignments)
        req["staged"] = True
        req["standby"] = True
        req["data_ns"] = f"{job.job_id}@{ns_ordinal}"
        req["restore_epoch"] = epoch or None
        with obs.span(
            "failover.arm",
            trace=obs.new_trace(job.job_id, "standby"),
            cat="controller", job=job.job_id,
            epoch=epoch, workers=[w.worker_id for w in chosen],
            disjoint=all(w not in job.workers for w in chosen),
        ):
            started = []
            try:
                for w in chosen:
                    await ctrl._worker_call(
                        w, "WorkerGrpc", "StartExecution", req
                    )
                    started.append(w)
            except Exception:
                await self._stop_staged(job.job_id, started)
                raise
        self._standbys[job.job_id] = _Standby(
            chosen, assignments, counts, ns_ordinal, epoch
        )
        logger.info(
            "standby armed for %s at epoch %d on workers %s",
            job.job_id, epoch, [w.worker_id for w in chosen],
        )

    # -- tailing -------------------------------------------------------------

    def note_publish(self, job):
        """Called after each manifest publish: schedule a (coalesced) tail
        of the new epoch onto the standby."""
        jid = job.job_id
        sb = self._standbys.get(jid)
        if sb is None or sb.promoting:
            return
        target = int(job.published_epoch or 0)
        if target <= sb.epoch:
            return
        self._tail_pending[jid] = max(self._tail_pending.get(jid, 0), target)
        if jid not in self._tail_tasks:
            self._tail_tasks[jid] = asyncio.ensure_future(
                self._tail_guard(job)
            )

    async def _tail_guard(self, job):
        jid = job.job_id
        set_task_root(f"failover-tail:{jid}")
        try:
            while True:
                sb = self._standbys.get(jid)
                target = self._tail_pending.get(jid)
                if sb is None or sb.promoting or target is None \
                        or target <= sb.epoch:
                    return
                await self._tail(job, sb, target)
        except Exception as e:  # noqa: BLE001 - a broken standby re-arms
            logger.warning(
                "standby tail for %s failed: %r; discarding", jid, e
            )
            await self.discard(job)
            self._next_arm[jid] = time.monotonic() + _REARM_BACKOFF
        finally:
            self._tail_tasks.pop(jid, None)

    @protocol_effect("failover.tail")
    async def _tail(self, job, sb: _Standby, target: int):
        with obs.span(
            "failover.tail",
            trace=obs.new_trace(job.job_id, "standby"),
            cat="controller", job=job.job_id,
            from_epoch=sb.epoch, epoch=target,
        ):
            for w in sb.workers:
                await self.ctrl._worker_call(
                    w, "WorkerGrpc", "TailCheckpoint",
                    {"job_id": job.job_id, "epoch": target},
                    timeout=60.0,
                )
        sb.epoch = target

    # -- promotion -----------------------------------------------------------

    async def try_promote(self, job) -> bool:
        """Attempt standby promotion instead of cold recovery. Returns
        True when the job is RUNNING again on the promoted generation;
        False (after discarding the standby) means the caller proceeds to
        the normal RECOVERING path."""
        jid = job.job_id
        sb = self._standbys.get(jid)
        if sb is None or sb.promoting or not config().failover.enabled:
            return False
        times = [
            t for t in self._promote_times.get(jid, [])
            if time.monotonic() - t < _STORM_WINDOW
        ]
        if len(times) >= _STORM_LIMIT:
            logger.warning(
                "job %s: %d promotions in %.0fs — falling back to cold "
                "recovery (restart budget applies)",
                jid, len(times), _STORM_WINDOW,
            )
            await self.discard(job)
            return False
        if any(self.ctrl._worker_stale(w) for w in sb.workers):
            # the standby died with the primary (co-located, or a host
            # fault): cold restore is the only path
            logger.warning("job %s: standby workers stale; discarding", jid)
            await self.discard(job)
            return False
        sb.promoting = True
        detect_at = time.monotonic()
        tail_task = self._tail_tasks.get(jid)
        if tail_task is not None:
            # let an in-flight tail settle; promotion re-tails anyway
            await asyncio.gather(tail_task, return_exceptions=True)
        try:
            # flight recorder: each promotion is its own lifecycle trace
            # (like job.recover) carrying the measured gap_ms — the drill
            # and the README worked example both read it from here
            with obs.span(
                "failover.promote",
                trace=obs.new_trace(jid, f"promote-{job.promotions + 1}"),
                cat="controller", job=jid,
                standby_epoch=sb.epoch, failure=str(job.failure or ""),
            ) as sp:
                await asyncio.wait_for(
                    self._promote(job, sb, detect_at, sp),
                    timeout=config().failover.promote_timeout,
                )
        except Exception as e:  # noqa: BLE001 - fall back to cold restore
            logger.warning(
                "job %s: standby promotion failed (%r); falling back to "
                "cold recovery", jid, e,
            )
            await self.discard(job)
            return False
        self._standbys.pop(jid, None)
        self._tail_pending.pop(jid, None)
        times.append(time.monotonic())
        self._promote_times[jid] = times
        self._grace_until[jid] = (
            time.monotonic() + config().failover.grace
        )
        self.promotions += 1
        job.promotions += 1
        if config().failover.rearm:
            # the next _run pass re-arms a fresh standby via note_running
            self._next_arm[jid] = time.monotonic() + _REARM_BACKOFF
        return True

    @protocol_effect("failover.promote")
    async def _promote(self, job, sb: _Standby, detect_at: float, sp):
        ctrl = self.ctrl
        # claim the FRESH generation, re-resolving the LATEST published
        # manifest. This is THE invariant the promote_while_primary_alive
        # model mutant violates: promoting at the standby's tailed epoch
        # (sb.epoch) would rewind behind an epoch a merely-slow primary
        # already published + committed, re-emitting visible output.
        newb = await asyncio.to_thread(
            lambda: StateBackend(job.storage_url, job.job_id).initialize()
        )
        target = int(newb.restore_epoch or 0)
        sp.set(restore_epoch=target, generation=newb.generation)
        # data-plane fence BEFORE releasing the standby: storage is
        # fenced by the generation CAS, but file sinks append outside it
        # — an alive-but-silent zombie (heartbeat blackout) writing after
        # the standby truncates to the checkpointed offset would
        # double-emit. A dead worker refuses the connection in
        # milliseconds, so the common (actually-dead) case stays well
        # under the gap budget; co-located workers are never in this set
        # (they host the standby too).
        old_workers = [w for w in job.workers if w not in sb.workers]
        for w in old_workers:
            try:
                await ctrl._worker_call(
                    w, "WorkerGrpc", "StopJob",
                    {"job_id": job.job_id, "force": True},
                    timeout=1.0,
                )
            except Exception as e:  # noqa: BLE001 - usually dead
                logger.debug("pre-promote StopJob to %s failed: %s",
                             w.worker_id, e)
        # release the standby: adopt the new generation, catch up the tail
        # to the latest manifest, run on_start on the tailed tables, go
        for w in sb.workers:
            await ctrl._worker_call(
                w, "WorkerGrpc", "StartProcessing",
                {"job_id": job.job_id, "promote": True,
                 "generation": newb.generation,
                 "tail_epoch": target or None},
                timeout=config().failover.promote_timeout,
            )
        gap_ms = round((time.monotonic() - detect_at) * 1e3, 3)
        # controller bookkeeping swap (mirrors _overlap_activate)
        for w in job.workers:
            w.assigned.pop(job.job_id, None)
        job.backend = newb
        job.workers = list(sb.workers)
        job.assignments = dict(sb.assignments)
        for w in job.workers:
            w.assigned[job.job_id] = sb.counts.get(w.worker_id, 0)
        # sync the namespace counter to the standby's reserved ordinal —
        # serve routing and straggler fencing now point at the promoted
        # incarnation
        job.schedules = sb.ns_ordinal
        job.checkpoints.clear()
        job.pending_epochs.clear()
        job.finished_tasks.clear()
        job.undrained_sources.clear()
        job.failure = None
        job.leader_resigned = False
        job.epoch = max(job.epoch, target)
        job.published_epoch = max(job.published_epoch, target)
        # prune dead handles from the registry so the scheduler replaces
        # them (the fence RPC above already stopped live stragglers)
        for w in old_workers:
            if ctrl._worker_stale(w) and w.worker_id in ctrl.workers:
                if ctrl.workers.pop(w.worker_id, None) is not None:
                    ctrl._benched[w.worker_id] = w
        sp.set(gap_ms=gap_ms, workers=len(job.workers),
               promoted_ns=sb.ns_ordinal)
        logger.info(
            "job %s promoted standby (gen %s, epoch %d) in %.1fms",
            job.job_id, newb.generation, target, gap_ms,
        )

    # -- discard / hooks -----------------------------------------------------

    async def discard(self, job_or_id):
        """Tear down a job's standby (if any): staged-only StopJob so a
        worker hosting BOTH primary and standby keeps the primary."""
        jid = getattr(job_or_id, "job_id", job_or_id)
        sb = self._standbys.pop(jid, None)
        t = self._tail_tasks.pop(jid, None)
        if t is not None:
            t.cancel()
        self._tail_pending.pop(jid, None)
        if sb is None:
            return
        await self._stop_staged(jid, sb.workers)

    async def _stop_staged(self, jid: str, workers):
        for w in workers:
            try:
                await self.ctrl._worker_call(
                    w, "WorkerGrpc", "StopJob",
                    {"job_id": jid, "staged_only": True},
                    timeout=5.0,
                )
            except Exception as e:  # noqa: BLE001 - worker may be dying
                logger.debug("standby StopJob to %s failed: %s",
                             w.worker_id, e)

    def on_standby_task_failed(self, jid: str, error: str):
        """A parked standby runner failed (restore error, worker-local
        fault): discard and back off — never the primary's problem."""
        logger.warning("standby task of %s failed: %s", jid, error)
        self._next_arm[jid] = time.monotonic() + _REARM_BACKOFF
        job = self.ctrl.jobs.get(jid)
        if job is not None:
            t = asyncio.ensure_future(self.discard(job))
            self._discard_tasks.add(t)
            t.add_done_callback(self._discard_tasks.discard)

    def on_job_expunged(self, jid: str):
        self._next_arm.pop(jid, None)
        self._grace_until.pop(jid, None)
        self._promote_times.pop(jid, None)

    # -- observability -------------------------------------------------------

    def in_grace(self, jid: str) -> bool:
        """True while a just-promoted job is inside `failover.grace`:
        watchtower freshness/e2e rules suppress paging — the sub-second
        gap shows up in the metrics but is an engineered, bounded event."""
        return time.monotonic() < self._grace_until.get(jid, 0.0)

    def status(self) -> dict:
        from ..state.chain_cache import CACHE

        return {
            "enabled": bool(config().failover.enabled),
            "promotions": self.promotions,
            "standbys": {
                jid: {
                    "workers": [w.worker_id for w in sb.workers],
                    "epoch": sb.epoch,
                    "ns_ordinal": sb.ns_ordinal,
                    "armed_for_s": round(
                        time.monotonic() - sb.armed_at, 1
                    ),
                    "promoting": sb.promoting,
                }
                for jid, sb in self._standbys.items()
            },
            "arming": sorted(self._arm_tasks),
            "grace": {
                jid: round(t - time.monotonic(), 2)
                for jid, t in self._grace_until.items()
                if t > time.monotonic()
            },
            "chain_cache": CACHE.stats(),
        }
