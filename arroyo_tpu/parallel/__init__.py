from .mesh import key_mesh  # noqa: F401
from .sharded_state import (  # noqa: F401
    MeshSlotDirectory,
    ShardedAccumulator,
    SharedMeshSlotDirectory,
)
