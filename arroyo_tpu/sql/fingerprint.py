"""Plan fingerprints: canonical hashes over logical sub-DAGs (ISSUE 16).

The shared-plan admission pass needs to answer "is this job's prefix the
SAME computation as one already running?" without being fooled by
surface differences: table aliases, SELECT-item naming, node-id
assignment order, or parallelism hints. This module computes a stable
fingerprint per logical node:

    fp(node) = sha256(canonical(ops of the node's chain)
                      + sorted upstream (fp, edge_type) pairs)

Canonicalization rules:

  * node ids, descriptions, and parallelism are EXCLUDED — ids depend on
    planner allocation order, descriptions carry aliases, and
    parallelism is a deployment knob, not a computation;
  * op configs serialize through the same `_config_json` used for graph
    distribution (schemas as Arrow IPC bytes), then dump with sorted
    keys, so dict ordering never matters;
  * upstream fingerprints are sorted, so sibling edge enumeration order
    never matters (joins keep their left/right identity via the
    edge_type component).

Two jobs that plan `SELECT count(*) FROM events_a` and
`SELECT count(*) FROM my_alias` over identically-configured tables get
identical source fingerprints; the controller mounts the second onto
the first's running scan (controller/sharing.py).

`shareable_source` is the admission predicate: sharing a scan is only
sound when replaying the source from checkpointed split state
reproduces rows AND event times byte-for-byte (the per-tenant
exactly-once guarantee is anchored on the host's deterministic replay),
so only deterministic source configurations qualify — impulse/nexmark
with an explicit `start_time` (synthetic event time) and no wall-clock
timestamp mode.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, NamedTuple, Optional, Tuple

from ..graph.logical import (
    ChainedOp,
    LogicalGraph,
    LogicalNode,
    OperatorName,
    _config_json,
)


def _canonical_ops(node: LogicalNode) -> List[dict]:
    # descriptions are alias-bearing display strings; drop them
    return [
        {"operator": op.operator.value, "config": _config_json(op.config)}
        for op in node.chain
    ]


def _opaque(v) -> dict:
    """Live runtime objects in configs (e.g. compiled projections in
    embedded mode) have no canonical text; hash a structural descriptor
    and keep them OUT of sharing keys (admission only fingerprints the
    source op, whose config is plain JSON)."""
    desc = {"__opaque__": type(v).__name__}
    out = getattr(v, "out_schema", None)
    if out is not None:
        desc["out_schema"] = str(out)
    return desc


def _digest(doc) -> str:
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True, default=_opaque).encode()
    ).hexdigest()[:16]


def node_fingerprints(graph: LogicalGraph) -> Dict[int, str]:
    """Fingerprint every node: operator kinds + canonical configs +
    sorted upstream fingerprints (alias/ordering-normalized)."""
    fps: Dict[int, str] = {}
    for node in graph.topo_order():
        ups = sorted(
            (fps[e.src], e.edge_type.value)
            for e in graph.edges if e.dst == node.node_id
        )
        fps[node.node_id] = _digest({
            "ops": _canonical_ops(node),
            "upstream": [list(u) for u in ups],
        })
    return fps


class SourceScan(NamedTuple):
    """An admission-eligible shared source scan."""

    node_id: int              # the tenant graph's source node
    fingerprint: str          # hash of the source OP alone (mount key)
    connector: str
    config: dict              # the source op's config (verbatim)


# connectors whose replay from checkpointed split state is
# deterministic enough to anchor per-tenant exactly-once on: synthetic
# generators with explicit synthetic event time
_DETERMINISTIC_CONNECTORS = ("impulse", "nexmark")


def _deterministic_source(connector: str, cfg: dict) -> bool:
    if connector not in _DETERMINISTIC_CONNECTORS:
        return False
    if cfg.get("start_time") is None:
        return False  # event time would be wall-clock-at-start
    if connector == "impulse":
        # realtime stamps wall-clock event time unless replay mode
        # re-synthesizes it
        return not cfg.get("realtime") or bool(cfg.get("replay"))
    return not cfg.get("realtime")


def source_scan_fingerprint(op_config: dict) -> str:
    """The mount key: hash of the source operator alone (kind + canonical
    config). Chained downstream ops do NOT contribute — tenants with
    different projections over the same scan still share it."""
    return _digest({
        "operator": OperatorName.CONNECTOR_SOURCE.value,
        "config": _config_json(op_config),
    })


def apply_mount(graph: LogicalGraph, mount: dict) -> None:
    """Rewrite the graph's source op to the `mounted` connector
    (connectors/shared.py) per a controller mount directive
    {node_id, fingerprint, connector}. Workers re-plan canonical SQL and
    then apply this — planner node ids are deterministic, so the rewrite
    lands on the same node the controller rewrote. Graph shape is
    untouched (same nodes/edges/parallelism): shipped assignments stay
    valid. Idempotent."""
    from ..connectors.base import get_connector

    node = graph.nodes[int(mount["node_id"])]
    fp = mount["fingerprint"]
    node.chain[0] = ChainedOp(
        OperatorName.CONNECTOR_SOURCE,
        {"connector": "mounted", "fingerprint": fp,
         "schema": get_connector(mount["connector"]).table_schema()},
        description=f"mounted[{fp}]",
    )


def shareable_source(graph: LogicalGraph) -> Optional[SourceScan]:
    """The admission predicate: return the job's single shareable source
    scan, or None if this job must spawn its own data plane.

    Requirements: exactly one source node (multi-source jobs keep their
    own planes in v1), and a deterministic-replay connector config (see
    module docstring)."""
    sources: List[Tuple[int, dict]] = []
    for node_id, node in graph.nodes.items():
        first = node.chain[0]
        if first.operator is OperatorName.CONNECTOR_SOURCE:
            sources.append((node_id, first.config))
    if len(sources) != 1:
        return None
    node_id, cfg = sources[0]
    connector = cfg.get("connector", "")
    if not _deterministic_source(connector, cfg):
        return None
    return SourceScan(
        node_id=node_id,
        fingerprint=source_scan_fingerprint(cfg),
        connector=connector,
        config=cfg,
    )
