"""Placeholder: kafka connector lands with the connector milestone."""
