"""MUST fire CFG002: batch_size is declared with no documentation."""
import dataclasses


@dataclasses.dataclass
class PipelineConfig:
    batch_size: int = 512


@dataclasses.dataclass
class Config:
    """Sections: pipeline."""

    pipeline: PipelineConfig = dataclasses.field(default_factory=PipelineConfig)
