"""Stateless value operators: map / filter / key-calculation.

Capability parity with the reference's ValueExecutionOperator /
KeyExecutionOperator / ProjectionOperator
(/root/reference/crates/arroyo-worker/src/arrow/mod.rs:245-347), which run a
compiled physical sub-plan batch-at-a-time. Here the compiled form is an
expression program from arroyo_tpu.sql.expressions (vectorized pyarrow/
numpy, or a jitted JAX path for numeric-heavy projections); `py_fn` configs
allow raw python callables for hand-built graphs and tests.
"""

from __future__ import annotations

from typing import Callable, Optional

import pyarrow as pa

from ..graph.logical import OperatorName
from ..engine.construct import register_operator
from .base import Operator


class BatchMapOperator(Operator):
    """Applies fn(RecordBatch) -> RecordBatch."""

    # stateless value transform: registered fusable into segment runs
    # (engine/segments.py). Lint JAX004 `segment-purity` enforces that a
    # fusable operator never touches state tables or checkpoint hooks —
    # a fused run executes with ONE dispatch and relies on having no
    # per-operator capture to skip.
    fusable = True
    # set by the value factories when engine.segment_fusion is OFF and
    # the planner marked this op as part of a would-be segment run: the
    # op then counts its per-batch dispatch (and, for the run's lead op,
    # the batch itself) into the arroyo_segment_* families, so the
    # fused/unfused A/B reads dispatches_per_batch from the same place
    segment_member = False
    segment_lead = False

    def __init__(self, fn: Callable[[pa.RecordBatch], Optional[pa.RecordBatch]],
                 name: str = "map", out_schema=None):
        super().__init__(name)
        self.fn = fn
        self.out_schema = out_schema
        self._seg_counters = None

    def _count_unfused(self, ctx):
        c = self._seg_counters
        if c is None:
            from ..metrics import SEGMENT_BATCHES, SEGMENT_DISPATCHES

            ti = ctx.task_info
            c = self._seg_counters = (
                SEGMENT_DISPATCHES.labels(job=ti.job_id, task=ti.task_id,
                                          fused="0"),
                SEGMENT_BATCHES.labels(job=ti.job_id, task=ti.task_id)
                if self.segment_lead else None,
            )
        c[0].inc()
        if c[1] is not None:
            c[1].inc()

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        if self.segment_member:
            self._count_unfused(ctx)
        out = self.fn(batch)
        if out is not None and out.num_rows:
            await collector.collect(out)


def _apply_segment_flags(op: BatchMapOperator, config: dict) -> BatchMapOperator:
    if config.get("segment_member"):
        op.segment_member = True
        op.segment_lead = bool(config.get("segment_lead"))
    return op


def _declare_flow(op: BatchMapOperator, prog) -> BatchMapOperator:
    """Conservation ledger: a compiled projection's selectivity is known
    statically — row-wise without a predicate (out == in), filtering with
    one (out <= in). py_fn operators stay "any" (arbitrary callables)."""
    op.flow_class = (
        "contracting" if getattr(prog, "predicate", None) is not None
        else "exact"
    )
    return op


@register_operator(OperatorName.ARROW_VALUE)
@register_operator(OperatorName.PROJECTION)
def _make_value(config: dict) -> Operator:
    if "py_fn" in config:
        return _apply_segment_flags(
            BatchMapOperator(config["py_fn"], config.get("name", "map"),
                             config.get("schema")), config)
    if "program" in config:
        from ..sql.expressions import CompiledProjection

        prog = CompiledProjection.from_config(config["program"])
        return _apply_segment_flags(
            _declare_flow(
                BatchMapOperator(prog, config.get("name", "project"),
                                 config.get("schema")), prog), config)
    raise ValueError("value operator config needs py_fn or program")


@register_operator(OperatorName.ARROW_KEY)
def _make_key(config: dict) -> Operator:
    """Key calculation: in this engine keys are column *indices* on the edge
    schema (no separate key column materialization needed) — an ArrowKey node
    may still compute key expressions into columns before the shuffle."""
    if "py_fn" in config:
        return _apply_segment_flags(
            BatchMapOperator(config["py_fn"], "key", config.get("schema")),
            config)
    if "program" in config:
        from ..sql.expressions import CompiledProjection

        prog = CompiledProjection.from_config(config["program"])
        return _apply_segment_flags(
            _declare_flow(
                BatchMapOperator(prog, "key", config.get("schema")), prog),
            config)
    # identity: routing handled by edge schema key indices
    op = BatchMapOperator(lambda b: b, "key", config.get("schema"))
    op.flow_class = "exact"  # identity pass-through
    return _apply_segment_flags(op, config)
