from arroyo_tpu.operators.context import WatermarkHolder
from arroyo_tpu.types import Watermark


def test_min_merge_waits_for_all_inputs():
    h = WatermarkHolder(2)
    assert h.set(0, Watermark.event_time(100)) is None  # input 1 unseen
    got = h.set(1, Watermark.event_time(50))
    assert got == Watermark.event_time(50)


def test_min_merge_advances_only_on_min_change():
    h = WatermarkHolder(2)
    h.set(0, Watermark.event_time(100))
    h.set(1, Watermark.event_time(50))
    assert h.set(0, Watermark.event_time(200)) is None  # min still 50
    assert h.set(1, Watermark.event_time(80)) == Watermark.event_time(80)


def test_idle_inputs_excluded_from_min():
    h = WatermarkHolder(2)
    h.set(0, Watermark.event_time(100))
    got = h.set(1, Watermark.idle())
    assert got == Watermark.event_time(100)  # idle doesn't hold back


def test_all_idle_propagates_idle():
    h = WatermarkHolder(2)
    h.set(0, Watermark.idle())
    got = h.set(1, Watermark.idle())
    assert got is not None and got.is_idle()


def test_single_input():
    h = WatermarkHolder(1)
    assert h.set(0, Watermark.event_time(5)) == Watermark.event_time(5)
    assert h.current_nanos() == 5
