"""Recursive-descent SQL parser for the engine's dialect.

Dialect follows the reference's test-suite SQL (DataFusion/Postgres style):
CREATE TABLE ... WITH (connector options), CREATE VIEW, INSERT INTO ...
SELECT, WITH CTEs, subqueries, joins with ON conditions, GROUP BY with
ordinals and window TVFs (tumble/hop/session), HAVING, UNION [ALL],
window functions with OVER, CASE/CAST/IN/BETWEEN/IS NULL, intervals,
`==` as equality (the reference accepts it), `--` comments.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .ast import (
    Between,
    BinaryOp,
    Case,
    Cast,
    Column,
    ColumnDef,
    CreateTable,
    CreateView,
    Expr,
    FieldAccess,
    FuncCall,
    InList,
    Insert,
    Interval,
    IsNull,
    Join,
    Literal,
    OverClause,
    Relation,
    Select,
    SelectItem,
    Star,
    SubqueryRef,
    TableRef,
    UnaryOp,
    Unnest,
)
from .lexer import SqlError, TokenStream, tokenize

RESERVED_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "UNION", "JOIN",
    "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "ON", "AS", "AND", "OR",
    "NOT", "WHEN", "THEN", "ELSE", "END", "BY", "ASC", "DESC", "INSERT",
    "CREATE", "SELECT", "WITH", "INTO", "VALUES", "SET", "DISTINCT",
}

INTERVAL_UNITS = {
    "NANOSECOND": 1,
    "NANOSECONDS": 1,
    "MICROSECOND": 1_000,
    "MICROSECONDS": 1_000,
    "MILLISECOND": 1_000_000,
    "MILLISECONDS": 1_000_000,
    "SECOND": 1_000_000_000,
    "SECONDS": 1_000_000_000,
    "MINUTE": 60 * 1_000_000_000,
    "MINUTES": 60 * 1_000_000_000,
    "HOUR": 3_600 * 1_000_000_000,
    "HOURS": 3_600 * 1_000_000_000,
    "DAY": 86_400 * 1_000_000_000,
    "DAYS": 86_400 * 1_000_000_000,
    "WEEK": 7 * 86_400 * 1_000_000_000,
    "WEEKS": 7 * 86_400 * 1_000_000_000,
    "MONTH": 30 * 86_400 * 1_000_000_000,  # calendar months approximated
    "MONTHS": 30 * 86_400 * 1_000_000_000,
}


def parse_statements(sql: str) -> List[object]:
    ts = TokenStream(tokenize(sql), sql)
    out = []
    while ts.peek().kind != "eof":
        if ts.accept("punct", ";"):
            continue
        out.append(_statement(ts))
    return out


def parse_expr_text(sql: str) -> Expr:
    ts = TokenStream(tokenize(sql), sql)
    e = _expr(ts)
    if ts.peek().kind != "eof":
        raise ts.error("trailing tokens after expression")
    return e


# -- statements -------------------------------------------------------------


def _statement(ts: TokenStream):
    if ts.at_keyword("CREATE"):
        return _create(ts)
    if ts.at_keyword("INSERT"):
        return _insert(ts)
    if ts.at_keyword("SELECT", "WITH"):
        return _select(ts)
    raise ts.error("expected CREATE, INSERT, SELECT or WITH")


def _create(ts: TokenStream):
    ts.expect_keyword("CREATE")
    ts.accept_keyword("OR")  # CREATE OR REPLACE
    ts.accept_keyword("REPLACE")
    temp = ts.accept_keyword("TEMPORARY", "TEMP")
    if ts.accept_keyword("VIEW"):
        name = _name(ts)
        ts.expect_keyword("AS")
        paren = ts.accept("punct", "(")
        q = _select(ts)
        if paren:
            ts.expect("punct", ")")
        return CreateView(name, q)
    ts.expect_keyword("TABLE")
    ts.accept_keyword("IF")  # IF NOT EXISTS
    ts.accept_keyword("NOT")
    ts.accept_keyword("EXISTS")
    name = _name(ts)
    columns: List[ColumnDef] = []
    pk: List[str] = []
    watermark = None
    if ts.accept("punct", "("):
        while True:
            if ts.at_keyword("PRIMARY"):
                ts.next()
                ts.expect_keyword("KEY")
                ts.expect("punct", "(")
                while True:
                    pk.append(_name(ts))
                    if not ts.accept("punct", ","):
                        break
                ts.expect("punct", ")")
            elif ts.at_keyword("WATERMARK"):
                # WATERMARK FOR ts [AS (ts - INTERVAL '...')] — DDL form of
                # event_time_field + watermark_delay (bare form = delay 0)
                ts.next()
                ts.expect_keyword("FOR")
                wm_col = _name(ts)
                delay_nanos = 0
                if ts.accept_keyword("AS"):
                    paren = ts.accept("punct", "(")
                    e = _expr(ts)
                    if paren:
                        ts.expect("punct", ")")
                    if (
                        isinstance(e, BinaryOp) and e.op == "-"
                        and isinstance(e.right, Interval)
                    ):
                        delay_nanos = e.right.nanos
                    else:
                        raise ts.error(
                            "WATERMARK expression must be "
                            "<column> - INTERVAL '...'"
                        )
                watermark = (wm_col, delay_nanos)
            else:
                columns.append(_column_def(ts))
            if not ts.accept("punct", ","):
                break
        ts.expect("punct", ")")
    options: Dict[str, str] = {}
    if ts.accept_keyword("WITH"):
        ts.expect("punct", "(")
        while True:
            key_tok = ts.next()
            if key_tok.kind not in ("ident", "string"):
                raise ts.error("expected option name")
            key = key_tok.value
            while ts.accept("punct", "."):
                key += "." + ts.next().value
            ts.expect("op", "=")
            val = ts.next()
            if val.kind not in ("string", "number", "ident"):
                raise ts.error("expected option value")
            options[key] = val.value
            if not ts.accept("punct", ","):
                break
        ts.expect("punct", ")")
    if pk:
        options["__pk__"] = ",".join(pk)
    if watermark is not None:
        options.setdefault("event_time_field", watermark[0])
        options.setdefault("watermark_delay_nanos", str(watermark[1]))
    if ts.accept_keyword("AS"):
        # CREATE TABLE x AS SELECT -- an in-memory (virtual) table
        q = _select(ts)
        return CreateView(name, q)
    return CreateTable(name, columns, options)


def _column_def(ts: TokenStream) -> ColumnDef:
    name = _name(ts)
    type_name = _type_name(ts)
    nullable = True
    generated = None
    metadata_key = None
    while True:
        if ts.accept_keyword("NOT"):
            ts.expect_keyword("NULL")
            nullable = False
        elif ts.accept_keyword("NULL"):
            nullable = True
        elif ts.accept_keyword("PRIMARY"):
            ts.expect_keyword("KEY")
        elif ts.accept_keyword("METADATA"):
            ts.expect_keyword("FROM")
            metadata_key = ts.expect("string").value
        elif ts.accept_keyword("GENERATED"):
            ts.expect_keyword("ALWAYS")
            ts.expect_keyword("AS")
            ts.expect("punct", "(")
            generated = _expr(ts)
            ts.expect("punct", ")")
            ts.accept_keyword("STORED")
        else:
            break
    return ColumnDef(name, type_name, nullable, generated, metadata_key)


def _type_name(ts: TokenStream) -> str:
    parts = [ts.expect("ident").upper]
    # multi-word types and modifiers
    while ts.at_keyword("UNSIGNED", "PRECISION", "VARYING", "ARRAY"):
        parts.append(ts.next().upper)
    if ts.accept("punct", "("):
        # e.g. VARCHAR(10), DECIMAL(10, 2) -- sizes ignored
        while not ts.accept("punct", ")"):
            ts.next()
        if ts.accept_keyword("ARRAY"):  # VARCHAR(10) ARRAY
            parts.append("ARRAY")
    if ts.accept("punct", "["):
        ts.expect("punct", "]")
        parts.append("ARRAY")
    return " ".join(parts)


def _insert(ts: TokenStream) -> Insert:
    ts.expect_keyword("INSERT")
    ts.expect_keyword("INTO")
    table = _name(ts)
    if ts.accept("punct", "("):
        while not ts.accept("punct", ")"):
            ts.next()
    q = _select(ts)
    return Insert(table, q)


# -- select -----------------------------------------------------------------


def _select(ts: TokenStream) -> Select:
    ctes: List[Tuple[str, Select]] = []
    if ts.accept_keyword("WITH"):
        while True:
            name = _name(ts)
            ts.expect_keyword("AS")
            ts.expect("punct", "(")
            q = _select(ts)
            ts.expect("punct", ")")
            ctes.append((name, q))
            if not ts.accept("punct", ","):
                break
    sel = _select_body(ts)
    # attach ctes (planner resolves them as scoped views)
    sel.ctes = ctes  # type: ignore[attr-defined]
    while ts.at_keyword("UNION"):
        ts.next()
        if not ts.accept_keyword("ALL"):
            sel.distinct_union = True  # type: ignore[attr-defined]
        sel.unions.append(_select_body(ts))
    if ts.accept_keyword("ORDER"):
        ts.expect_keyword("BY")
        while True:
            e = _expr(ts)
            desc = bool(ts.accept_keyword("DESC"))
            ts.accept_keyword("ASC")
            sel.order_by.append((e, desc))
            if not ts.accept("punct", ","):
                break
    if ts.accept_keyword("LIMIT"):
        sel.limit = int(ts.expect("number").value)
    return sel


def _select_body(ts: TokenStream) -> Select:
    if ts.accept("punct", "("):
        q = _select(ts)
        ts.expect("punct", ")")
        return q
    ts.expect_keyword("SELECT")
    distinct = bool(ts.accept_keyword("DISTINCT"))
    ts.accept_keyword("ALL")
    items: List[SelectItem] = []
    while True:
        items.append(_select_item(ts))
        if not ts.accept("punct", ","):
            break
    from_ = None
    if ts.accept_keyword("FROM"):
        from_ = _relation(ts)
    where = None
    if ts.accept_keyword("WHERE"):
        where = _expr(ts)
    group_by: List[Expr] = []
    if ts.accept_keyword("GROUP"):
        ts.expect_keyword("BY")
        while True:
            group_by.append(_expr(ts))
            if not ts.accept("punct", ","):
                break
    having = None
    if ts.accept_keyword("HAVING"):
        having = _expr(ts)
    return Select(items, from_, where, group_by, having, distinct)


def _select_item(ts: TokenStream) -> SelectItem:
    if ts.accept("op", "*"):
        return SelectItem(Star())
    # t.* qualified star
    t = ts.peek()
    if (
        t.kind == "ident"
        and ts.peek(1).kind == "punct"
        and ts.peek(1).value == "."
        and ts.peek(2).kind == "op"
        and ts.peek(2).value == "*"
    ):
        ts.next()
        ts.next()
        ts.next()
        return SelectItem(Star(table=t.value))
    e = _expr(ts)
    alias = None
    if ts.accept_keyword("AS"):
        alias = _name(ts)
    elif ts.peek().kind == "ident" and ts.peek().upper not in RESERVED_STOP:
        alias = _name(ts)
    return SelectItem(e, alias)


# -- relations --------------------------------------------------------------


def _relation(ts: TokenStream) -> Relation:
    rel = _relation_primary(ts)
    while True:
        join_type = None
        if ts.accept_keyword("JOIN"):
            join_type = "inner"
        elif ts.at_keyword("INNER") and ts.peek(1).upper == "JOIN":
            ts.next()
            ts.next()
            join_type = "inner"
        elif ts.at_keyword("LEFT", "RIGHT", "FULL"):
            jt = ts.next().upper.lower()
            ts.accept_keyword("OUTER")
            ts.expect_keyword("JOIN")
            join_type = jt
        elif ts.at_keyword("CROSS") and ts.peek(1).upper == "JOIN":
            ts.next()
            ts.next()
            join_type = "cross"
        elif ts.accept("punct", ","):
            join_type = "cross"
        else:
            break
        right = _relation_primary(ts)
        cond = None
        if join_type != "cross":
            ts.expect_keyword("ON")
            cond = _expr(ts)
        rel = Join(rel, right, "inner" if join_type == "cross" else join_type,
                   cond)
    return rel


def _relation_primary(ts: TokenStream) -> Relation:
    if ts.accept("punct", "("):
        if ts.at_keyword("SELECT", "WITH"):
            q = _select(ts)
            ts.expect("punct", ")")
            alias = _opt_alias(ts)
            return SubqueryRef(q, alias)
        rel = _relation(ts)
        ts.expect("punct", ")")
        a = _opt_alias(ts)
        if a is not None and isinstance(rel, (TableRef, SubqueryRef)):
            rel.alias = a
        return rel
    if ts.at_keyword("UNNEST"):
        ts.next()
        ts.expect("punct", "(")
        e = _expr(ts)
        ts.expect("punct", ")")
        return Unnest(e, _opt_alias(ts))
    name = _name(ts)
    return TableRef(name, _opt_alias(ts))


def _opt_alias(ts: TokenStream) -> Optional[str]:
    if ts.accept_keyword("AS"):
        return _name(ts)
    t = ts.peek()
    if t.kind == "ident" and t.upper not in RESERVED_STOP:
        return _name(ts)
    return None


def _name(ts: TokenStream) -> str:
    t = ts.next()
    if t.kind != "ident":
        raise SqlError(f"expected name, found {t.value!r} at offset {t.pos}")
    return t.value


# -- expressions (precedence climbing) --------------------------------------


def _expr(ts: TokenStream) -> Expr:
    return _or_expr(ts)


def _or_expr(ts: TokenStream) -> Expr:
    left = _and_expr(ts)
    while ts.accept_keyword("OR"):
        left = BinaryOp("OR", left, _and_expr(ts))
    return left


def _and_expr(ts: TokenStream) -> Expr:
    left = _not_expr(ts)
    while ts.accept_keyword("AND"):
        left = BinaryOp("AND", left, _not_expr(ts))
    return left


def _not_expr(ts: TokenStream) -> Expr:
    if ts.accept_keyword("NOT"):
        return UnaryOp("NOT", _not_expr(ts))
    return _comparison(ts)


_CMP_OPS = {"=", "==", "!=", "<>", "<", "<=", ">", ">="}


def _comparison(ts: TokenStream) -> Expr:
    left = _additive(ts)
    while True:
        t = ts.peek()
        if t.kind == "op" and t.value in _CMP_OPS:
            ts.next()
            op = "=" if t.value == "==" else ("!=" if t.value == "<>" else t.value)
            left = BinaryOp(op, left, _additive(ts))
        elif ts.at_keyword("IS"):
            ts.next()
            negated = bool(ts.accept_keyword("NOT"))
            ts.expect_keyword("NULL")
            left = IsNull(left, negated)
        elif ts.at_keyword("IN"):
            ts.next()
            ts.expect("punct", "(")
            items = [_expr(ts)]
            while ts.accept("punct", ","):
                items.append(_expr(ts))
            ts.expect("punct", ")")
            left = InList(left, items)
        elif ts.at_keyword("NOT") and ts.peek(1).upper in ("IN", "BETWEEN", "LIKE"):
            ts.next()
            if ts.accept_keyword("IN"):
                ts.expect("punct", "(")
                items = [_expr(ts)]
                while ts.accept("punct", ","):
                    items.append(_expr(ts))
                ts.expect("punct", ")")
                left = InList(left, items, negated=True)
            elif ts.accept_keyword("BETWEEN"):
                low = _additive(ts)
                ts.expect_keyword("AND")
                left = Between(left, low, _additive(ts), negated=True)
            else:
                ts.expect_keyword("LIKE")
                left = UnaryOp("NOT", FuncCall("like", [left, _additive(ts)]))
        elif ts.at_keyword("BETWEEN"):
            ts.next()
            low = _additive(ts)
            ts.expect_keyword("AND")
            left = Between(left, low, _additive(ts))
        elif ts.at_keyword("LIKE"):
            ts.next()
            left = FuncCall("like", [left, _additive(ts)])
        else:
            return left


def _additive(ts: TokenStream) -> Expr:
    left = _multiplicative(ts)
    while True:
        t = ts.peek()
        if t.kind == "op" and t.value in ("+", "-", "||", "->", "->>"):
            ts.next()
            left = BinaryOp(t.value, left, _multiplicative(ts))
        else:
            return left


def _multiplicative(ts: TokenStream) -> Expr:
    left = _unary(ts)
    while True:
        t = ts.peek()
        if t.kind == "op" and t.value in ("*", "/", "%"):
            ts.next()
            left = BinaryOp(t.value, left, _unary(ts))
        else:
            return left


def _unary(ts: TokenStream) -> Expr:
    t = ts.peek()
    if t.kind == "op" and t.value == "-":
        ts.next()
        return UnaryOp("-", _unary(ts))
    if t.kind == "op" and t.value == "+":
        ts.next()
        return _unary(ts)
    return _postfix(ts)


def _postfix(ts: TokenStream) -> Expr:
    e = _primary(ts)
    while True:
        if ts.peek().kind == "punct" and ts.peek().value == ".":
            ts.next()
            field = _name(ts)
            if isinstance(e, Column) and e.table is None:
                e = Column(field, table=e.name)
            else:
                e = FieldAccess(e, field)
        elif ts.peek().kind == "punct" and ts.peek().value == "[":
            ts.next()
            idx = _expr(ts)
            ts.expect("punct", "]")
            e = FuncCall("array_element", [e, idx])
        else:
            return e


def _primary(ts: TokenStream) -> Expr:
    t = ts.peek()
    if t.kind == "number":
        ts.next()
        v = float(t.value) if any(c in t.value for c in ".eE") else int(t.value)
        return Literal(v)
    if t.kind == "string":
        ts.next()
        return Literal(t.value)
    if t.kind == "punct" and t.value == "(":
        ts.next()
        if ts.at_keyword("SELECT", "WITH"):
            raise ts.error("scalar subqueries are not supported")
        e = _expr(ts)
        ts.expect("punct", ")")
        return e
    if t.kind != "ident":
        raise SqlError(f"unexpected token {t.value!r} at offset {t.pos}")
    up = t.upper
    if up == "NULL":
        ts.next()
        return Literal(None)
    if up in ("TRUE", "FALSE"):
        ts.next()
        return Literal(up == "TRUE")
    if up == "INTERVAL":
        ts.next()
        return _interval(ts)
    if up == "CAST":
        ts.next()
        ts.expect("punct", "(")
        e = _expr(ts)
        ts.expect_keyword("AS")
        type_name = _type_name(ts)
        ts.expect("punct", ")")
        return Cast(e, type_name)
    if up == "CASE":
        ts.next()
        return _case(ts)
    if up == "EXTRACT":
        ts.next()
        ts.expect("punct", "(")
        part = _name(ts)
        ts.expect_keyword("FROM")
        e = _expr(ts)
        ts.expect("punct", ")")
        return FuncCall("extract", [Literal(part.lower()), e])
    # function call or column
    if ts.peek(1).kind == "punct" and ts.peek(1).value == "(":
        name = ts.next().value
        ts.expect("punct", "(")
        distinct = bool(ts.accept_keyword("DISTINCT"))
        star = False
        args: List[Expr] = []
        if ts.accept("op", "*"):
            star = True
        elif not (ts.peek().kind == "punct" and ts.peek().value == ")"):
            args.append(_expr(ts))
            while ts.accept("punct", ","):
                args.append(_expr(ts))
        ts.expect("punct", ")")
        # WITHIN GROUP (ORDER BY x): ordered-set aggregate syntax
        # (approx_percentile_cont etc.) — normalized by prepending the
        # ordering expression to the argument list
        if ts.at_keyword("WITHIN"):
            ts.next()
            ts.expect_keyword("GROUP")
            ts.expect("punct", "(")
            ts.expect_keyword("ORDER")
            ts.expect_keyword("BY")
            order_e = _expr(ts)
            desc = bool(ts.accept_keyword("DESC"))
            ts.accept_keyword("ASC")
            ts.expect("punct", ")")
            # percentile over a DESC ordering is the (1-p) ascending
            # quantile; rewrite the literal so the reducer stays ascending
            if desc:
                if args and isinstance(args[-1], Literal) and isinstance(
                    args[-1].value, (int, float)
                ):
                    args = args[:-1] + [Literal(1.0 - float(args[-1].value))]
                else:
                    raise ts.error(
                        "WITHIN GROUP (ORDER BY ... DESC) requires a "
                        "literal percentile to invert"
                    )
            args = [order_e] + args
        over = None
        if ts.at_keyword("OVER"):
            ts.next()
            ts.expect("punct", "(")
            partition: List[Expr] = []
            order: List[Tuple[Expr, bool]] = []
            if ts.accept_keyword("PARTITION"):
                ts.expect_keyword("BY")
                partition.append(_expr(ts))
                while ts.accept("punct", ","):
                    partition.append(_expr(ts))
            if ts.accept_keyword("ORDER"):
                ts.expect_keyword("BY")
                while True:
                    e = _expr(ts)
                    desc = bool(ts.accept_keyword("DESC"))
                    ts.accept_keyword("ASC")
                    order.append((e, desc))
                    if not ts.accept("punct", ","):
                        break
            ts.expect("punct", ")")
            over = OverClause(partition, order)
        return FuncCall(name.lower(), args, distinct, star, over)
    ts.next()
    return Column(t.value)


def _case(ts: TokenStream) -> Case:
    operand = None
    if not ts.at_keyword("WHEN"):
        operand = _expr(ts)
    branches = []
    while ts.accept_keyword("WHEN"):
        when = _expr(ts)
        ts.expect_keyword("THEN")
        branches.append((when, _expr(ts)))
    else_ = None
    if ts.accept_keyword("ELSE"):
        else_ = _expr(ts)
    ts.expect_keyword("END")
    return Case(operand, branches, else_)


def _interval(ts: TokenStream) -> Interval:
    s = ts.expect("string").value.strip()
    parts = s.split()
    if len(parts) == 2 and parts[0].replace(".", "").isdigit():
        qty = float(parts[0])
        unit = parts[1].upper()
        if unit not in INTERVAL_UNITS:
            raise SqlError(f"unknown interval unit {parts[1]!r}")
        return Interval(int(qty * INTERVAL_UNITS[unit]))
    # INTERVAL '1' HOUR style: unit follows as a keyword
    if s.replace(".", "").isdigit():
        unit_tok = ts.peek()
        if unit_tok.kind == "ident" and unit_tok.upper in INTERVAL_UNITS:
            ts.next()
            return Interval(int(float(s) * INTERVAL_UNITS[unit_tok.upper]))
        # bare number defaults to seconds
        return Interval(int(float(s) * 1_000_000_000))
    # compound strings like '1 hour 30 minutes'
    total = 0
    i = 0
    while i < len(parts) - 1:
        qty = float(parts[i])
        unit = parts[i + 1].upper()
        if unit not in INTERVAL_UNITS:
            raise SqlError(f"unknown interval unit {parts[i + 1]!r}")
        total += int(qty * INTERVAL_UNITS[unit])
        i += 2
    if i != len(parts):
        raise SqlError(f"cannot parse interval {s!r}")
    return Interval(total)
