"""Device-resident slot directory (sorted hash table + searchsorted
lookup on the accelerator) must agree with the host SlotDirectory on
assignment identity, emission contents, slot reuse, and growth."""

import numpy as np
import pytest

from arroyo_tpu.ops.device_directory import DeviceSlotDirectory
from arroyo_tpu.ops.directory import SlotDirectory


def groups_of(d, bins, keys):
    """slot per row -> canonical group labeling for comparison."""
    slots = d.assign(bins, [keys])
    return slots


def test_assignment_matches_host_directory():
    rng = np.random.default_rng(5)
    dev = DeviceSlotDirectory(n_keys=1, table_capacity=256)
    host = SlotDirectory()
    for _ in range(6):
        n = 700
        bins = rng.integers(0, 4, n)
        keys = rng.integers(0, 150, n)
        s_dev = dev.assign(bins, [keys])
        s_host = host.assign(bins, [keys])
        # same rows must land in the same group under both directories
        # (slot numbering may differ): compare group partition ids
        _, inv_dev = np.unique(s_dev, return_inverse=True)
        _, inv_host = np.unique(s_host, return_inverse=True)
        # mapping dev-group -> host-group must be a bijection on rows
        pairs = set(zip(inv_dev.tolist(), inv_host.tolist()))
        assert len(pairs) == len(set(p[0] for p in pairs))
        assert len(pairs) == len(set(p[1] for p in pairs))
    assert dev.n_live == host.n_live


def test_same_group_same_slot_across_batches():
    dev = DeviceSlotDirectory(n_keys=1)
    s1 = dev.assign(np.array([1, 1]), [np.array([7, 8])])
    s2 = dev.assign(np.array([1, 1, 1]), [np.array([8, 7, 9])])
    assert s1[0] == s2[1] and s1[1] == s2[0]
    assert s2[2] not in (s1[0], s1[1])


def test_take_bin_frees_and_reuses_slots():
    dev = DeviceSlotDirectory(n_keys=1)
    bins = np.zeros(5, dtype=np.int64)
    keys = np.arange(5)
    slots = dev.assign(bins, [keys])
    got_keys, got_slots = dev.take_bin(0)
    assert sorted(k[0] for k in got_keys) == list(range(5))
    assert sorted(got_slots.tolist()) == sorted(slots.tolist())
    assert dev.n_live == 0
    # emitted groups are gone from the device table: re-assigning the
    # same (bin, key) allocates fresh slots drawn from the free list
    s2 = dev.assign(bins, [keys])
    assert set(s2.tolist()) == set(slots.tolist())
    assert dev.n_live == 5


def test_multi_word_keys_and_bin_isolation():
    dev = DeviceSlotDirectory(n_keys=2)
    k1 = np.array([1, 1, 2])
    k2 = np.array([10, 11, 10])
    bins = np.array([0, 0, 0])
    s = dev.assign(bins, [k1, k2])
    assert len(set(s.tolist())) == 3
    # same keys, different bin -> different groups
    s_other = dev.assign(np.array([1, 1, 1]), [k1, k2])
    assert not (set(s.tolist()) & set(s_other.tolist()))
    keys0, slots0 = dev.take_bin_arrays(0)
    assert sorted(zip(keys0[0].tolist(), keys0[1].tolist())) == [
        (1, 10), (1, 11), (2, 10)
    ]
    assert dev.n_live == 3


def test_table_growth_preserves_entries():
    dev = DeviceSlotDirectory(n_keys=1, table_capacity=64)
    bins = np.zeros(500, dtype=np.int64)
    keys = np.arange(500)
    s1 = dev.assign(bins, [keys])
    assert dev.n_live == 500 and dev._cap >= 512
    # every group still found after growth
    s2 = dev.assign(bins, [keys])
    assert np.array_equal(s1, s2)


def test_bin_entries_nondestructive():
    dev = DeviceSlotDirectory(n_keys=1)
    dev.assign(np.array([3, 3]), [np.array([1, 2])])
    kmat, slots = dev.bin_entries(3)
    assert len(slots) == 2 and kmat.shape == (2, 1)
    assert dev.n_live == 2
    assert dev.by_bin == {3: True}
    assert dev.live_bins() == [3]
    assert dev.bins_up_to(4) == [3] and dev.bins_up_to(3) == []


@pytest.mark.parametrize("golden", ["hourly_by_event_type",
                                    "sliding_window_end", "nexmark_q5",
                                    "updating_aggregate",
                                    "filter_updating_aggregates",
                                    "min_max_retracting"])
def test_golden_queries_with_device_directory(golden, tmp_path):
    """Window pipelines with tpu.device_directory=True must reproduce the
    committed golden outputs (tumbling, sliding, the q5 hop+join shape,
    and — round 5 — the UPDATING aggregate subset riding the widened
    directory surface: keys_for_slots, slot-valued peek_bin, targeted
    remove), with the collision audit sampling every assign."""
    import asyncio
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__)))
    import test_golden as tg

    from arroyo_tpu.config import update
    from arroyo_tpu.engine import Engine
    from arroyo_tpu.sql import plan_query

    qpath = os.path.join(tg.GOLDEN, "queries", f"{golden}.sql")
    gpath = os.path.join(tg.GOLDEN, "golden_outputs", f"{golden}.json")
    out = str(tmp_path / "out.json")
    sql = tg.load_query(qpath, out)
    with update(tpu={"enabled": True, "device_directory": True,
                     "device_directory_audit": True,
                     "require_accelerator": False}):
        plan = plan_query(sql, parallelism=2)

        async def go():
            eng = Engine(plan.graph).start()
            await eng.join(120)

        asyncio.run(go())
    got = tg.canonicalize_output(out, sql)
    want = [line.strip() for line in open(gpath)]
    assert got == want


def test_updating_surface_keys_for_slots_and_point_lookup():
    """Round-5 widening: the device directory serves the updating
    aggregate's surface — keys_for_slots, slots_for_keys, slot-valued
    peek_bin — identically to the host directory."""
    dev = DeviceSlotDirectory(n_keys=1)
    bins = np.zeros(6, dtype=np.int64)
    keys = np.array([10, 20, 30, 10, 20, 40])
    slots = dev.assign(bins, [keys])
    # reverse index: every slot maps back to its (bin, key)
    entries = dev.keys_for_slots(np.unique(slots))
    assert all(e is not None and e[0] == 0 for e in entries)
    assert sorted(e[1][0] for e in entries) == [10, 20, 30, 40]
    # unknown slot -> None
    assert dev.keys_for_slots(np.array([99999]))[0] is None
    # slot-valued peek: key -> slot agrees with assign
    peek = dev.peek_bin(0)
    assert peek[(10,)] == int(slots[0]) and peek[(40,)] == int(slots[5])
    # point lookups resolve only present keys
    got = dev.slots_for_keys(0, [(20,), (77,)])
    assert got == {(20,): int(slots[1])}


def test_updating_surface_targeted_remove():
    """remove(bin, keys) drops exactly those groups (TTL eviction),
    frees their slots for reuse, and keeps lookups for survivors."""
    dev = DeviceSlotDirectory(n_keys=1)
    bins = np.zeros(4, dtype=np.int64)
    keys = np.array([1, 2, 3, 4])
    slots = dev.assign(bins, [keys])
    freed = dev.remove(0, [(2,), (4,)])
    assert sorted(freed.tolist()) == sorted([int(slots[1]), int(slots[3])])
    assert dev.n_live == 2
    # removed keys re-assign into FRESH slots (reused ids allowed),
    # survivors keep theirs
    s2 = dev.assign(bins, [keys])
    assert s2[0] == slots[0] and s2[2] == slots[2]
    assert dev.n_live == 4
    # removing every remaining key empties the bin
    dev.remove(0, [(1,), (2,), (3,), (4,)])
    assert dev.n_live == 0 and dev.peek_bin(0) is None


def test_collision_audit_detects_merged_groups():
    """tpu.device_directory_audit: a 64-bit hash collision (simulated by
    corrupting the reverse hash index) must raise instead of silently
    merging two groups' aggregates."""
    from arroyo_tpu.config import update as cfg_update

    with cfg_update(tpu={"device_directory_audit": True}):
        dev = DeviceSlotDirectory(n_keys=1)
    dev._audit = True
    dev.assign(np.zeros(2, dtype=np.int64), [np.array([5, 6])])
    # force the index to claim hash(bin0, key5) belongs to key 999 —
    # exactly what a colliding group would observe on its lookup hit
    dev._build_indexes()
    h5 = dev._hash(np.zeros(1, dtype=np.int64), [np.array([5])])[0]
    dev._hash_index[int(h5)] = (999,)
    with pytest.raises(RuntimeError, match="collision"):
        dev.assign(np.zeros(1, dtype=np.int64), [np.array([5])])
