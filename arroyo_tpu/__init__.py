"""arroyo_tpu — a TPU-native distributed stream-processing framework.

Capabilities modeled on ArroyoSystems/arroyo (Rust, reference at
/root/reference): SQL-defined streaming pipelines compiled to a dataflow DAG
of Arrow-native operators with event-time watermarks, windowed/updating
aggregations and joins, exactly-once checkpointing, and a connector library.
The execution layer is TPU-first: window aggregates, joins and UDAFs run as
jax.jit/XLA kernels over Arrow batches, keyed state lives in device memory as
mesh-shardable arrays, and keyed shuffles map onto ICI collectives.

Layer map (mirrors SURVEY.md §1):
  api/         REST control surface (reference: crates/arroyo-api)
  controller/  job state machine + schedulers (crates/arroyo-controller)
  sql/         SQL → logical dataflow graph (crates/arroyo-planner)
  graph/       DAG types + chaining optimizer (crates/arroyo-datastream)
  operators/   operator framework (crates/arroyo-operator)
  engine/      physical execution engine (crates/arroyo-worker)
  connectors/  sources and sinks (crates/arroyo-connectors)
  formats/     serialization (crates/arroyo-formats)
  state/       checkpointed state (crates/arroyo-state{,-protocol}, -storage)
  ops/         TPU compute kernels (jax/XLA/pallas) — the hot data path
  parallel/    device mesh, sharding, collective shuffle
  udf/         user-defined scalar/aggregate/async functions
  utils/       logging, shutdown, misc substrate
"""

__version__ = "0.1.0"
