"""Retained metric history (ISSUE 13): the windowed time-series tier.

Every observability surface before this PR was an instantaneous
snapshot: the doctor diagnosed from the current scrape, the autoscaler
re-derived rates from ad-hoc counter diffs, and a 3am incident in a
100-job fleet left nothing to look back on. Following Monarch's
in-memory windowed store close to the workload (Adams et al., VLDB'20),
this module keeps a bounded per-series ring of (t, value) samples
scraped from the live `Registry` and answers the windowed queries
everything else derives from:

  delta(window)      counter increase over the window, RESET-AWARE: a
                     replaced worker's restart reads as the post-restart
                     value, never a negative delta (the clamping that
                     used to live ad hoc in autoscale/signals.py —
                     this is now the ONE rate-computation code path);
  rate(window)       delta / window;
  window_max/latest  gauge views;
  hist_window        windowed histogram: the cumulative-bucket DIFF of
                     the snapshots spanning the window, fed to
                     `metrics.hist_quantiles` for windowed p50/p95/p99
                     (a lifetime-cumulative histogram can never show
                     "p99 over the last minute");
  last_change_age    seconds since a value last moved (epoch stall).

Series are keyed (family, sorted label items); families are bounded by
an allowlist (`DEFAULT_RETAIN` + `watch.retain_extra`) and a hard
`watch.max_series` cap, and job-labeled series GC through `drop_job`
beside `Registry.drop_job` on the expunge path. One process-wide
`HISTORY` instance is pumped by the worker accounting pump and the
controller watchtower (a min-interval guard dedupes co-resident
pumps); the autoscaler's `SignalSampler` owns a private instance fed
from merged GetMetrics snapshots, so both read rates from this code.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

LabelSet = Tuple[Tuple[str, str], ...]

# families the history tier retains by default: what the SLO engine,
# the doctor and the autoscaler actually read. Everything else is
# scraped and dropped — retention is RAM, and a churn fleet mints
# thousands of series.
DEFAULT_RETAIN = (
    "arroyo_worker_messages_recv",
    "arroyo_worker_messages_sent",
    "arroyo_worker_busy_seconds",
    "arroyo_worker_backpressure",
    "arroyo_worker_queue_size",
    "arroyo_worker_watermark_lag_seconds",
    "arroyo_worker_batch_processing_seconds",
    "arroyo_worker_e2e_latency_seconds",
    "arroyo_worker_loop_lag_seconds",
    "arroyo_serve_request_seconds",
    "arroyo_job_attributed_busy_seconds",
    "arroyo_job_attributed_device_seconds",
    "arroyo_checkpoint_phase_seconds",
    "arroyo_trace_dropped_spans_total",
    "arroyo_job_published_epoch",
    # conservation ledger: the watchtower's conservation rule reads the
    # breach count; FIRING bundles attach this family's recent history
    "arroyo_audit_breaches_total",
)


def _is_hist(value) -> bool:
    return isinstance(value, dict) and "buckets" in value


class Series:
    """One metric labelset's bounded sample ring."""

    __slots__ = ("name", "labels", "kind", "samples")

    def __init__(self, name: str, labels: LabelSet, kind: str,
                 capacity: int):
        self.name = name
        self.labels = labels
        self.kind = kind  # "scalar" | "hist"
        self.samples: deque = deque(maxlen=max(2, int(capacity)))

    def add(self, t: float, value) -> None:
        self.samples.append((t, value))

    def label(self, key: str) -> str:
        for k, v in self.labels:
            if k == key:
                return v
        return ""

    # -- queries (samples are (t, value), oldest first) ----------------------

    def latest(self):
        return self.samples[-1][1] if self.samples else None

    def latest_time(self) -> Optional[float]:
        return self.samples[-1][0] if self.samples else None

    def window(self, window: float, now: Optional[float] = None,
               include_base: bool = True) -> list:
        """Samples covering [now - window, now]: every in-window sample
        plus (for counters — include_base) the last sample at-or-before
        the window start, the delta base without which the first
        in-window increment is invisible. Gauge views (window_max) drop
        the base: a stale pre-window value is not part of the window."""
        now = time.monotonic() if now is None else now
        cutoff = now - window
        out: list = []
        base = None
        for t, v in self.samples:
            if t > now:
                break
            if t <= cutoff:
                base = (t, v)
            else:
                out.append((t, v))
        if base is not None and include_base:
            out.insert(0, base)
        return out

    def delta(self, window: float,
              now: Optional[float] = None) -> Optional[float]:
        """Counter increase over the window, reset-aware: consecutive
        samples that go DOWN read as a restart and contribute the
        post-restart value (Prometheus increase() semantics). None with
        fewer than two covering samples — "no judgement", distinct
        from a measured zero."""
        pts = self.window(window, now)
        if len(pts) < 2:
            return None
        total = 0.0
        prev = float(pts[0][1])
        for _t, v in pts[1:]:
            v = float(v)
            total += (v - prev) if v >= prev else v
            prev = v
        return total

    def rate(self, window: float,
             now: Optional[float] = None) -> Optional[float]:
        d = self.delta(window, now)
        if d is None:
            return None
        return d / window if window > 0 else 0.0

    def window_max(self, window: float,
                   now: Optional[float] = None) -> Optional[float]:
        pts = self.window(window, now, include_base=False)
        if not pts:
            return None
        return max(float(v) for _t, v in pts)

    def last_change_age(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the value last changed; if it never changed in
        the retained window, seconds since the oldest retained sample (a
        floor on the true age — retention bounds what we can know)."""
        if not self.samples:
            return None
        now = time.monotonic() if now is None else now
        pts = list(self.samples)
        last = pts[-1][1]
        # the change happened at the first sample that HOLDS the current
        # value, i.e. the sample after the last differing one
        changed_at = pts[0][0]
        for i in range(len(pts) - 1, 0, -1):
            if pts[i - 1][1] != last:
                changed_at = pts[i][0]
                break
        return max(0.0, now - changed_at)

    def hist_window(self, window: float,
                    now: Optional[float] = None) -> Optional[dict]:
        """Windowed histogram: accumulate the cumulative-bucket diffs of
        consecutive snapshots in the window (reset pairs contribute the
        post-restart snapshot whole). Returns the same {"sum", "count",
        "buckets": {le: cumulative}} shape `metrics.hist_quantiles`
        consumes, or None without two covering snapshots."""
        pts = self.window(window, now)
        pts = [(t, v) for t, v in pts if _is_hist(v)]
        if len(pts) < 2:
            return None
        buckets: Dict[str, float] = {}
        total_sum = 0.0
        total_count = 0
        prev = pts[0][1]
        for _t, cur in pts[1:]:
            if cur.get("count", 0) >= prev.get("count", 0):
                d_count = cur.get("count", 0) - prev.get("count", 0)
                d_sum = cur.get("sum", 0.0) - prev.get("sum", 0.0)
                les = set(cur.get("buckets", {})) | set(
                    prev.get("buckets", {}))
                for le in les:
                    buckets[le] = buckets.get(le, 0) + max(
                        0,
                        cur.get("buckets", {}).get(le, 0)
                        - prev.get("buckets", {}).get(le, 0),
                    )
            else:  # counter restart: the new snapshot IS the increment
                d_count = cur.get("count", 0)
                d_sum = cur.get("sum", 0.0)
                for le, c in cur.get("buckets", {}).items():
                    buckets[le] = buckets.get(le, 0) + c
            total_sum += d_sum
            total_count += d_count
            prev = cur
        return {"sum": total_sum, "count": total_count, "buckets": buckets}

    def quantiles(self, window: float, now: Optional[float] = None,
                  qs: Tuple[float, ...] = (0.5, 0.95, 0.99)) -> dict:
        from ..metrics import hist_quantiles

        return hist_quantiles(self.hist_window(window, now), qs)

    def export(self, window: Optional[float] = None,
               now: Optional[float] = None) -> dict:
        """Structured view for REST / bundles: raw samples (histograms
        reduced to counts) plus derived windowed stats."""
        now = time.monotonic() if now is None else now
        pts = (self.window(window, now) if window is not None
               else list(self.samples))
        # wall-clock conversion for humans reading bundles offline
        off = time.time() - time.monotonic()
        out = {
            "name": self.name,
            "labels": dict(self.labels),
            "kind": self.kind,
            "samples": [
                [round(t + off, 3),
                 (v.get("count", 0) if _is_hist(v) else v)]
                for t, v in pts
            ],
        }
        if window is not None:
            if self.kind == "hist":
                q = self.quantiles(window, now)
                if q:
                    out["quantiles"] = {k: round(v, 6)
                                        for k, v in q.items()}
                h = self.hist_window(window, now)
                out["count_delta"] = h["count"] if h else 0
            else:
                d = self.delta(window, now)
                if d is not None:
                    out["delta"] = round(d, 6)
                    out["rate"] = round(d / window, 6) if window else 0.0
                m = self.window_max(window, now)
                if m is not None:
                    out["max"] = round(m, 6)
                out["latest"] = self.latest()
        return out


class MetricHistory:
    """Bounded multi-series history with a registry scrape front end.

    `retain=None` reads the allowlist from config (`DEFAULT_RETAIN` +
    `watch.retain_extra`) at each ingest; an explicit tuple pins it
    (the autoscaler's private sampler instance does this)."""

    def __init__(self, capacity: Optional[int] = None,
                 retain: Optional[Iterable[str]] = None,
                 max_series: Optional[int] = None):
        self._series: Dict[Tuple[str, LabelSet], Series] = {}
        # (family, job-label-or-"") -> [Series]: the SLO engine asks for
        # one job's series of one family ~14x per job per tick — a flat
        # scan over every retained series would be quadratic in fleet
        # size right inside the idle-CPU-per-job budget
        self._index: Dict[Tuple[str, str], List[Series]] = {}
        self._lock = threading.Lock()
        self._capacity = capacity
        self._retain = tuple(retain) if retain is not None else None
        self._max_series = max_series
        self.dropped_series = 0
        self._last_sample = 0.0

    # -- config-derived knobs ------------------------------------------------

    def _cfg(self):
        from ..config import config

        return config().watch

    def retained(self) -> frozenset:
        if self._retain is not None:
            return frozenset(self._retain)
        cfg = self._cfg()
        extra = tuple(
            s.strip() for s in str(cfg.retain_extra or "").split(",")
            if s.strip()
        )
        return frozenset(DEFAULT_RETAIN + extra)

    def capacity(self) -> int:
        return int(self._capacity or self._cfg().samples)

    def series_cap(self) -> int:
        return int(self._max_series or self._cfg().max_series)

    # -- ingest --------------------------------------------------------------

    def ingest(self, snapshot: dict, now: Optional[float] = None) -> int:
        """Append one scrape's samples. Accepts both snapshot shapes in
        the codebase: `Registry.snapshot()`'s {name: [(labels, value)]}
        and `merge_snapshots()`'s {name: {label_tuple: value}}. Returns
        the number of samples appended."""
        now = time.monotonic() if now is None else now
        fams = self.retained()
        cap = self.capacity()
        series_cap = self.series_cap()
        appended = 0
        with self._lock:
            for name, entries in (snapshot or {}).items():
                if name not in fams:
                    continue
                items = (entries.items() if isinstance(entries, dict)
                         else entries)
                for labels, value in items:
                    key_labels: LabelSet = (
                        tuple(sorted(dict(labels).items()))
                        if not isinstance(labels, tuple) else labels
                    )
                    key = (name, key_labels)
                    s = self._series.get(key)
                    if s is None:
                        if len(self._series) >= series_cap:
                            self.dropped_series += 1
                            continue
                        s = self._series[key] = Series(
                            name, key_labels,
                            "hist" if _is_hist(value) else "scalar", cap,
                        )
                        self._index.setdefault(
                            (name, s.label("job")), []).append(s)
                    s.add(now, value)
                    appended += 1
            self._last_sample = now
        return appended

    def sample_registry(self, registry=None,
                        now: Optional[float] = None) -> int:
        """Scrape the live registry into the history — the pump entry
        point. Guarded by `watch.sample_interval`: co-resident pumps
        (embedded worker accounting pump + controller watchtower share
        one process) never double-sample. Returns samples appended (0
        when guarded off or watch disabled)."""
        cfg = self._cfg()
        if not cfg.enabled:
            return 0
        now = time.monotonic() if now is None else now
        with self._lock:
            if now - self._last_sample < 0.9 * float(cfg.sample_interval):
                return 0
        if registry is None:
            from ..metrics import REGISTRY as registry  # noqa: N813
        return self.ingest(registry.snapshot(), now=now)

    # -- queries -------------------------------------------------------------

    def get(self, name: str, **labels) -> List[Series]:
        """Series of one family whose labels contain all of `labels`.
        A `job=` filter hits the (family, job) index directly."""
        with self._lock:
            if "job" in labels:
                candidates = list(self._index.get(
                    (name, labels["job"]), ()))
            else:
                candidates = [
                    s for (n, j), lst in self._index.items()
                    if n == name for s in lst
                ]
        rest = [(k, v) for k, v in labels.items() if k != "job"]
        if not rest:
            return candidates
        out = []
        for s in candidates:
            d = dict(s.labels)
            if all(d.get(k) == v for k, v in rest):
                out.append(s)
        return out

    def families(self) -> List[str]:
        with self._lock:
            return sorted({n for n, _ in self._series})

    def job_series(self, job_id: str) -> List[Series]:
        with self._lock:
            return [s for (_n, ls), s in self._series.items()
                    if ("job", job_id) in ls]

    def export_job(self, job_id: str, window: float,
                   now: Optional[float] = None,
                   series: Optional[str] = None) -> List[dict]:
        """The REST/bundle payload: every retained series of one job
        (plus the process-wide unlabeled families the job's SLOs read —
        loop lag, trace drops), windowed."""
        now = time.monotonic() if now is None else now
        out = []
        with self._lock:
            entries = list(self._series.items())
        for (name, ls), s in entries:
            d = dict(ls)
            owner = d.get("job")
            if owner is not None and owner != job_id:
                continue
            if owner is None and name not in (
                "arroyo_worker_loop_lag_seconds",
                "arroyo_trace_dropped_spans_total",
            ):
                continue
            if series is not None and name != series:
                continue
            out.append(s.export(window=window, now=now))
        out.sort(key=lambda e: (e["name"], sorted(e["labels"].items())))
        return out

    # -- lifecycle -----------------------------------------------------------

    def drop_job(self, job_id: str) -> int:
        """Cardinality GC beside Registry.drop_job: a torn-down job's
        retained series must not outlive its metric series."""
        match = ("job", job_id)
        with self._lock:
            stale = [k for k in self._series if match in k[1]]
            for k in stale:
                del self._series[k]
            for ikey in [i for i in self._index if i[1] == job_id]:
                del self._index[ikey]
            return len(stale)

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self._index.clear()
            self.dropped_series = 0
            self._last_sample = 0.0

    def stats(self) -> dict:
        with self._lock:
            n_samples = sum(len(s.samples) for s in self._series.values())
            last = self._last_sample
            return {
                "series": len(self._series),
                "samples": n_samples,
                "dropped_series": self.dropped_series,
                "capacity": self.capacity(),
                "last_sample_age_s": round(
                    max(0.0, time.monotonic() - last), 3
                ) if last else None,
            }


# the process-wide history tier: pumped by the worker accounting pump
# and the controller watchtower, read by the doctor and /debug surfaces
HISTORY = MetricHistory()
