"""Worker schedulers.

Capability parity with the reference's scheduler implementations
(/root/reference/crates/arroyo-controller/src/schedulers/mod.rs:49-71
trait + Process/Embedded/Manual/Kubernetes impls): given a job's slot
requirement, start workers and wait for them to register. The embedded
scheduler runs workers as asyncio tasks in the controller process
(`arroyo run` mode); the process scheduler forks `python -m arroyo_tpu
worker` subprocesses; the manual scheduler waits for externally-launched
workers to join; a kubernetes scheduler renders worker pod specs (applied
via kubectl when available).
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys
from typing import Dict, List

from ..utils.logging import get_logger

logger = get_logger("scheduler")


class Scheduler:
    async def start_workers(self, controller_addr: str, n_workers: int,
                            job_id: str) -> None:
        raise NotImplementedError

    async def stop_workers(self, job_id: str, force: bool = False) -> None:
        pass


_next_embedded_id = 1000


class EmbeddedScheduler(Scheduler):
    """Workers as asyncio tasks inside the controller process."""

    def __init__(self):
        self.jobs: Dict[str, List] = {}  # job_id -> [(worker, task)]

    async def start_workers(self, controller_addr, n_workers, job_id):
        global _next_embedded_id

        from ..engine.worker import WorkerServer

        entries = self.jobs.setdefault(job_id, [])
        for _ in range(n_workers):
            wid = _next_embedded_id
            _next_embedded_id += 1  # unique across concurrent jobs
            w = WorkerServer(controller_addr, worker_id=wid)
            await w.start()
            entries.append(
                (w, asyncio.ensure_future(w.run_until_finished()))
            )

    async def stop_workers(self, job_id, force=False):
        entries = self.jobs.pop(job_id, [])
        if force:
            # full teardown: cancel runners, heartbeats and servers so no
            # zombie keeps refreshing the controller's liveness view
            for w, t in entries:
                await w.shutdown()
                t.cancel()
            await asyncio.gather(
                *[t for _, t in entries], return_exceptions=True
            )


_next_process_id = 2000


class ProcessScheduler(Scheduler):
    """Forks worker subprocesses (reference ProcessScheduler mod.rs:118)."""

    def __init__(self):
        self.procs: Dict[str, List[subprocess.Popen]] = {}

    async def start_workers(self, controller_addr, n_workers, job_id):
        global _next_process_id

        for _ in range(n_workers):
            env = dict(os.environ)
            env["ARROYO_WORKER_ID"] = str(_next_process_id)
            _next_process_id += 1
            p = subprocess.Popen(
                [sys.executable, "-m", "arroyo_tpu", "worker",
                 "--controller", controller_addr],
                env=env,
            )
            self.procs.setdefault(job_id, []).append(p)

    async def stop_workers(self, job_id, force=False):
        procs = self.procs.pop(job_id, [])
        for p in procs:
            if p.poll() is None:
                p.kill() if force else p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


class ManualScheduler(Scheduler):
    """Workers join on their own (reference mod.rs:334)."""

    async def start_workers(self, controller_addr, n_workers, job_id):
        logger.info(
            "manual scheduler: waiting for %d workers to join %s",
            n_workers, controller_addr,
        )


class KubernetesScheduler(Scheduler):
    """Renders worker pod specs (reference schedulers/kubernetes/mod.rs:240);
    applies them with kubectl when present, else raises with the manifest
    path so operators can apply it themselves."""

    def __init__(self, namespace: str = "default",
                 image: str = "arroyo-tpu:latest", task_slots: int = 4):
        self.namespace = namespace
        self.image = image
        self.task_slots = task_slots

    def render_pod(self, controller_addr: str, job_id: str, index: int) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"arroyo-worker-{job_id}-{index}".lower(),
                "namespace": self.namespace,
                "labels": {
                    "app": "arroyo-tpu-worker",
                    "arroyo/job_id": job_id,
                },
            },
            "spec": {
                "restartPolicy": "Never",
                "containers": [
                    {
                        "name": "worker",
                        "image": self.image,
                        "command": [
                            "python", "-m", "arroyo_tpu", "worker",
                            "--controller", controller_addr,
                        ],
                        "env": [
                            {"name": "ARROYO__WORKER__TASK_SLOTS",
                             "value": str(self.task_slots)},
                        ],
                        "resources": {
                            "requests": {"google.com/tpu": "1"},
                            "limits": {"google.com/tpu": "1"},
                        },
                    }
                ],
            },
        }

    async def start_workers(self, controller_addr, n_workers, job_id):
        import json
        import shutil
        import tempfile

        pods = [
            self.render_pod(controller_addr, job_id, i)
            for i in range(n_workers)
        ]
        manifest = tempfile.NamedTemporaryFile(
            "w", suffix=".json", delete=False
        )
        json.dump({"apiVersion": "v1", "kind": "List", "items": pods},
                  manifest)
        manifest.close()
        if shutil.which("kubectl"):
            subprocess.run(["kubectl", "apply", "-f", manifest.name],
                           check=True)
        else:
            raise RuntimeError(
                f"kubectl not available; worker pod manifest written to "
                f"{manifest.name}"
            )

    async def stop_workers(self, job_id, force=False):
        import shutil

        if shutil.which("kubectl"):
            subprocess.run(
                ["kubectl", "delete", "pod", "-n", self.namespace,
                 "-l", f"arroyo/job_id={job_id}",
                 "--wait=false" if not force else "--force"],
                check=False,
            )


def make_scheduler(kind: str) -> Scheduler:
    return {
        "embedded": EmbeddedScheduler,
        "process": ProcessScheduler,
        "manual": ManualScheduler,
        "kubernetes": KubernetesScheduler,
    }[kind]()
