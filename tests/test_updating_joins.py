"""Updating (non-windowed) joins with retractions — mirrors the reference's
updating_{inner,left,right,full}_join.sql queries."""

import asyncio
import json

import pytest

from arroyo_tpu.engine import Engine
from arroyo_tpu.sql import plan_query
from arroyo_tpu.sql.lexer import SqlError

IMPULSE = """
CREATE TABLE impulse WITH (
  connector = 'impulse', event_rate = '1000000',
  message_count = '40', start_time = '0'
);
CREATE VIEW impulse_odd AS (
  SELECT counter FROM impulse WHERE counter % 2 == 1
);
"""


def run_to_debezium(sql, tmp_path, parallelism=1):
    out = tmp_path / "out.json"
    plan = plan_query(
        sql.replace("$out", str(out)), parallelism=parallelism
    )

    async def go():
        eng = Engine(plan.graph).start()
        await eng.join(60)

    asyncio.run(go())
    state = {}
    ops = {"c": 0, "d": 0}
    with open(out) as f:
        for line in f:
            if not line.strip():
                continue
            env = json.loads(line)
            ops[env["op"]] = ops.get(env["op"], 0) + 1
            row = env["before"] if env["op"] == "d" else env["after"]
            k = json.dumps(row, sort_keys=True)
            if env["op"] == "d":
                state[k] = state.get(k, 0) - 1
            else:
                state[k] = state.get(k, 0) + 1
    final = [json.loads(k) for k, v in state.items() if v > 0 for _ in range(v)]
    return final, ops


def test_updating_inner_join(tmp_path):
    """reference updating_inner_join.sql: impulse ⋈ odd-only view."""
    final, ops = run_to_debezium(
        IMPULSE
        + """
        CREATE TABLE output (left_count BIGINT, right_count BIGINT) WITH (
          connector = 'single_file', path = '$out',
          format = 'debezium_json', type = 'sink'
        );
        INSERT INTO output
        SELECT A.counter, B.counter
        FROM impulse A
        JOIN impulse_odd B ON A.counter = B.counter;
        """,
        tmp_path,
    )
    got = sorted(r["left_count"] for r in final)
    assert got == list(range(1, 40, 2))  # odds only
    assert all(r["left_count"] == r["right_count"] for r in final)


def test_updating_left_join(tmp_path):
    # separate sources: the left table lands instantly, the right side is
    # realtime-paced, so the left side's null-padded rows DETERMINISTICALLY
    # precede their matches (a shared fanned-out source makes side arrival
    # order scheduler-dependent, and either order is legal join semantics)
    final, ops = run_to_debezium(
        """
        CREATE TABLE lsrc WITH (
          connector = 'impulse', event_rate = '100000', realtime = 'true',
          message_count = '40'
        );
        CREATE TABLE rsrc WITH (
          connector = 'impulse', event_rate = '150', realtime = 'true',
          message_count = '40'
        );
        CREATE TABLE output (l BIGINT, r BIGINT) WITH (
          connector = 'single_file', path = '$out',
          format = 'debezium_json', type = 'sink'
        );
        INSERT INTO output
        SELECT A.counter, B.counter
        FROM lsrc A
        LEFT JOIN (
          SELECT counter FROM rsrc WHERE counter % 2 == 1
        ) B ON A.counter = B.counter;
        """,
        tmp_path,
    )
    # every left row survives; evens keep a null right side
    assert sorted(r["l"] for r in final) == list(range(40))
    nulls = [r for r in final if r["r"] is None]
    assert sorted(r["l"] for r in nulls) == list(range(0, 40, 2))
    # the odd rows' null-padded versions were retracted as matches arrived
    assert ops["d"] >= 1


def test_updating_right_join(tmp_path):
    final, _ = run_to_debezium(
        IMPULSE
        + """
        CREATE TABLE output (l BIGINT, r BIGINT) WITH (
          connector = 'single_file', path = '$out',
          format = 'debezium_json', type = 'sink'
        );
        INSERT INTO output
        SELECT A.counter, B.counter
        FROM impulse_odd A
        RIGHT JOIN impulse B ON A.counter = B.counter;
        """,
        tmp_path,
    )
    assert sorted(r["r"] for r in final) == list(range(40))
    assert sorted(r["r"] for r in final if r["l"] is None) == list(
        range(0, 40, 2)
    )


def test_updating_full_join_with_updating_inputs(tmp_path):
    """reference updating_full_join.sql shape: full join of two updating
    aggregates (retraction-consuming join)."""
    from arroyo_tpu.config import update

    with update(pipeline={"update_aggregate_flush_interval": 0.05}):
        final, ops = run_to_debezium(
            """
            CREATE TABLE impulse WITH (
              connector = 'impulse', event_rate = '8000', realtime = 'true',
              message_count = '3000', start_time = '0'
            );
            CREATE TABLE output (k BIGINT, lc BIGINT, rc BIGINT) WITH (
              connector = 'single_file', path = '$out',
              format = 'debezium_json', type = 'sink'
            );
            INSERT INTO output
            SELECT coalesce(A.k, B.k), A.cnt, B.cnt FROM (
              SELECT counter % 4 as k, count(*) as cnt FROM impulse
              WHERE counter % 2 = 0 GROUP BY 1
            ) A
            FULL JOIN (
              SELECT counter % 4 as k, count(*) as cnt FROM impulse
              WHERE counter % 4 = 1 GROUP BY 1
            ) B ON A.k = B.k;
            """,
            tmp_path,
        )
    # exact final multiset: every intermediate count was retracted
    assert len(final) == 3, final
    got = {r["k"]: (r["lc"], r["rc"]) for r in final}
    # evens: k=0 and k=2 get 750 each; k%4==1 side: k=1 gets 750
    assert got == {0: (750, None), 2: (750, None), 1: (None, 750)}
    assert ops["d"] > 0  # incremental counts retracted along the way


def test_updating_join_requires_debezium_sink(tmp_path):
    with pytest.raises(SqlError, match="debezium"):
        plan_query(
            IMPULSE
            + f"""
            CREATE TABLE output (l BIGINT, r BIGINT) WITH (
              connector = 'single_file', path = '{tmp_path}/x.json',
              format = 'json', type = 'sink'
            );
            INSERT INTO output
            SELECT A.counter, B.counter FROM impulse A
            JOIN impulse_odd B ON A.counter = B.counter;
            """
        )


def test_updating_join_checkpoint_restore(tmp_path):
    sql = """
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '15000', realtime = 'true',
      message_count = '4000', start_time = '0'
    );
    CREATE VIEW odd AS (SELECT counter FROM impulse WHERE counter % 2 == 1);
    CREATE TABLE output (l BIGINT, r BIGINT) WITH (
      connector = 'single_file', path = '$OUT',
      format = 'debezium_json', type = 'sink'
    );
    INSERT INTO output
    SELECT A.counter, B.counter FROM impulse A
    LEFT JOIN odd B ON A.counter = B.counter;
    """.replace("$OUT", str(tmp_path / "out.json"))
    url = str(tmp_path / "ck")

    async def phase1():
        plan = plan_query(sql, parallelism=2)
        eng = Engine(plan.graph, job_id="uj", storage_url=url).start()
        await asyncio.sleep(0.12)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(phase1())

    async def phase2():
        plan = plan_query(sql, parallelism=2)
        eng = Engine(plan.graph, job_id="uj", storage_url=url).start()
        await eng.join(60)

    asyncio.run(phase2())

    state = {}
    with open(tmp_path / "out.json") as f:
        for line in f:
            if line.strip():
                env = json.loads(line)
                row = env["before"] if env["op"] == "d" else env["after"]
                k = json.dumps(row, sort_keys=True)
                state[k] = state.get(k, 0) + (-1 if env["op"] == "d" else 1)
    final = [json.loads(k) for k, v in state.items() if v > 0 for _ in range(v)]
    assert sorted(r["l"] for r in final) == list(range(4000))
    assert sorted(r["l"] for r in final if r["r"] is None) == list(
        range(0, 4000, 2)
    )


def _count_bulk_hits(monkeypatch):
    """Patch UpdatingJoinOperator._inner_bulk to count engagements so a
    silent fallback to the per-row path can't pass the bulk tests
    vacuously."""
    import arroyo_tpu.operators.updating_join as uj

    hits = {"bulk": 0, "slow": 0}
    orig = uj.UpdatingJoinOperator._inner_bulk

    def spy(self, batch, side, ts):
        r = orig(self, batch, side, ts)
        hits["bulk" if r is not None else "slow"] += 1
        return r

    monkeypatch.setattr(uj.UpdatingJoinOperator, "_inner_bulk", spy)
    return hits


def test_updating_inner_join_bulk_probe_path(tmp_path, monkeypatch):
    """The device-probe bulk path (inner, append-only batches) must
    produce the same net debezium state as the per-row path (VERDICT r3
    item 4: updating join inner core rides the merge-join probe)."""
    from arroyo_tpu.config import update

    hits = _count_bulk_hits(monkeypatch)
    sql = (
        IMPULSE
        + """
        CREATE TABLE output (left_count BIGINT, right_count BIGINT) WITH (
          connector = 'single_file', path = '$out',
          format = 'debezium_json', type = 'sink'
        );
        INSERT INTO output
        SELECT A.counter, B.counter
        FROM impulse A
        JOIN impulse_odd B ON A.counter = B.counter;
        """
    )
    with update(tpu={"device_join_force": True, "device_join_min_rows": 0}):
        final, ops = run_to_debezium(sql, tmp_path)
    got = sorted(r["left_count"] for r in final)
    assert got == list(range(1, 40, 2))
    assert all(r["left_count"] == r["right_count"] for r in final)
    assert hits["bulk"] > 0 and hits["slow"] == 0


def test_updating_join_bulk_falls_back_on_retracts(tmp_path, monkeypatch):
    """A retract-carrying input (updating aggregate upstream, so batches
    carry __updating_meta) must take the per-row path and still produce
    the correct net state with the force flag on."""
    from arroyo_tpu.config import update

    hits = _count_bulk_hits(monkeypatch)
    sql = (
        IMPULSE
        + """
        CREATE VIEW agg AS (
          SELECT counter % 4 AS g, count(*) AS c FROM impulse GROUP BY 1
        );
        CREATE TABLE output (g BIGINT, c BIGINT, counter BIGINT) WITH (
          connector = 'single_file', path = '$out',
          format = 'debezium_json', type = 'sink'
        );
        INSERT INTO output
        SELECT A.g, A.c, B.counter
        FROM agg A
        JOIN impulse B ON A.g = B.counter;
        """
    )
    baseline, _ = run_to_debezium(sql, tmp_path / "base")
    with update(tpu={"device_join_force": True, "device_join_min_rows": 0}):
        final, _ = run_to_debezium(sql, tmp_path / "bulk")
    key = lambda rows: sorted(json.dumps(r, sort_keys=True) for r in rows)
    assert key(final) == key(baseline)
    assert len(final) > 0


def test_updating_inner_join_bulk_probe_strings(tmp_path, monkeypatch):
    """Bulk path with string join keys (joint-dictionary probe) against
    larger per-key fan-out; net state must match the per-row run."""
    from arroyo_tpu.config import update

    hits = _count_bulk_hits(monkeypatch)
    sql = (
        IMPULSE
        + """
        CREATE VIEW lab AS (
          SELECT counter, concat('k', counter % 5) AS tag FROM impulse
        );
        CREATE TABLE output (lc BIGINT, rc BIGINT) WITH (
          connector = 'single_file', path = '$out',
          format = 'debezium_json', type = 'sink'
        );
        INSERT INTO output
        SELECT A.counter, B.counter
        FROM lab A
        JOIN lab B ON A.tag = B.tag;
        """
    )
    baseline, _ = run_to_debezium(sql, tmp_path / "base")
    with update(tpu={"device_join_force": True, "device_join_min_rows": 0}):
        bulk, _ = run_to_debezium(sql, tmp_path / "bulk")
    key = lambda rows: sorted(json.dumps(r, sort_keys=True) for r in rows)
    assert key(bulk) == key(baseline)
    assert len(baseline) == 40 * 8  # 5 tags x 8 rows each -> 8x8 pairs x 5
    assert hits["bulk"] > 0
