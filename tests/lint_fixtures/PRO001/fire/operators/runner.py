"""MUST fire PRO001: CheckpointMsg is not dispatched in _handle_control."""
from .control import CheckpointMsg, CommitMsg, StopMsg


class Runner:
    async def _handle_control(self, msg):
        if isinstance(msg, CommitMsg):
            return "commit"
        elif isinstance(msg, StopMsg):
            return "stop"
        # CheckpointMsg silently dropped

    async def source_handle_control(self, msg):
        if isinstance(msg, (CheckpointMsg, StopMsg, CommitMsg)):
            return "ok"
