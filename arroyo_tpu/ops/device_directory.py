"""Device-resident slot directory: the (bin, key) -> slot group index on
the accelerator.

SURVEY.md §7 flags "hash tables on TPU" as a hard part and prescribes
sorted-key segment ops + binary search over device arrays rather than true
hash maps. This module implements that design as the third directory tier
(config flag `tpu.device_directory`; host python dict and native C++
open-addressing remain the fallbacks — reference analog: the in-engine
hash-aggregation state of
/root/reference/crates/arroyo-worker/src/arrow/tumbling_aggregating_window.rs:66-110):

  device state:  tab_hash [C] int64, sorted ascending with SENT (int64
                 max) padding; tab_slot [C] the slot of each entry.
  assign():      h = splitmix64(bin, key words)      [host numpy, O(n)]
                 jitted lookup: searchsorted(tab_hash, h) -> found, slot
                 NEW groups only (steady state: none) fall back to the
                 host: allocate slots from the free list, record (bin,
                 key, slot, hash) in O(new) bookkeeping, and dispatch a
                 jitted merge that splices the new sorted hashes into the
                 table by scatter (searchsorted positions — no sort).
  take_bin():    bins/keys/slots come from the host bookkeeping (built
                 incrementally, O(new groups) per batch); a jitted
                 remove compacts the emitted hashes out of the table
                 (cumsum positions + scatter — no sort).

Per-batch work therefore no longer round-trips the batch's UNIQUE keys
through a host hash table (the structural cap the round-3 verdict names):
after a window's first batches, every key is a device searchsorted hit and
the host does O(0) dictionary work.

Exactness: groups are identified by their 64-bit mixed hash. Two distinct
(bin, key) groups colliding on all 64 bits would silently merge; with
splitmix64 that is ~n^2/2^65 (≈3e-8 at one million live groups) and is
accepted for this tier (the python/native tiers are exact); the flag
defaults off. Per-operator bound: tumbling/sliding keep at most one
window span of groups live (n = groups/bin x bins/window); the updating
aggregate keeps all live keys (n = live cardinality, TTL-evicted) — at
the default 1<<20 max_keys_per_shard both stay under ~4e-8. For
runtime evidence, `tpu.device_directory_audit` samples found rows each
assign and verifies their key against the host bookkeeping via the
reverse hash index — a detected merge raises instead of corrupting
aggregates (cost: <=64 host tuple compares per batch).

Round-5 widening (VERDICT r4 item 4): the directory now serves the
updating aggregate's surface — slot-valued peek_bin, keys_for_slots,
slots_for_keys point lookups, and targeted remove(bin, keys) — via a
lazily-built host reverse index that is invalidated on mutation and
rebuilt O(live) only when the steady state actually changed (reference
analog: incremental_aggregator.rs:77-90's key-level state map).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..types import hash_arrays, hash_column
from ._jax import safe_donate
from .aggregates import _bucket

SENT = np.int64(np.iinfo(np.int64).max)

_FNS: Dict[str, object] = {}


def _fns():
    """Lazily-built jitted table ops (shape-specialized by jax's cache)."""
    if _FNS:
        return _FNS
    import jax

    from ..parallel.mesh import _get_jnp

    jnp = _get_jnp()

    @jax.jit
    def lookup(tab_hash, tab_slot, q):
        idx = jnp.searchsorted(tab_hash, q)
        idx = jnp.clip(idx, 0, tab_hash.shape[0] - 1)
        found = tab_hash[idx] == q
        return found, tab_slot[idx]

    @partial(jax.jit, donate_argnums=safe_donate(0, 1))
    def merge(tab_hash, tab_slot, add_h, add_slot):
        # splice sorted add_h (SENT-padded) into sorted tab_hash by
        # computing every element's merged position and scattering; SENT
        # padding from either side lands past the end and is dropped.
        C = tab_hash.shape[0]
        real_add = add_h != SENT
        n_add = real_add.sum()
        pos_old = jnp.arange(C) + jnp.searchsorted(add_h, tab_hash,
                                                   side="left")
        pos_old = jnp.where(tab_hash == SENT, C, pos_old)
        pos_new = jnp.arange(add_h.shape[0]) + jnp.searchsorted(
            tab_hash, add_h, side="left"
        )
        pos_new = jnp.where(real_add, pos_new, C)
        out_h = jnp.full((C,), SENT, dtype=tab_hash.dtype)
        out_s = jnp.zeros((C,), dtype=tab_slot.dtype)
        out_h = out_h.at[pos_old].set(tab_hash, mode="drop")
        out_s = out_s.at[pos_old].set(tab_slot, mode="drop")
        out_h = out_h.at[pos_new].set(add_h, mode="drop")
        out_s = out_s.at[pos_new].set(add_slot, mode="drop")
        return out_h, out_s, n_add

    @partial(jax.jit, donate_argnums=safe_donate(0, 1))
    def remove(tab_hash, tab_slot, del_h):
        # drop entries whose hash appears in sorted del_h (SENT-padded),
        # then compact left to restore the sorted-real/SENT-tail layout
        C = tab_hash.shape[0]
        idx = jnp.clip(jnp.searchsorted(del_h, tab_hash), 0,
                       del_h.shape[0] - 1)
        drop = (del_h[idx] == tab_hash) | (tab_hash == SENT)
        keep = ~drop
        pos = jnp.cumsum(keep) - 1
        pos = jnp.where(keep, pos, C)
        out_h = jnp.full((C,), SENT, dtype=tab_hash.dtype)
        out_s = jnp.zeros((C,), dtype=tab_slot.dtype)
        out_h = out_h.at[pos].set(tab_hash, mode="drop")
        out_s = out_s.at[pos].set(tab_slot, mode="drop")
        return out_h, out_s

    from ..obs import device as obs_device

    _FNS.update(
        lookup=obs_device.InstrumentedJit("dir.lookup", lookup),
        merge=obs_device.InstrumentedJit("dir.merge", merge),
        remove=obs_device.InstrumentedJit("dir.remove", remove),
    )
    return _FNS


def _i64_view(c: np.ndarray) -> np.ndarray:
    c = np.asarray(c)
    if c.dtype == np.uint64:
        return c.view(np.int64)
    if c.dtype.kind == "M":
        return c.view("i8")
    return c.astype(np.int64, copy=False)


class _BinData:
    """Per-bin host bookkeeping: column chunks appended O(new groups) per
    batch, coalesced on first read."""

    __slots__ = ("keys", "slots", "hashes")

    def __init__(self):
        self.keys: List[np.ndarray] = []   # chunks [k, W]
        self.slots: List[np.ndarray] = []
        self.hashes: List[np.ndarray] = []

    def coalesce(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if len(self.slots) > 1:
            self.keys = [np.concatenate(self.keys, axis=0)]
            self.slots = [np.concatenate(self.slots)]
            self.hashes = [np.concatenate(self.hashes)]
        return self.keys[0], self.slots[0], self.hashes[0]

    def __len__(self):
        return sum(len(s) for s in self.slots)


class DeviceSlotDirectory:
    """N-int64-key directory over the device-resident sorted hash table,
    API-compatible with ops.native.NativeSlotDirectory (assign /
    take_bin / take_bin_arrays / bin_entries / peek_bin / by_bin /
    items). Keys surface as n-tuples; take_bin_arrays is the vectorized
    emission path."""

    def __init__(self, n_keys: int = 1, table_capacity: int = 1 << 16):
        import jax

        from ..parallel.mesh import _get_jnp

        jnp = _get_jnp()
        self.n_keys = n_keys
        self._stride = max(1, n_keys)
        self._cap = int(table_capacity)
        self.tab_hash = jnp.full((self._cap,), SENT, dtype=jnp.int64)
        self.tab_slot = jnp.zeros((self._cap,), dtype=jnp.int64)
        self._n_entries = 0
        self._bins: Dict[int, _BinData] = {}
        self.free: List[int] = []
        self.next_slot = 0
        self._q_buckets = (1024, 8192, 65536)
        self._jnp = jnp
        self._jax = jax
        # lazy host indexes (slot -> (bin, key), per-bin key -> slot,
        # hash -> key); rebuilt O(live) on first use after any mutation
        self._rev: Optional[Dict[int, tuple]] = None
        self._bin_index: Optional[Dict[int, Dict[tuple, int]]] = None
        self._hash_index: Optional[Dict[int, tuple]] = None
        from ..config import config as _cfg

        self._audit = bool(_cfg().tpu.device_directory_audit)

    # -- host bookkeeping ----------------------------------------------------

    @property
    def n_live(self) -> int:
        return self._n_entries

    def required_capacity(self) -> int:
        return self.next_slot + 1

    def _hash(self, bins: np.ndarray, key_cols: List[np.ndarray]) -> np.ndarray:
        h = hash_arrays(
            [hash_column(np.asarray(bins))]
            + [hash_column(_i64_view(c)) for c in key_cols]
        ).view(np.int64)
        # SENT is the table's empty sentinel; remap the 1-in-2^64 hash
        return np.where(h == SENT, SENT - 1, h)

    def _pad_sorted(self, v: np.ndarray, slots: Optional[np.ndarray] = None):
        p = _bucket(len(v), self._q_buckets)
        out = np.full(p, SENT, dtype=np.int64)
        out[: len(v)] = v
        if slots is None:
            return out
        s = np.zeros(p, dtype=np.int64)
        s[: len(v)] = slots
        return out, s

    def _grow_table(self, need: int):
        while self._cap < need:
            self._cap *= 2
        jnp = self._jnp
        h = np.asarray(self.tab_hash)
        s = np.asarray(self.tab_slot)
        nh = np.full(self._cap, SENT, dtype=np.int64)
        ns = np.zeros(self._cap, dtype=np.int64)
        nh[: len(h)] = h
        ns[: len(s)] = s
        self.tab_hash = jnp.asarray(nh)
        self.tab_slot = jnp.asarray(ns)

    # -- hot path ------------------------------------------------------------

    def assign(self, bins: np.ndarray, key_cols: List[np.ndarray]) -> np.ndarray:
        n = len(bins)
        if n == 0:
            return np.empty(0, dtype=np.int64)
        bins = np.asarray(bins)
        kc = [_i64_view(c) for c in key_cols] if key_cols else [
            np.zeros(n, dtype=np.int64)
        ]
        h = self._hash(bins, kc)
        q = self._pad_sorted_queries(h)
        found_d, slot_d = _fns()["lookup"](self.tab_hash, self.tab_slot, q)
        found_d, slot_d = self._jax.device_get((found_d, slot_d))
        found = found_d[:n]
        out = slot_d[:n].copy()
        if self._audit and found.any():
            self._audit_found(h, found, kc)
        if not found.all():
            new_rows = np.nonzero(~found)[0]
            nh = h[new_rows]
            uniq_h, first = np.unique(nh, return_index=True)
            k = len(uniq_h)
            # slot allocation: free list first, then fresh
            reuse = min(k, len(self.free))
            slots_new = np.empty(k, dtype=np.int64)
            if reuse:
                slots_new[:reuse] = self.free[-reuse:]
                del self.free[-reuse:]
            if k > reuse:
                slots_new[reuse:] = np.arange(
                    self.next_slot, self.next_slot + (k - reuse)
                )
                self.next_slot += k - reuse
            first_abs = new_rows[first]
            kmat = np.stack([c[first_abs] for c in kc], axis=1)
            gbins = bins[first_abs]
            # per-bin bookkeeping, columnar: one append per touched bin
            border = np.argsort(gbins, kind="stable")
            gb = gbins[border]
            cut = np.nonzero(np.diff(gb))[0] + 1
            for seg in np.split(border, cut):
                b_seg = int(gbins[seg[0]])
                bd = self._bins.setdefault(b_seg, _BinData())
                bd.keys.append(kmat[seg])
                bd.slots.append(slots_new[seg])
                bd.hashes.append(uniq_h[seg])
                self._index_add(b_seg, kmat[seg], slots_new[seg],
                                uniq_h[seg])
            # splice into the device table
            if self._n_entries + k > self._cap - 1:
                self._grow_table(2 * (self._n_entries + k))
            add_h, add_s = self._pad_sorted(uniq_h, slots_new)
            self.tab_hash, self.tab_slot, _ = _fns()["merge"](
                self.tab_hash, self.tab_slot,
                self._jnp.asarray(add_h), self._jnp.asarray(add_s),
            )
            self._n_entries += k
            out[new_rows] = slots_new[np.searchsorted(uniq_h, nh)]
        return out

    def _audit_found(self, h: np.ndarray, found: np.ndarray,
                     kc: List[np.ndarray]):
        """Verify a sample of lookup hits against the host bookkeeping:
        a 64-bit collision would silently merge two groups — raise with
        both keys instead (tpu.device_directory_audit)."""
        if self._hash_index is None:
            self._build_indexes()
        for r in np.nonzero(found)[0][:64]:
            key = () if self.n_keys == 0 else tuple(int(c[r]) for c in kc)
            expect = self._hash_index.get(int(h[r]))
            if expect is not None and expect != key:
                raise RuntimeError(
                    "device directory 64-bit hash collision: groups "
                    f"{expect} and {key} share hash {int(h[r])}"
                )

    def _pad_sorted_queries(self, h: np.ndarray):
        return self._jnp.asarray(self._pad_sorted(h))

    # -- emission ------------------------------------------------------------

    def _drop_hashes(self, hashes: np.ndarray):
        if not len(hashes):
            return
        del_h = self._pad_sorted(np.sort(hashes))
        self.tab_hash, self.tab_slot = _fns()["remove"](
            self.tab_hash, self.tab_slot, self._jnp.asarray(del_h)
        )
        self._n_entries -= len(hashes)

    def take_bin(self, b: int) -> Tuple[List[tuple], np.ndarray]:
        kcols, slots = self.take_bin_arrays(b)
        if self.n_keys == 0:
            return [() for _ in range(len(slots))], slots
        keys = [tuple(int(c[i]) for c in kcols) for i in range(len(slots))]
        return keys, slots

    def take_bin_arrays(self, b: int) -> Tuple[List[np.ndarray], np.ndarray]:
        bd = self._bins.pop(int(b), None)
        if bd is None:
            z = np.empty(0, dtype=np.int64)
            return [z for _ in range(self._stride)], z
        kmat, slots, hashes = bd.coalesce()
        self._drop_hashes(hashes)
        self.free.extend(slots.tolist())
        self._index_drop(int(b), kmat, slots, hashes)
        return [kmat[:, j] for j in range(self._stride)], slots

    def bin_entries(self, b: int) -> Tuple[np.ndarray, np.ndarray]:
        bd = self._bins.get(int(b))
        if bd is None:
            z = np.empty(0, dtype=np.int64)
            return np.empty((0, self._stride), dtype=np.int64), z
        kmat, slots, _ = bd.coalesce()
        return kmat, slots

    @property
    def by_bin(self):
        return {b: True for b in self._bins}

    # -- host indexes (updating-aggregate surface) ---------------------------

    def _key_of_row(self, kmat: np.ndarray, i: int) -> tuple:
        """Key spelling must match items()/take_bin and the native/python
        tiers: the unkeyed directory (n_keys == 0, synthetic zero column)
        surfaces () — not (0,)."""
        if self.n_keys == 0:
            return ()
        return tuple(int(x) for x in kmat[i])

    def _build_indexes(self):
        """One O(live) pass building every lazy index. Only the FIRST use
        pays it: every later mutation (insert / emission / remove)
        maintains the indexes incrementally, so steady-state batches do
        O(new)/O(emitted) index work — never O(live)."""
        rev: Dict[int, tuple] = {}
        bi: Dict[int, Dict[tuple, int]] = {}
        hi: Dict[int, tuple] = {}
        for b, bd in self._bins.items():
            kmat, slots, hashes = bd.coalesce()
            bmap: Dict[tuple, int] = {}
            for i in range(len(slots)):
                key = self._key_of_row(kmat, i)
                slot = int(slots[i])
                bmap[key] = slot
                rev[slot] = (b, key)
                hi[int(hashes[i])] = key
            bi[b] = bmap
        self._rev, self._bin_index, self._hash_index = rev, bi, hi

    def _index_add(self, b: int, kmat: np.ndarray, slots: np.ndarray,
                   hashes: np.ndarray):
        if self._rev is None:
            return  # indexes not materialized yet; first use builds all
        bmap = self._bin_index.setdefault(int(b), {})
        for i in range(len(slots)):
            key = self._key_of_row(kmat, i)
            slot = int(slots[i])
            bmap[key] = slot
            self._rev[slot] = (int(b), key)
            self._hash_index[int(hashes[i])] = key

    def _index_drop(self, b: int, kmat: np.ndarray, slots: np.ndarray,
                    hashes: np.ndarray):
        if self._rev is None:
            return
        bmap = self._bin_index.get(int(b))
        for i in range(len(slots)):
            key = self._key_of_row(kmat, i)
            self._rev.pop(int(slots[i]), None)
            self._hash_index.pop(int(hashes[i]), None)
            if bmap is not None:
                bmap.pop(key, None)
        if bmap is not None and not bmap:
            self._bin_index.pop(int(b), None)

    def keys_for_slots(self, slots: np.ndarray) -> List[Optional[tuple]]:
        """(bin, key) per slot via the lazy reverse index (the updating
        aggregate's dirty tracking; native-directory parity)."""
        if self._rev is None:
            self._build_indexes()
        return [self._rev.get(int(s)) for s in np.asarray(slots)]

    def slots_for_keys(self, b: int, keys: List[tuple]) -> Dict[tuple, int]:
        """Point lookups for a (usually small) key set in one bin."""
        if self._bin_index is None:
            self._build_indexes()
        bmap = self._bin_index.get(int(b), {})
        return {k: bmap[k] for k in keys if k in bmap}

    def remove(self, b: int, keys: List[tuple]) -> np.ndarray:
        """Targeted removal (TTL eviction): drop specific keys from a bin's
        bookkeeping and the device table; returns freed slots."""
        bd = self._bins.get(int(b))
        if bd is None or not keys:
            return np.empty(0, dtype=np.int64)
        kmat, slots, hashes = bd.coalesce()
        kill = set(keys)
        mask = np.fromiter(
            (self._key_of_row(kmat, i) in kill
             for i in range(len(slots))),
            dtype=bool, count=len(slots),
        )
        if not mask.any():
            return np.empty(0, dtype=np.int64)
        freed = slots[mask]
        self._drop_hashes(hashes[mask])
        keep = ~mask
        if keep.any():
            bd.keys = [kmat[keep]]
            bd.slots = [slots[keep]]
            bd.hashes = [hashes[keep]]
        else:
            self._bins.pop(int(b), None)
        self.free.extend(freed.tolist())
        self._index_drop(int(b), kmat[mask], freed, hashes[mask])
        return freed

    def peek_bin(self, b: int):
        """{key tuple: slot} — slot-valued like the native directory (the
        updating aggregate resolves emission slots from it)."""
        bd = self._bins.get(int(b))
        if bd is None:
            return None
        kmat, slots, _ = bd.coalesce()
        if not len(slots):
            return None
        if self.n_keys == 0:
            return {(): int(slots[0])}
        return {
            tuple(int(x) for x in kmat[i]): int(slots[i])
            for i in range(len(slots))
        }

    def live_bins(self) -> List[int]:
        return sorted(self._bins)

    def bins_up_to(self, limit: int) -> List[int]:
        return sorted(b for b in self._bins if b < limit)

    def items(self):
        for b in sorted(self._bins):
            kmat, slots = self.bin_entries(b)
            for i in range(len(slots)):
                k = () if self.n_keys == 0 else tuple(
                    int(x) for x in kmat[i]
                )
                yield int(b), k, int(slots[i])
