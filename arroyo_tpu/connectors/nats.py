"""NATS connector: core + JetStream durable consumers (reference:
crates/arroyo-connectors/src/nats/, 1,029 LoC). JetStream consumer
positions checkpoint via stream sequence numbers. Client gated on nats-py.
"""

from __future__ import annotations

from typing import Optional

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from ._gated import require_client
from .base import ConnectionSchema, Connector, register_connector


class NatsSource(SourceOperator):
    def __init__(self, servers: str, subject: str, jetstream: bool,
                 schema, format, bad_data):
        super().__init__("nats_source")
        self.servers = servers
        self.subject = subject
        self.jetstream = jetstream
        self.out_schema = schema
        self.format = format
        self.bad_data = bad_data
        self.sequence: Optional[int] = None  # JetStream resume position

    def tables(self):
        from ..state.table_config import global_table

        return {"nats": global_table("nats")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("nats")
            self.sequence = table.get(ctx.task_info.task_index)

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("nats")
            table.put(ctx.task_info.task_index, self.sequence)

    async def run(self, ctx, collector) -> SourceFinishType:
        import asyncio

        nats = require_client("nats")
        deser = Deserializer(self.out_schema, format=self.format or "json",
                             bad_data=self.bad_data)
        nc = await nats.connect(self.servers)
        try:
            if self.jetstream:
                js = nc.jetstream()
                opts = {}
                if self.sequence is not None:
                    opts = {"opt_start_seq": self.sequence + 1}
                sub = await js.subscribe(self.subject, **opts)
            else:
                sub = await nc.subscribe(self.subject)
            async def on_message(msg):
                for row in deser.deserialize_slice(
                    msg.data, error_reporter=ctx.error_reporter
                ):
                    ctx.buffer_row(row)
                if self.jetstream and msg.metadata:
                    self.sequence = msg.metadata.sequence.stream

            finish = await self.poll_async_iter(
                sub.messages.__aiter__(), ctx, collector, on_message
            )
            if finish is not None:
                return finish
            await self.flush_buffer(ctx, collector)
        finally:
            await nc.close()
        return SourceFinishType.FINAL


class NatsSink(Operator):
    def __init__(self, servers: str, subject: str, format):
        super().__init__("nats_sink")
        self.servers = servers
        self.subject = subject
        self.serializer = Serializer(format=format or "json")
        self.nc = None

    async def on_start(self, ctx):
        nats = require_client("nats")
        self.nc = await nats.connect(self.servers)

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        for rec in self.serializer.serialize(batch):
            await self.nc.publish(self.subject, rec)

    async def on_close(self, ctx, collector, is_eod: bool):
        if self.nc is not None:
            await self.nc.close()
        return None


@register_connector
class NatsConnector(Connector):
    name = "nats"
    description = "NATS core / JetStream source and sink"
    source = True
    sink = True
    config_schema = {
        "servers": {"type": "string", "required": True},
        "subject": {"type": "string", "required": True},
        "nats.stream": {"type": "string"},
    }

    def validate_options(self, options, schema):
        for k in ("servers", "subject"):
            if k not in options:
                raise ValueError(f"nats requires a {k} option")
        return {
            "servers": options["servers"],
            "subject": options["subject"],
            "jetstream": "nats.stream" in options
            or str(options.get("jetstream", "false")).lower() == "true",
        }

    def make_source(self, config, schema: ConnectionSchema):
        return NatsSource(config["servers"], config["subject"],
                          config.get("jetstream", False),
                          config.get("schema"), config.get("format"),
                          config.get("bad_data", "fail"))

    def make_sink(self, config, schema: ConnectionSchema):
        return NatsSink(config["servers"], config["subject"],
                        config.get("format"))
