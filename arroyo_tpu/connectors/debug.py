"""Debug connectors: vec (in-memory capture), stdout, blackhole, preview.

Capability parity with the reference's stdout/blackhole/preview sinks
(/root/reference/crates/arroyo-connectors/src/{stdout,blackhole,preview}).
`vec` is the in-process capture sink the test harness uses (the reference
uses its single_file connector for that; we offer both).
"""

from __future__ import annotations

import json
import sys
from typing import Optional

from ..operators.base import Operator, SourceFinishType, SourceOperator
from .base import ConnectionSchema, Connector, register_connector


class VecSink(Operator):
    """Collects all rows into an in-memory list (shared via config)."""

    def __init__(self, results: list, batches: Optional[list] = None):
        super().__init__("vec_sink")
        self.results = results
        self.batches = batches

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        if self.batches is not None:
            self.batches.append(batch)
        self.results.extend(batch.to_pylist())


class VecSource(SourceOperator):
    """Replays pre-built RecordBatches (benchmark/test source that isolates
    engine throughput from data generation)."""

    def __init__(self, batches: list, loops: int = 1):
        super().__init__("vec_source")
        self.batches = batches
        self.loops = loops
        self.position = 0  # (loop * len + idx), checkpointed

    def tables(self):
        from ..state.table_config import global_table

        return {"v": global_table("v")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("v")
            stored = table.get(ctx.task_info.task_index)
            if stored is not None:
                self.position = stored

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("v")
            table.put(ctx.task_info.task_index, self.position)

    async def run(self, ctx, collector):
        import asyncio

        total = len(self.batches) * self.loops
        while self.position < total:
            finish = await ctx.check_control(collector)
            if finish is not None:
                return finish
            await collector.collect(self.batches[self.position % len(self.batches)])
            self.position += 1
            await asyncio.sleep(0)
        return SourceFinishType.FINAL


@register_connector
class VecConnector(Connector):
    name = "vec"
    description = "in-memory capture sink / replay source for tests"
    source = True
    sink = True

    def make_source(self, config, schema):
        return VecSource(config["batches"], config.get("loops", 1))

    def make_sink(self, config, schema):
        return VecSink(config["results"], config.get("batches"))


class StdoutSink(Operator):
    def __init__(self, serializer=None):
        super().__init__("stdout_sink")
        self.serializer = serializer

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        if self.serializer is not None:
            for line in self.serializer.serialize(batch):
                sys.stdout.write(line.decode() + "\n")
        else:
            for row in batch.to_pylist():
                sys.stdout.write(json.dumps(row, default=str) + "\n")
        sys.stdout.flush()


@register_connector
class StdoutConnector(Connector):
    name = "stdout"
    description = "writes each row as JSON to stdout"
    sink = True

    def make_sink(self, config, schema):
        from ..formats.ser import make_serializer

        ser = make_serializer(schema) if schema and schema.format else None
        return StdoutSink(ser)


class BlackholeSink(Operator):
    def __init__(self):
        super().__init__("blackhole_sink")
        self.rows = 0

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        self.rows += batch.num_rows


@register_connector
class BlackholeConnector(Connector):
    name = "blackhole"
    description = "null sink for benchmarking"
    sink = True

    def make_sink(self, config, schema):
        return BlackholeSink()


@register_connector
class PreviewConnector(Connector):
    name = "preview"
    description = "streams rows to the controller for UI preview"
    sink = True

    def make_sink(self, config, schema):
        # rows land in the shared session list that the API tails over its
        # websocket (in-process path); cross-process preview goes over gRPC
        return VecSink(config.setdefault("results", []))


class LatencyFileSink(Operator):
    """Appends one 'arrival_ns event_ts_ns' line per row, flushed per
    batch: end-to-end latency measurement in DISTRIBUTED runs, where the
    sink lives in a worker process and an in-memory capture can't cross
    the process boundary (bench.py --latency-distributed reads the
    file). Arrival time is taken once per batch — rows of a batch arrive
    together."""

    def __init__(self, path: str):
        super().__init__("latency_file_sink")
        self.path = path
        self._fh = None

    async def on_start(self, ctx):
        import os

        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._fh = open(self.path, "ab")

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        import time

        import numpy as np
        import pyarrow as pa

        from ..schema import TIMESTAMP_FIELD

        now = time.time_ns()
        names = batch.schema.names
        if TIMESTAMP_FIELD not in names:
            return
        ts = np.asarray(
            batch.column(names.index(TIMESTAMP_FIELD)).cast(pa.int64())
        )
        self._fh.write(
            b"".join(b"%d %d\n" % (now, t) for t in ts.tolist())
        )
        self._fh.flush()

    async def on_close(self, ctx, collector, is_eod):
        if self._fh is not None:
            self._fh.close()
        return None


@register_connector
class LatencyFileConnector(Connector):
    name = "latency_file"
    description = "per-row arrival/event-time log for latency benchmarks"
    sink = True
    config_schema = {"path": {"type": "string", "required": True}}

    def validate_options(self, options, schema):
        if "path" not in options:
            raise ValueError("latency_file requires a path option")
        return {"path": options["path"]}

    def make_sink(self, config, schema):
        return LatencyFileSink(config["path"])
