"""Console app.js validation (VERDICT r4 item 8).

The endpoint contract test (test_api.py) pins every API path app.js
names to a registered route, but never evaluates a line of it — a JS
syntax error would ship green. `node --check` is unavailable in this
image, so this scanner walks the source with full string/template/
comment/regex awareness and verifies bracket balance and terminated
literals — the class of error a truncated edit or unbalanced template
actually produces. It also pins the round-5 live-preview contract: the
SPA must open the preview output WEBSOCKET (not only poll).
"""

import os
import re

APP_JS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "arroyo_tpu", "api", "static", "app.js",
)

_REGEX_ALLOWED_BEFORE = set("=([{,;:!&|?+-*%~^<>")


def scan_js(src: str):
    """Returns (errors, bracket_depth_map). Modes: code, line/block
    comment, ' " strings, `template` (with ${ } nesting), /regex/."""
    errors = []
    stack = []          # open brackets as (char, line)
    mode = ["code"]     # mode stack; template pushes "tpl", ${ pushes code
    i, n, line = 0, len(src), 1
    last_sig = ""       # last significant char in code mode (regex vs div)
    while i < n:
        c = src[i]
        if c == "\n":
            line += 1
        m = mode[-1]
        if m == "line_comment":
            if c == "\n":
                mode.pop()
            i += 1
            continue
        if m == "block_comment":
            if c == "*" and i + 1 < n and src[i + 1] == "/":
                mode.pop()
                i += 2
                continue
            i += 1
            continue
        if m in ("'", '"'):
            if c == "\\":
                i += 2
                continue
            if c == "\n":
                errors.append(f"line {line}: unterminated string")
                mode.pop()
                i += 1
                continue
            if c == m:
                mode.pop()
                last_sig = '"'
            i += 1
            continue
        if m == "tpl":
            if c == "\\":
                i += 2
                continue
            if c == "`":
                mode.pop()
                last_sig = '"'
                i += 1
                continue
            if c == "$" and i + 1 < n and src[i + 1] == "{":
                mode.append("code")
                stack.append(("{", line))
                last_sig = ""
                i += 2
                continue
            i += 1
            continue
        if m == "regex":
            if c == "\\":
                i += 2
                continue
            if c == "[":
                mode.append("regex_class")
            elif c == "/":
                mode.pop()
                last_sig = '"'
                # flags
                while i + 1 < n and src[i + 1].isalpha():
                    i += 1
            elif c == "\n":
                errors.append(f"line {line}: unterminated regex")
                mode.pop()
            i += 1
            continue
        if m == "regex_class":
            if c == "\\":
                i += 2
                continue
            if c == "]":
                mode.pop()
            i += 1
            continue
        # ---- code mode ----
        if c == "/" and i + 1 < n and src[i + 1] == "/":
            mode.append("line_comment")
            i += 2
            continue
        if c == "/" and i + 1 < n and src[i + 1] == "*":
            mode.append("block_comment")
            i += 2
            continue
        if c in "'\"":
            mode.append(c)
            i += 1
            continue
        if c == "`":
            mode.append("tpl")
            i += 1
            continue
        if c == "/":
            # regex when the previous significant char can't end a value
            if last_sig == "" or last_sig in _REGEX_ALLOWED_BEFORE:
                mode.append("regex")
                i += 1
                continue
            last_sig = c
            i += 1
            continue
        if c in "([{":
            stack.append((c, line))
            last_sig = c
            i += 1
            continue
        if c in ")]}":
            if c == "}" and len(mode) > 1 and mode[-2] == "tpl" and (
                    not stack or stack[-1][0] != "{"):
                errors.append(f"line {line}: unbalanced template substitution")
                mode.pop()
                i += 1
                continue
            want = {")": "(", "]": "[", "}": "{"}[c]
            if not stack or stack[-1][0] != want:
                errors.append(f"line {line}: unmatched {c!r}")
            else:
                stack.pop()
                # closing a ${ } substitution returns to the template
                if c == "}" and len(mode) > 1 and mode[-2] == "tpl":
                    mode.pop()
            last_sig = c
            i += 1
            continue
        if not c.isspace():
            last_sig = c
        i += 1
    for ch, ln in stack:
        errors.append(f"line {ln}: unclosed {ch!r}")
    if mode != ["code"]:
        errors.append(f"EOF inside {mode[-1]}")
    return errors


def test_app_js_parses():
    src = open(APP_JS).read()
    errors = scan_js(src)
    assert not errors, "\n".join(errors)


def test_scanner_catches_real_breakage():
    """The scanner must actually flag the error classes it claims to
    catch — truncation, unbalanced braces, unterminated strings."""
    src = open(APP_JS).read()
    assert scan_js(src[: len(src) // 2])  # truncated file
    assert scan_js('const x = { a: 1;\n')
    assert scan_js('const s = "unterminated\nconst y = 1;')
    assert scan_js("const t = `tpl ${ broken;\n")
    # and must NOT flag tricky-but-valid constructs
    assert not scan_js('const r = /[&<>"\']/g; const d = a / b / c;')
    assert not scan_js('const t = `a ${x ? `${y}` : "z"} b`;')


def test_live_preview_contract():
    """Round-5 UI contract: the SQL editor's preview tails rows over the
    preview output websocket (with the poll fallback retained), and the
    ws path it builds is a registered route."""
    src = open(APP_JS).read()
    assert "new WebSocket" in src
    assert "/output/ws" in src
    assert "pollPreview" in src  # fallback kept
    from arroyo_tpu.api.openapi import ROUTES

    paths = {p for _, p, *_ in ROUTES}
    assert "/pipelines/preview/{id}/output/ws" in paths
    # renderPreview is fed from the ws message handler
    assert re.search(r"onmessage\s*=[^;]*renderPreview",
                     src, re.S | re.M) or "ws.onmessage" in src


def test_metric_graph_contract():
    """Round-5 UI depth: operator metrics render as axis-labeled line
    charts (reference webui graphs), not bare sparklines."""
    src = open(APP_JS).read()
    assert "function lineChart" in src
    assert "lineChart(rates" in src
    assert "function sparkline" not in src  # dead path removed
    css = open(os.path.join(os.path.dirname(APP_JS), "style.css")).read()
    assert ".chart .grid" in css and ".chart .ax" in css
