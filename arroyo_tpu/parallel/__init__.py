from .mesh import key_mesh  # noqa: F401
from .sharded_state import ShardedAccumulator  # noqa: F401
