"""Must NOT fire JAX002: only local state inside the jit; captured
containers are mutated by the host caller."""
import jax

CALL_LOG = []


@jax.jit
def step(x):
    parts = []  # local: rebuilt every trace, never stale
    parts.append(x)
    parts.append(x * 2)
    acc = {}
    acc["sum"] = parts[0] + parts[1]
    return acc["sum"]


def host(x):
    CALL_LOG.append("dispatch")  # outside the jit: runs every call
    return step(x)
