"""Must NOT fire JAX001: host syncs happen outside the traced bodies."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(x):
    return jnp.sum(x * 2)


def host_loop(batch):
    x = np.asarray(batch)  # host conversion before dispatch: fine
    out = step(x)
    out.block_until_ready()  # sync outside the jit: fine
    return out.item()
