"""AWS Kinesis connector (reference: crates/arroyo-connectors/src/kinesis/,
955 LoC). Shard iterators checkpoint by sequence number. Client gated on
boto3/aioboto3.

Offset state rides the per-SPLIT scheme (connectors/splits.py): each
shard is one split, checkpointed under `split_key(shard_id)` instead of
the consuming subtask's index, so a rescale moves the shard's position
with its ownership from the replicated union — no per-subtask snapshot
merging. Shards cannot subdivide broker-side (like kafka partitions),
so elasticity is reassignment-only. Legacy per-subtask snapshots
(task_index -> {shard: seq}) still merge on restore."""

from __future__ import annotations

import asyncio
from typing import Dict

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from ._gated import require_client
from .base import ConnectionSchema, Connector, register_connector
from .splits import SPLIT_PREFIX, split_key

# position sentinel for a shard fully drained after a split/merge
CLOSED = "__closed__"


def _seq_ge(a: str, b: str) -> bool:
    """a >= b for Kinesis sequence numbers (numeric strings). Non-numeric
    ids (test doubles) compare by (length, lexicographic) — the same
    total order as numeric for digit strings — so the restore merge still
    prefers the furthest position instead of last-wins."""
    try:
        return int(a) >= int(b)
    except (TypeError, ValueError):
        sa, sb = str(a), str(b)
        return (len(sa), sa) >= (len(sb), sb)


class KinesisSource(SourceOperator):
    def __init__(self, stream: str, region: str, init_position: str,
                 schema, format, bad_data, reshard_poll: float = 1.0):
        super().__init__("kinesis_source")
        self.stream = stream
        self.region = region
        self.init_position = init_position  # latest | earliest
        self.out_schema = schema
        self.format = format
        self.bad_data = bad_data
        self.reshard_poll = reshard_poll  # seconds between shard re-lists
        self.positions: Dict[str, str] = {}  # shard id -> sequence number

    def tables(self):
        from ..state.table_config import global_table

        return {"kin": global_table("kin")}

    def _merge_position(self, sid: str, pos) -> None:
        """Entries can overlap after a reassignment (or a split entry vs
        a legacy snapshot): CLOSED wins, else the furthest sequence
        number (Kinesis sequence numbers are numeric strings)."""
        if pos is None:
            return
        cur = self.positions.get(sid)
        if cur == CLOSED:
            return
        if pos == CLOSED:
            self.positions[sid] = pos
        elif cur is None or _seq_ge(pos, cur):
            self.positions[sid] = pos

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("kin")
            # per-SPLIT entries (split_key(shard) -> {"seq": pos}), plus
            # legacy per-subtask snapshots: shard ownership is by hash,
            # so a rescale moves a shard between subtasks and its
            # position follows it through the replicated union
            for k, stored in table.items():
                if isinstance(k, str) and k.startswith(SPLIT_PREFIX):
                    self._merge_position(k[len(SPLIT_PREFIX):],
                                         (stored or {}).get("seq"))
                else:
                    for sid, pos in (stored or {}).items():
                        self._merge_position(sid, pos)

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("kin")
            # one entry per SPLIT (shard), keyed by the shard id, never
            # the consuming subtask's index (reassignment-only scheme)
            for sid, pos in self.positions.items():
                if self._owned(sid, ctx):
                    table.put(split_key(sid), {"seq": pos})

    def _owned(self, shard_id: str, ctx) -> bool:
        """Stable shard -> subtask assignment: crc32 of the shard's ROOT
        ancestor (ownership follows reshard lineage), so a child always
        lands on the subtask that drained its parent — the parent-drain
        gate can then be enforced locally and per-key order holds across
        splits. Falls back to the shard's own id when lineage is unknown
        (pre-refresh checkpoint filtering)."""
        import zlib

        root = shard_id
        lineage = getattr(self, "_parent_of", {})
        seen = set()
        while root in lineage and root not in seen:
            seen.add(root)
            root = lineage[root]
        par = ctx.task_info.parallelism
        return zlib.crc32(root.encode()) % par == ctx.task_info.task_index

    def _open_iterator(self, client, sid: str):
        if sid in self.positions and self.positions[sid] != CLOSED:
            it = client.get_shard_iterator(
                StreamName=self.stream, ShardId=sid,
                ShardIteratorType="AFTER_SEQUENCE_NUMBER",
                StartingSequenceNumber=self.positions[sid],
            )
        else:
            # children created by a split/merge must replay from their
            # start; LATEST would drop the records written before we
            # discovered them
            it = client.get_shard_iterator(
                StreamName=self.stream, ShardId=sid,
                ShardIteratorType=(
                    "TRIM_HORIZON"
                    if self.init_position == "earliest"
                    or sid in self._discovered_children
                    else "LATEST"
                ),
            )
        return it["ShardIterator"]

    async def run(self, ctx, collector) -> SourceFinishType:
        boto3 = require_client("boto3")
        deser = Deserializer(self.out_schema, format=self.format or "json",
                             bad_data=self.bad_data)
        client = boto3.client("kinesis", region_name=self.region)
        iterators: Dict[str, str] = {}
        known: set = set()
        self._discovered_children: set = set()
        self._parent_of: Dict[str, str] = {}

        def refresh_shards(initial: bool = False) -> bool:
            """Pick up resharding children (reference kinesis resharding
            handling): a child shard starts only after its parent(s) are
            fully drained by their owner, preserving per-key order.
            Returns True when the stream metadata shows every shard
            closed AND all of ours are drained (stream has ended)."""
            shards = client.list_shards(StreamName=self.stream)["Shards"]
            # lineage map first: ownership derives from the root ancestor
            # (the PRIMARY parent; a merge child therefore lands on its
            # primary parent's subtask and the drain gate below covers
            # that side locally — the adjacent parent may drain on a
            # different subtask, so strict per-key order across a MERGE
            # with cross-subtask parents is best-effort, like most
            # non-coordinated Kinesis consumers)
            for s in shards:
                if s.get("ParentShardId"):
                    self._parent_of[s["ShardId"]] = s["ParentShardId"]
            for s in shards:
                sid = s["ShardId"]
                if sid in known or not self._owned(sid, ctx):
                    continue
                if self.positions.get(sid) == CLOSED:
                    known.add(sid)
                    continue
                parents = [
                    p for p in (
                        s.get("ParentShardId"),
                        s.get("AdjacentParentShardId"),
                    )
                    if p and self._owned(p, ctx)
                    and self.positions.get(p) != CLOSED
                    and any(x["ShardId"] == p for x in shards)
                    # the gate only matters when the parent's records will
                    # actually be consumed: an 'earliest' scan, or a
                    # stored/live position proving prior consumption. A
                    # fresh 'latest' start tails both generations — no
                    # ordering to preserve, no deferral (deferring would
                    # TRIM_HORIZON-replay the child after the parent
                    # insta-drains).
                    and (self.init_position == "earliest"
                         or p in self.positions)
                ]
                if parents:
                    # wait until our parent drains — on the INITIAL refresh
                    # too (startup and restore): the parent is opened in
                    # this same pass, and the closed_any-triggered re-list
                    # picks the child up once it drains. Opening both at
                    # once would interleave parent and child reads and
                    # break per-key ordering across the reshard.
                    continue
                if s.get("ParentShardId") and (
                    not initial
                    or s["ParentShardId"] in self.positions
                ):
                    # a reshard child replays from its start even under
                    # init_position=latest: continuity from the drained
                    # parent (incl. restore-time discovery, where the
                    # stored parent position proves prior consumption)
                    self._discovered_children.add(sid)
                known.add(sid)
                iterators[sid] = self._open_iterator(client, sid)
            all_meta_closed = all(
                s.get("SequenceNumberRange", {}).get("EndingSequenceNumber")
                is not None
                for s in shards
            )
            mine_drained = not iterators and all(
                self.positions.get(s["ShardId"]) == CLOSED
                for s in shards
                if self._owned(s["ShardId"], ctx)
            )
            return all_meta_closed and mine_drained

        refresh_shards(initial=True)
        last_refresh = 0.0
        import time as _time

        while True:
            finish = await ctx.check_control(collector)
            if finish is not None:
                return finish
            closed_any = False
            for sid, it in list(iterators.items()):
                resp = client.get_records(ShardIterator=it, Limit=1000)
                for rec in resp["Records"]:
                    ts = int(rec["ApproximateArrivalTimestamp"].timestamp()
                             * 1e9)
                    for row in deser.deserialize_slice(
                        rec["Data"], timestamp=ts,
                        error_reporter=ctx.error_reporter,
                    ):
                        ctx.buffer_row(row)
                    self.positions[sid] = rec["SequenceNumber"]
                nxt = resp.get("NextShardIterator")
                if nxt is None:
                    # shard closed by a split/merge: remember so restores
                    # and re-lists never re-read it, then look for children
                    self.positions[sid] = CLOSED
                    del iterators[sid]
                    closed_any = True
                else:
                    iterators[sid] = nxt
            await self.flush_buffer(ctx, collector)
            # refresh on closures AND on a timer: a reshard child can hash
            # to a subtask whose own iterators never closed (or that owns
            # nothing yet), so every subtask must re-list periodically
            now = _time.monotonic()
            if closed_any or now - last_refresh >= self.reshard_poll:
                last_refresh = now
                if refresh_shards():
                    return SourceFinishType.FINAL
            await asyncio.sleep(0.2)


class KinesisSink(Operator):
    def __init__(self, stream: str, region: str, format):
        super().__init__("kinesis_sink")
        self.stream = stream
        self.region = region
        self.serializer = Serializer(format=format or "json")
        self.client = None

    async def on_start(self, ctx):
        boto3 = require_client("boto3")
        self.client = boto3.client("kinesis", region_name=self.region)

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        records = [
            {"Data": rec, "PartitionKey": str(i)}
            for i, rec in enumerate(self.serializer.serialize(batch))
        ]
        for lo in range(0, len(records), 500):  # API limit per call
            self.client.put_records(
                StreamName=self.stream, Records=records[lo: lo + 500]
            )


@register_connector
class KinesisConnector(Connector):
    name = "kinesis"
    description = "AWS Kinesis source and sink"
    source = True
    sink = True
    config_schema = {
        "stream_name": {"type": "string", "required": True},
        "aws_region": {"type": "string"},
        "source.init_position": {"type": "string"},
    }

    def validate_options(self, options, schema):
        if "stream_name" not in options:
            raise ValueError("kinesis requires stream_name")
        return {
            "stream": options["stream_name"],
            "region": options.get("aws_region", "us-east-1"),
            "init_position": options.get("source.init_position", "latest"),
        }

    def make_source(self, config, schema: ConnectionSchema):
        return KinesisSource(config["stream"], config["region"],
                             config.get("init_position", "latest"),
                             config.get("schema"), config.get("format"),
                             config.get("bad_data", "fail"))

    def make_sink(self, config, schema: ConnectionSchema):
        return KinesisSink(config["stream"], config["region"],
                           config.get("format"))
