--udf=udfs.py
CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE out (start TIMESTAMP, total BIGINT, n BIGINT) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO out
SELECT window.start, total, n FROM (
  SELECT tumble(interval '20 second') as window,
         sum(d) as total, count(*) as n
  FROM (SELECT async_double_negative(counter) as d FROM impulse)
  GROUP BY 1
);
