"""Must NOT fire ASY004: cancellation re-raised, or terminal teardown."""
import asyncio


async def commit(task):
    try:
        await task
    except asyncio.CancelledError:
        raise
    except Exception:
        pass
    await task


async def loop_body():
    try:
        while True:
            await asyncio.sleep(1)
    except asyncio.CancelledError:
        pass  # terminal: the task ends here, nothing runs after
