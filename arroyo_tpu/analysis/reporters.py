"""Finding reporters: human text, machine JSON, and SARIF 2.1.0.

The SARIF reporter is what lets lint and model-check findings annotate
PRs in CI (GitHub's code-scanning upload understands SARIF natively)
instead of living only in job logs. `sarif_document` is shared by
`tools/lint.py --sarif` and `tools/model_check.py --sarif`: both emit
one `run` whose rules metadata comes from the registered rule objects
(or the model checker's violation catalog)."""

from __future__ import annotations

import json
from typing import IO, Iterable, List, Optional

from .core import Finding, get_rule
from .engine import LintResult

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def report_text(result: LintResult, out: IO, verbose: bool = False) -> None:
    for f in result.errors:
        out.write(f"{f.path}:{f.line}: [LINT000] {f.message}\n")
    for f in result.findings:
        out.write(f"{f.path}:{f.line}:{f.col}: [{f.rule}] {f.message}\n")
        if verbose:
            try:
                out.write(f"    rule: {get_rule(f.rule).description}\n")
            except KeyError:
                pass
    for e in result.stale_baseline:
        out.write(
            f"LINT_BASELINE: stale entry [{e['rule']}] {e['path']}: "
            f"{e['message']} (fixed or moved — remove it)\n"
        )
    if result.grandfathered:
        out.write(f"{len(result.grandfathered)} grandfathered finding(s) "
                  "suppressed by baseline\n")
    status = "clean" if result.clean else f"{len(result.findings)} finding(s)"
    out.write(
        f"arroyolint: {status} — {result.n_files} files, "
        f"{result.n_rules} rules\n"
    )


def _sarif_rule_meta(rule_id: str) -> dict:
    try:
        rule = get_rule(rule_id)
        return {
            "id": rule.id,
            "name": rule.name,
            "shortDescription": {"text": rule.name},
            "fullDescription": {"text": rule.description},
        }
    except KeyError:
        return {"id": rule_id, "name": rule_id}


def _sarif_result(f: Finding, level: str) -> dict:
    return {
        "ruleId": f.rule,
        "level": level,
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {
                    "startLine": max(1, f.line),
                    "startColumn": max(1, f.col + 1),
                },
            }
        }],
        "partialFingerprints": {"arroyolint/v1": f.fingerprint()},
    }


def sarif_document(
    findings: Iterable[Finding],
    tool_name: str = "arroyolint",
    errors: Iterable[Finding] = (),
    extra_rules: Optional[List[dict]] = None,
) -> dict:
    """One SARIF run over `findings` (level error) + `errors` (parse
    failures, level error too — an unparseable file can hide anything).
    `extra_rules` injects rule metadata for ids the lint registry does
    not know (the model checker's violation catalog)."""
    findings = list(findings)
    errors = list(errors)
    known_extra = {r["id"]: r for r in (extra_rules or [])}
    rule_ids: List[str] = []
    for f in findings + errors:
        if f.rule not in rule_ids:
            rule_ids.append(f.rule)
    rules = [
        known_extra.get(rid) or _sarif_rule_meta(rid) for rid in rule_ids
    ]
    results = [_sarif_result(f, "error") for f in findings]
    results += [_sarif_result(f, "error") for f in errors]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": tool_name,
                    "informationUri":
                        "https://github.com/arroyo-tpu/arroyo-tpu",
                    "rules": rules,
                }
            },
            "results": results,
        }],
    }


def report_sarif(result: LintResult, out: IO) -> None:
    json.dump(
        sarif_document(result.findings, errors=result.errors), out, indent=2
    )
    out.write("\n")


def report_json(result: LintResult, out: IO) -> None:
    json.dump(
        {
            "findings": [f.to_dict() for f in result.findings],
            "grandfathered": [f.to_dict() for f in result.grandfathered],
            "stale_baseline": result.stale_baseline,
            "errors": [f.to_dict() for f in result.errors],
            "summary": {
                "files": result.n_files,
                "rules": result.n_rules,
                "clean": result.clean,
            },
        },
        out,
        indent=2,
    )
    out.write("\n")
