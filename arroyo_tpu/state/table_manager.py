"""TableManager: per-(subtask, chain-op) state table ownership.

Capability parity with the reference's TableManager
(/root/reference/crates/arroyo-state/src/tables/table_manager.rs:37): owns
the operator's tables, restores them from the backend's restore manifest on
open, flushes dirty state on checkpoint barriers, and swaps file references
after compaction. Restore semantics per table kind:
  * global: union of ALL subtasks' blob chains (replication — rescale-aware
    operators re-filter by key range themselves). Each subtask's manifest
    entry carries a base+delta chain replayed in epoch order; entry stamps
    make the cross-subtask merge deterministic (tables.GlobalTable).
  * time_key: read every subtask's live files, filter rows to this
    subtask's key range and retention (rescale = overlap re-read,
    reference parquet.rs + expiring_time_key_map.rs)

Checkpointing is split into capture (synchronous at the barrier, O(dirty))
and flush (storage I/O, safe to overlap later epochs): the runner keeps up
to `state.max_inflight_flushes` epochs' flushes in flight, strictly
epoch-ordered per subtask, so flush N always lands before flush N+1 runs —
which is what lets flush-time bookkeeping (the cumulative time-key file
list) read `table.files` without racing a later capture.

Rebase policy: an incremental global table's chain is truncated with a
fresh base once it carries `state.rebase_epochs` deltas or its delta bytes
exceed `state.rebase_bytes_factor` x the base size (restore replays the
whole chain, so the chain length is a restore-time tax).
"""

from __future__ import annotations

import weakref
from typing import Dict, Optional

from .. import obs
from ..analysis.model.effects import protocol_effect
from ..config import config as get_config
from ..metrics import (
    STATE_BYTES,
    STATE_CHAIN_LEN,
    STATE_ROWS,
    STATE_SPILLED_BYTES,
)
from ..types import TaskInfo
from ..utils.logging import get_logger
from .backend import StateBackend
from .chain_cache import CACHE
from .table_config import TableConfig
from .tables import GlobalTable, TimeKeyTable

logger = get_logger("table_manager")


class TableManager:
    def __init__(self, backend: StateBackend, task_info: TaskInfo, op_idx: int):
        self.backend = backend
        self.task_info = task_info
        self.op_idx = op_idx
        self.tables: Dict[str, object] = {}
        self.configs: Dict[str, TableConfig] = {}
        # global tables' current blob chain: name -> [{"path", "bytes",
        # "epoch", "base"}]. Extended at CAPTURE time (paths are
        # deterministic) so pipelined flushes can't race the bookkeeping.
        self._chains: Dict[str, list] = {}
        # hot-standby tailing (ISSUE 17): highest manifest epoch whose
        # chain suffix has been replayed onto the open tables
        self._tailed_epoch = -1

    def _read_chain_blob(self, path: str, sp) -> Optional[bytes]:
        """One chain blob, preferring the task-local cache (same-worker
        restart / tail of a blob this process flushed) over storage."""
        blob = CACHE.get(self.backend.storage.url, path)
        if blob is not None:
            sp.event("cached_blob", path=path)
            return blob
        sp.event("read_blob", path=path)
        blob = self.backend.read_blob(path)
        if blob is not None:
            CACHE.put(self.backend.storage.url, path, blob)
        return blob

    async def open(self, configs: Dict[str, TableConfig]):
        self.configs = dict(configs)
        for name, cfg in self.configs.items():
            if cfg.kind == "global":
                table = GlobalTable(cfg)
            else:
                table = TimeKeyTable(cfg)
            self.tables[name] = table
        if self.backend.restore_manifest:
            self._restore()
        self._register_gauges()

    def _register_gauges(self):
        """Scrape-time state-size gauges (weakref pattern: a collected
        table unregisters its refresher instead of pinning stale values)."""
        jid, tid = self.task_info.job_id, self.task_info.task_id
        for name, table in self.tables.items():
            kind = self.configs[name].kind
            tref = weakref.ref(table)
            labels = dict(job=jid, task=tid, table=name, kind=kind)

            def _bytes(tref=tref):
                t = tref()
                if t is None:
                    return None
                if isinstance(t, GlobalTable):
                    return float(t.state_size()[0])
                mem, spilled, _r, _b = t.entry_stats()
                return float(mem + spilled)

            def _rows(tref=tref):
                t = tref()
                if t is None:
                    return None
                if isinstance(t, GlobalTable):
                    return float(t.state_size()[1])
                return float(t.entry_stats()[2])

            STATE_BYTES.labels(**labels).set_refresher(_bytes)
            STATE_ROWS.labels(**labels).set_refresher(_rows)
            if kind != "global":

                def _spilled(tref=tref):
                    t = tref()
                    if t is None:
                        return None
                    return float(t.entry_stats()[1])

                STATE_SPILLED_BYTES.labels(
                    job=jid, task=tid, table=name
                ).set_refresher(_spilled)
            if kind == "global":
                mref = weakref.ref(self)

                def _chain(mref=mref, name=name):
                    m = mref()
                    if m is None:
                        return None
                    return float(len(m._chains.get(name, ())))

                STATE_CHAIN_LEN.labels(job=jid, task=tid,
                                       table=name).set_refresher(_chain)

    def _restore(self):
        node_id = self.task_info.node_id
        # deterministic replay order: the cross-subtask union resolves
        # stale replicated copies by entry stamp, and ties by replay
        # order — sort so ties break the same way on every restore
        per_subtask = sorted(
            self.backend.tables_for(node_id, self.op_idx),
            key=lambda e: e["subtask"],
        )
        restore_wm = self.backend.restore_watermark(self.task_info.task_id)
        for name, table in self.tables.items():
            cfg = self.configs[name]
            # flight recorder: one span per restored table, staged events
            # per file — a restore failure (e.g. the process-scheduler
            # IndexError in ROADMAP open items) names its table, file and
            # stage in the trace dump instead of just a stack
            with obs.span(
                "state.restore_table", cat="storage", table=name,
                kind=cfg.kind, task=self.task_info.task_id,
                op_idx=self.op_idx,
            ) as sp:
                if cfg.kind == "global":
                    n_blobs = 0
                    for entry in per_subtask:
                        meta = entry["tables"].get(name)
                        if not meta:
                            continue
                        chain = meta.get("chain")
                        if chain is None and meta.get("path"):
                            chain = [{"path": meta["path"]}]
                        blobs = []
                        for f in chain or []:
                            blob = self._read_chain_blob(f["path"], sp)
                            if blob is not None:
                                blobs.append(blob)
                        if blobs:
                            table.load_chain(blobs)
                            n_blobs += len(blobs)
                    sp.set(blobs=n_blobs)
                else:
                    seen = set()
                    batches = []
                    for entry in per_subtask:
                        meta = entry["tables"].get(name)
                        for f in (meta or {}).get("files", []):
                            if f["path"] in seen:
                                continue
                            seen.add(f["path"])
                            sp.event("read_file", path=f["path"])
                            t = self.backend.read_parquet(f["path"])
                            if t is not None:
                                batches.extend(t.to_batches())
                            table.files.append(dict(f))
                    sp.set(files=len(seen), batches=len(batches))
                    sp.event("load_batches")
                    table.load_batches(
                        batches,
                        key_indices=None,
                        parallelism=self.task_info.parallelism,
                        task_index=self.task_info.task_index,
                    )
                    sp.event("filter_expired", watermark=restore_wm)
                    table.filter_expired(restore_wm)
        restored = self.backend.restore_epoch
        self._tailed_epoch = restored if restored is not None else -1

    @protocol_effect("state.tail_chains")
    def tail_chains(self) -> int:
        """Hot-standby tailing (ISSUE 17): replay the delta-chain SUFFIX of
        a newer published manifest onto the already-open tables instead of
        re-restoring from scratch. The caller points
        `backend.restore_manifest` at the new manifest first; only chain
        entries for epochs beyond `_tailed_epoch` are read and applied.

        Safe to re-apply overlapping entries: the cross-subtask global
        merge resolves replicated copies by entry stamp, so a rebase base
        that subsumes already-applied deltas loads idempotently. Time-key
        tables load only files not already referenced, then adopt the new
        manifest's file list. Returns the number of blobs/files applied."""
        target = self.backend.restore_epoch
        if target is None or target <= self._tailed_epoch:
            return 0
        node_id = self.task_info.node_id
        per_subtask = sorted(
            self.backend.tables_for(node_id, self.op_idx),
            key=lambda e: e["subtask"],
        )
        applied = 0
        with obs.span(
            "state.tail_chains", cat="storage",
            task=self.task_info.task_id, op_idx=self.op_idx,
            from_epoch=self._tailed_epoch, to_epoch=target,
        ) as sp:
            for name, table in self.tables.items():
                cfg = self.configs[name]
                if cfg.kind == "global":
                    floor = None
                    for entry in per_subtask:
                        meta = entry["tables"].get(name)
                        chain = (meta or {}).get("chain") or []
                        blobs = []
                        for f in chain:
                            e = f.get("epoch")
                            if e is not None and floor is not None:
                                floor = min(floor, e)
                            elif e is not None:
                                floor = e
                            if e is None or e <= self._tailed_epoch:
                                continue
                            blob = self._read_chain_blob(f["path"], sp)
                            if blob is not None:
                                blobs.append(blob)
                        if blobs:
                            table.load_chain(blobs)
                            applied += len(blobs)
                    if floor is not None and floor > 0:
                        # the chain floor moved (rebase/GC): cached blobs
                        # below it are unreferenced now
                        CACHE.invalidate_below(
                            self.task_info.job_id, floor
                        )
                else:
                    seen = {f["path"] for f in table.files}
                    batches = []
                    files = []
                    for entry in per_subtask:
                        meta = entry["tables"].get(name)
                        for f in (meta or {}).get("files", []):
                            if f["path"] in {x["path"] for x in files}:
                                continue
                            files.append(dict(f))
                            if f["path"] in seen:
                                continue
                            sp.event("read_file", path=f["path"])
                            t = self.backend.read_parquet(f["path"])
                            if t is not None:
                                batches.extend(t.to_batches())
                                applied += 1
                    if batches:
                        table.load_batches(
                            batches,
                            key_indices=None,
                            parallelism=self.task_info.parallelism,
                            task_index=self.task_info.task_index,
                        )
                    table.files = files
                    wm = self.backend.restore_watermark(
                        self.task_info.task_id
                    )
                    table.filter_expired(wm)
            sp.set(applied=applied)
        self._tailed_epoch = target
        return applied

    async def get_table(self, name: str):
        return self.tables[name]

    async def checkpoint(self, epoch: int, watermark: Optional[int]) -> Dict:
        """Flush dirty state; returns per-table metadata for the manifest.
        One-shot form of capture() + flush_captured()."""
        return self.flush_captured(epoch, self.capture(epoch, watermark))

    def _should_rebase(self, chain: list) -> bool:
        st = get_config().state
        if not chain:
            return True
        deltas = [f for f in chain if not f.get("base")]
        if len(deltas) >= st.rebase_epochs:
            return True
        base_bytes = sum(
            f.get("bytes", 0) for f in chain if f.get("base")
        ) or 1
        delta_bytes = sum(f.get("bytes", 0) for f in deltas)
        return delta_bytes > st.rebase_bytes_factor * base_bytes

    @protocol_effect("state.capture_tables")
    def capture(self, epoch: int, watermark: Optional[int]) -> Dict:
        """Synchronously stage this epoch's state at the barrier: global
        tables serialize only their dirty entries + tombstones (a base
        when the chain is empty or the rebase policy fires), time-key
        deltas are detached from the tables (possibly as unresolved
        thunks whose device->host copy completes later). After capture
        the operator may resume processing; flush_captured does the I/O."""
        staged: Dict[str, dict] = {}
        ti = self.task_info
        for name, table in self.tables.items():
            cfg = self.configs[name]
            if cfg.kind == "global":
                chain = self._chains.setdefault(name, [])
                blob, is_base = table.serialize_delta(
                    epoch, force_base=self._should_rebase(chain)
                )
                if blob is not None:
                    path = self.backend.global_blob_path(
                        epoch, ti.node_id, self.op_idx, name, ti.task_index
                    )
                    meta = {"path": path, "bytes": len(blob),
                            "epoch": epoch, "base": is_base}
                    if is_base:
                        chain[:] = [meta]
                    else:
                        chain.append(meta)
                staged[name] = {
                    "kind": "global", "blob": blob,
                    "chain": [dict(f) for f in chain],
                }
            else:
                dirty = table.take_dirty_staged()
                table.expire(watermark)
                staged[name] = {
                    "kind": "time_key",
                    "dirty": dirty,
                    "watermark": watermark,
                    "table": table,
                }
        return staged

    @protocol_effect("state.flush_tables")
    def flush_captured(self, epoch: int, staged: Dict) -> Dict:
        """Write captured state to storage; safe to run while the operator
        processes later epochs (captured data is immutable), as long as
        flushes stay epoch-ordered per subtask (the runner's flush queue
        guarantees it — time-key file bookkeeping reads `table.files`
        here, which epoch N must update before epoch N+1 flushes).
        Returns the manifest metadata."""
        meta: Dict[str, dict] = {}
        ti = self.task_info
        for name, st in staged.items():
            cfg = self.configs[name]
            if st["kind"] == "global":
                chain = st["chain"]
                if st["blob"] is not None:
                    self.backend.write_blob(chain[-1]["path"], st["blob"])
                    # task-local recovery (ISSUE 17): a same-worker restart
                    # or tailing standby re-reads this exact blob; keep it
                    # in process memory so that read skips storage
                    CACHE.put(self.backend.storage.url, chain[-1]["path"],
                              st["blob"])
                meta[name] = {
                    "kind": "global",
                    "chain": chain,
                    "bytes": sum(f.get("bytes", 0) for f in chain),
                }
            else:
                dirty = TimeKeyTable.resolve_staged(st["dirty"])
                table = st["table"]
                files = table.live_files(st["watermark"])
                if dirty is not None and dirty.num_rows:
                    f = self.backend.write_time_key_file(
                        epoch, ti.node_id, self.op_idx, name, ti.task_index,
                        dirty, timestamp_field=cfg.timestamp_field,
                    )
                    files = files + [f]
                table.files = files
                meta[name] = {"kind": "time_key", "files": files}
        return meta

    async def load_compacted(self, table: str, paths):
        """Swap pre-compaction file references for the compacted file
        (reference ControlMessage::LoadCompacted). In-memory rows already
        hold the data; only restore bookkeeping changes."""
        t = self.tables.get(table)
        if t is None or not hasattr(t, "files"):
            return
        if isinstance(paths, list) and paths and isinstance(paths[0], dict):
            t.files = [dict(f) for f in paths]
