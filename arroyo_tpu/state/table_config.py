"""State table descriptors, declared by operators via Operator.tables().

Capability parity with the reference's table config protos
(/root/reference/crates/arroyo-rpc/proto/rpc.proto checkpoint metadata +
arroyo-state/src/tables): two table kinds —
  * global: small bincode-style KV replicated to all subtasks on restore
    (reference GlobalKeyedTable, tables/global_keyed_map.rs:47)
  * expiring_time_key: RecordBatch rows bucketed by time with a retention,
    key-range filtered on restore (reference ExpiringTimeKeyTable,
    tables/expiring_time_key_map.rs:53)
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class TableConfig:
    name: str
    kind: str  # "global" | "expiring_time_key"
    retention_nanos: Optional[int] = None  # expiring tables only
    # schema of stored batches (expiring tables); None = same as input edge
    schema: object = None
    # which column holds the bucketing timestamp (defaults to _timestamp)
    timestamp_field: str = "_timestamp"
    # key columns used for key-range filtering on restore
    key_fields: tuple = ()


def global_table(name: str) -> TableConfig:
    return TableConfig(name=name, kind="global")


def time_key_table(
    name: str,
    retention_nanos: Optional[int] = None,
    schema=None,
    timestamp_field: str = "_timestamp",
    key_fields: tuple = (),
) -> TableConfig:
    return TableConfig(
        name=name,
        kind="expiring_time_key",
        retention_nanos=retention_nanos,
        schema=schema,
        timestamp_field=timestamp_field,
        key_fields=key_fields,
    )
