"""Placeholder: serializers land with the formats milestone."""


def make_serializer(schema):
    raise NotImplementedError("formats milestone pending")
