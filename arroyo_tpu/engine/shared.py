"""Shared-plan data bus (ISSUE 16): one source scan, N tenant readers.

When the controller admits a job whose source scan fingerprints equal to
one already running (sql/fingerprint.py), it does NOT spawn a second
scan. Instead a hidden, registry-owned *host* job `__shared/<fp>` runs
the scan once and publishes every batch into a process-local
`SharedChannel`; each tenant job runs a `mounted` source
(connectors/shared.py) that reads the channel from its own cursor. The
bus is the seam where N similar jobs collapse to ~1× source work.

Design — a retained log, not per-subscriber queues:

  * the channel holds `(start_offset, batch)` entries where offsets are
    ABSOLUTE cumulative row counts over the host scan's lifetime. A
    batch is therefore self-identifying: a reader at cursor C skips rows
    below C (slicing a straddling batch) no matter how many times the
    host re-published them;
  * late joiners replay from offset 0 through the retained log, so a
    tenant mounted minutes after the host started still sees every row;
  * on host restart the scan resumes from its checkpointed offset and
    re-publishes; `publish()` at an offset below the log tail REWINDS
    the log (drops entries at/after it). Host sources are restricted to
    deterministic-replay configs, so the re-published rows are
    byte-identical and no reader observes divergence;
  * retention is trimmed only below every attached tenant's durable
    restore floor (their last *published* checkpoint position — the
    deepest any restart can rewind them). A mount whose requested
    position predates the retained base is refused; the controller
    falls back to an unshared spawn;
  * backpressure is shared fate: `publish()` blocks while the slowest
    attached reader is more than `max_retained_rows` behind, so one
    stalled tenant throttles the scan rather than ballooning memory
    (exactly the semantics a per-job scan would have had).

Epoch bookkeeping for the publication gate (controller/sharing.py): the
host tail records epoch -> offset at each of ITS barriers
(`note_host_capture`); tenants record epoch -> position at each of
THEIRS (`note_tenant_capture`). The controller refuses to publish a
host epoch E until every mounted tenant's durable position has reached
the host's offset at E — otherwise a host restart could resume the
scan beyond rows some tenant still needs (the `sp.kill` V_LOSS
violation in analysis/model/sharedplan.py, and the
`leaked_barrier_across_tenants` mutant's counterexample).

Process-local by design: embedded and pooled workers are in-process
asyncio tasks, so a module-level registry keyed by fingerprint is the
correct transport. A multi-host bus would ride the same interface over
the shuffle layer.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

# job-id namespace of hidden host jobs (`__shared/<fp>`): defined here,
# at the lowest layer, so obs/attribution can recognize host jobs
# without importing the controller
HOST_PREFIX = "__shared/"


class SharedChannel:
    """The retained log for one fingerprinted source scan."""

    def __init__(self, fingerprint: str, max_retained_rows: int = 1 << 22):
        self.fingerprint = fingerprint
        self.max_retained_rows = max_retained_rows
        # (start_offset, batch) entries, ascending, non-overlapping
        self.log: List[Tuple[int, object]] = []
        self.base = 0            # offset of the first retained row
        self.end = 0             # offset one past the last published row
        self.closed = False      # host scan reached EOS
        self.cursors: Dict[str, int] = {}    # job_id -> next offset to read
        self.expected: set = set()           # mounted but not yet attached
        self.floors: Dict[str, int] = {}     # job_id -> durable restore floor
        self.consumed: Dict[str, int] = {}   # job_id -> rows delivered (obs)
        # host epoch -> offset at capture; tenant job -> {epoch: position}
        self.epoch_offsets: Dict[int, int] = {}
        self.tenant_epochs: Dict[str, Dict[int, int]] = {}
        self._cond = asyncio.Condition()

    # -- host side ------------------------------------------------------------

    async def publish(self, start_offset: int, batch) -> None:
        """Append (or rewind-and-append after a host restart). Blocks
        while the slowest attached reader is over the retention cap
        behind (shared-fate backpressure)."""
        async with self._cond:
            if start_offset < self.end:
                # host restarted below the tail: deterministic replay
                # regenerates identical rows, so superseded entries go
                self.log = [e for e in self.log if e[0] < start_offset]
                self.end = self.log[-1][0] + self.log[-1][1].num_rows \
                    if self.log else self.base
                # a restart can't rewind below the retained base
                assert start_offset >= self.end, (
                    f"host republish at {start_offset} inside retained "
                    f"entry ending {self.end}"
                )
            if not self.log and start_offset > self.end:
                # fresh channel, host restored mid-stream (durable host,
                # new bus incarnation): rows below the restore offset
                # were never retained here — reflect that in the base so
                # a from-zero mount is refused, not silently truncated
                self.base = self.end = start_offset
            n = batch.num_rows
            if n:
                self.log.append((start_offset, batch))
                self.end = start_offset + n
            self._cond.notify_all()
            while (
                self.cursors
                and self.end - min(self.cursors.values())
                    > self.max_retained_rows
                and not self.closed
            ):
                await self._cond.wait()
            self._trim()

    def _trim(self) -> None:
        """Drop entries no restart can ever need: below every attached
        tenant's durable floor (and every live cursor). Only kicks in
        past the soft cap, so late joiners usually find a full log."""
        if self.end - self.base <= self.max_retained_rows:
            return
        if self.cursors:
            safe = min(
                min(self.cursors.values()),
                min((self.floors.get(j, 0) for j in self.cursors),
                    default=0),
            )
        elif self.expected:
            # a mounted tenant hasn't attached yet (worker still
            # scheduling): it reads from its restore position, which may
            # be 0 — hold the full log until it shows up
            return
        else:
            # zero subscribers: keep a cap-sized tail so a FUTURE mount
            # attempt sees an honest base (and falls back to an
            # unshared spawn if it needed the trimmed prefix)
            safe = self.end - self.max_retained_rows
        while self.log:
            start, batch = self.log[0]
            if start + batch.num_rows > safe:
                break
            self.log.pop(0)
            self.base = self.log[0][0] if self.log else self.end

    async def close(self) -> None:
        async with self._cond:
            self.closed = True
            self._cond.notify_all()

    def note_host_capture(self, epoch: int, offset: int) -> None:
        self.epoch_offsets[epoch] = offset

    # -- tenant side ----------------------------------------------------------

    async def attach(self, job_id: str, position: int) -> bool:
        """Mount a reader at `position`. Refused (False) when the log no
        longer retains that offset — the caller must spawn unshared."""
        async with self._cond:
            if position < self.base:
                return False
            self.cursors[job_id] = max(position, 0)
            self.expected.discard(job_id)
            self.consumed.setdefault(job_id, 0)
            self._cond.notify_all()
            return True

    def expect(self, job_id: str) -> None:
        """Admission-time reservation: the tenant is mounted but its
        MountedSource hasn't attached yet; retention holds the full log
        for it (see _trim)."""
        self.expected.add(job_id)

    async def detach(self, job_id: str) -> None:
        async with self._cond:
            self.cursors.pop(job_id, None)
            self.expected.discard(job_id)
            self.floors.pop(job_id, None)
            self.tenant_epochs.pop(job_id, None)
            self._cond.notify_all()

    async def read(
        self, job_id: str, max_wait: float = 0.25
    ) -> Optional[List[object]]:
        """Batches at/after the reader's cursor, cursor-sliced so the
        first row delivered is exactly the cursor row. Empty list on
        timeout (caller re-checks control), None when the host closed
        and the log is drained."""
        async with self._cond:
            cursor = self.cursors.get(job_id)
            if cursor is None:
                return None  # detached under us
            if cursor >= self.end:
                if self.closed:
                    return None
                try:
                    await asyncio.wait_for(self._cond.wait(), max_wait)
                except asyncio.TimeoutError:
                    return []
                cursor = self.cursors.get(job_id)
                if cursor is None:
                    return None
                if cursor >= self.end:
                    return None if self.closed else []
            out: List[object] = []
            delivered = 0
            for start, batch in self.log:
                n = batch.num_rows
                if start + n <= cursor:
                    continue
                if start < cursor:
                    batch = batch.slice(cursor - start)
                out.append(batch)
                delivered += batch.num_rows
            self.cursors[job_id] = self.end
            self.consumed[job_id] = self.consumed.get(job_id, 0) + delivered
            self._cond.notify_all()  # publisher may be waiting on retention
            return out

    async def seek(self, job_id: str, position: int) -> None:
        """Rewind/advance a reader (tenant restore re-attaches here)."""
        async with self._cond:
            if job_id in self.cursors:
                self.cursors[job_id] = position
                self._cond.notify_all()

    def note_tenant_capture(self, job_id: str, epoch: int,
                            position: int) -> None:
        self.tenant_epochs.setdefault(job_id, {})[epoch] = position

    def tenant_durable_position(self, job_id: str,
                                published_epoch: int) -> int:
        """The deepest position this tenant restores to: its latest
        position captured at an epoch its controller already published.
        0 until the first published checkpoint (a restart replays the
        log from the start)."""
        caps = self.tenant_epochs.get(job_id, {})
        durable = [p for e, p in caps.items() if e <= published_epoch]
        return max(durable) if durable else 0

    def set_floor(self, job_id: str, position: int) -> None:
        """Raise the tenant's durable restore floor (retention may trim
        below it). Monotone: floors never regress."""
        if position > self.floors.get(job_id, 0):
            self.floors[job_id] = position

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "base": self.base,
            "end": self.end,
            "retained_rows": self.end - self.base,
            "retained_batches": len(self.log),
            "closed": self.closed,
            "subscribers": {
                j: {
                    "cursor": c,
                    "lag": self.end - c,
                    "consumed": self.consumed.get(j, 0),
                    "floor": self.floors.get(j, 0),
                }
                for j, c in sorted(self.cursors.items())
            },
            "host_epochs": dict(sorted(self.epoch_offsets.items())),
        }


class SharedBus:
    """Process-local registry of shared channels, keyed by the source
    scan fingerprint (sql/fingerprint.py source_scan_fingerprint)."""

    def __init__(self):
        self.channels: Dict[str, SharedChannel] = {}

    def get_or_create(self, fingerprint: str,
                      max_retained_rows: int = 1 << 22) -> SharedChannel:
        ch = self.channels.get(fingerprint)
        if ch is None:
            ch = SharedChannel(fingerprint, max_retained_rows)
            self.channels[fingerprint] = ch
        return ch

    def get(self, fingerprint: str) -> Optional[SharedChannel]:
        return self.channels.get(fingerprint)

    def drop(self, fingerprint: str) -> None:
        self.channels.pop(fingerprint, None)

    def stats(self) -> dict:
        return {fp: ch.stats() for fp, ch in sorted(self.channels.items())}


# the process-wide bus (embedded/pooled workers share this interpreter)
BUS = SharedBus()
