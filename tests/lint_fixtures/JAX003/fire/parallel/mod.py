"""MUST fire JAX003: host sync inside the exchange hot path."""
import numpy as np


class Acc:
    def update(self, slots, vals):
        # blocking the device per update serializes every dispatch
        self.state[0].block_until_ready()
        self._dispatch(slots, vals)

    def _dispatch_rows(self, rows):
        # implicit __array__ over device state on the flush path
        host_copy = np.asarray(self.state[0])
        return host_copy[rows]

    def flush(self):
        total = float(self.state[1])
        return total
