"""Polling HTTP source.

Capability parity with the reference's polling_http connector
(/root/reference/crates/arroyo-connectors/src/polling_http/, 521 LoC):
polls an endpoint on an interval, optionally emitting only when the
response body changes.

State rides the per-SPLIT scheme (connectors/splits.py) as a single
split `p0` holding the last-emitted body digest and the poll count, so
`emit_behavior = changed` deduplicates ACROSS restarts: a restore does
not re-emit the body it already delivered before the crash. The single
split's round-robin owner is subtask 0 at any parallelism.
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Optional

from ..operators.base import SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from .base import ConnectionSchema, Connector, register_connector
from . import splits as sm


class PollingHttpSource(SourceOperator):
    SPLIT_ID = "p0"

    def __init__(self, endpoint: str, interval: float, emit_behavior: str,
                 method: str, body: Optional[str], headers: dict,
                 schema, format: str, bad_data: str):
        super().__init__("polling_http_source")
        self.endpoint = endpoint
        self.interval = interval
        self.emit_behavior = emit_behavior  # all | changed
        self.method = method
        self.body = body
        self.headers = headers
        self.out_schema = schema
        self.deserializer = Deserializer(schema, format=format or "json",
                                         bad_data=bad_data,
                                         framing="newline")
        self.last_sha: Optional[str] = None  # digest of last emitted body
        self.polls = 0

    def tables(self):
        from ..state.table_config import global_table

        return {"poll": global_table("poll")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("poll")
            stored = sm.load_splits(table).get(self.SPLIT_ID)
            if stored:
                self.last_sha = stored.get("etag")
                self.polls = int(stored.get("polls", 0))

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None and ctx.task_info.task_index == 0:
            table = await ctx.table("poll")
            table.put(sm.split_key(self.SPLIT_ID),
                      {"etag": self.last_sha, "polls": self.polls})

    async def run(self, ctx, collector) -> SourceFinishType:
        import aiohttp

        if ctx.task_info.task_index != 0:
            # the single split's owner (round-robin rank 0)
            return SourceFinishType.FINAL
        async with aiohttp.ClientSession() as session:
            while True:
                finish = await ctx.check_control(collector)
                if finish is not None:
                    return finish
                try:
                    async with session.request(
                        self.method, self.endpoint, data=self.body,
                        headers=self.headers,
                    ) as resp:
                        payload = await resp.read()
                except aiohttp.ClientError as e:
                    ctx.error_reporter.report("poll failed", str(e))
                    await asyncio.sleep(self.interval)
                    continue
                self.polls += 1
                digest = hashlib.sha256(payload).hexdigest()
                if self.emit_behavior != "changed" \
                        or digest != self.last_sha:
                    self.last_sha = digest
                    for row in self.deserializer.deserialize_slice(
                        payload, error_reporter=ctx.error_reporter
                    ):
                        ctx.buffer_row(row)
                    await self.flush_buffer(ctx, collector)
                await asyncio.sleep(self.interval)


@register_connector
class PollingHttpConnector(Connector):
    name = "polling_http"
    description = "polls an HTTP endpoint on an interval"
    source = True
    config_schema = {
        "endpoint": {"type": "string", "required": True},
        "poll_interval": {"type": "string"},
        "emit_behavior": {"type": "string", "enum": ["all", "changed"]},
        "method": {"type": "string"},
        "body": {"type": "string"},
    }

    def validate_options(self, options, schema):
        from ..config import parse_duration

        if "endpoint" not in options:
            raise ValueError("polling_http requires an endpoint option")
        headers = {}
        for pair in (options.get("headers") or "").split(","):
            if ":" in pair:
                k, v = pair.split(":", 1)
                headers[k.strip()] = v.strip()
        return {
            "endpoint": options["endpoint"],
            "interval": parse_duration(options.get("poll_interval", "1s")),
            "emit_behavior": options.get("emit_behavior", "all"),
            "method": options.get("method", "GET").upper(),
            "body": options.get("body"),
            "headers": headers,
        }

    def make_source(self, config, schema: ConnectionSchema):
        return PollingHttpSource(
            config["endpoint"], config["interval"], config["emit_behavior"],
            config["method"], config.get("body"), config.get("headers", {}),
            config.get("schema"), config.get("format"),
            config.get("bad_data", "fail"),
        )
