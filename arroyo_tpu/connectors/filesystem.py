"""Placeholder: filesystem connector lands with the connector milestone."""
