"""MUST fire ASY001: spawned task result discarded."""
import asyncio


async def work():
    pass


async def go():
    asyncio.create_task(work())
    asyncio.ensure_future(work())
