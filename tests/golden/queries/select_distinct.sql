--pk=k,s
CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE out (k BIGINT, s BIGINT) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO out
SELECT DISTINCT counter % 5 as k, counter % 3 as s FROM impulse;
