"""Test configuration: force JAX onto a virtual 8-device CPU platform so
sharding/collective paths are exercised without TPU hardware, per the build
environment contract. Must run before jax is imported anywhere."""

import os

# force, don't setdefault: the environment pins JAX_PLATFORMS=axon (real TPU
# tunnel) globally, and tests must never claim the real chip
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture()
def tmp_storage(tmp_path):
    return str(tmp_path / "storage")
