"""CLI: all roles as subcommands of one entrypoint.

Capability parity with the reference binary
(/root/reference/crates/arroyo/src/main.rs:43-120): `run` (single-process
cluster for one query), `worker`, `controller`, `api`, `cluster`
(api+controller), `visualize` (DAG dump), plus `bench` for the nexmark
benchmark.
"""

from __future__ import annotations

import argparse
import asyncio
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(prog="arroyo_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    run_p = sub.add_parser("run", help="run a query in an embedded cluster")
    run_p.add_argument("query", help="SQL text or path to a .sql file")
    run_p.add_argument("--parallelism", type=int, default=1)
    run_p.add_argument("--state-dir", default=None,
                       help="checkpoint storage URL (enables durability)")
    run_p.add_argument("--workers", type=int, default=1)
    run_p.add_argument("--scheduler", default="embedded",
                       choices=["embedded", "process"])
    run_p.add_argument("--autoscale", action="store_true",
                       help="enable the closed-loop autoscaler (requires "
                       "--state-dir: rescales restore from checkpoints)")
    run_p.add_argument("--max-parallelism", type=int, default=None,
                       help="autoscaler parallelism ceiling "
                       "(autoscale.max_parallelism)")

    w_p = sub.add_parser("worker", help="start a worker")
    w_p.add_argument("--controller", required=True)

    n_p = sub.add_parser("node", help="start a node daemon (offers "
                         "worker slots to the controller)")
    n_p.add_argument("--controller", required=True)
    n_p.add_argument("--slots", type=int, default=None)

    c_p = sub.add_parser("controller", help="start a controller")
    c_p.add_argument("--scheduler", default=None,
                     choices=["embedded", "process", "manual", "node",
                              "kubernetes"])
    c_p.add_argument("--port", type=int, default=None)

    api_p = sub.add_parser("api", help="start the REST API server")
    api_p.add_argument("--port", type=int, default=None)

    cl_p = sub.add_parser("cluster", help="start api + controller")
    cl_p.add_argument("--port", type=int, default=None)
    cl_p.add_argument("--scheduler", default="process")
    cl_p.add_argument("--autoscale", action="store_true",
                      help="enable the closed-loop autoscaler for jobs "
                      "with durable state")

    v_p = sub.add_parser("visualize", help="print a query's dataflow DAG")
    v_p.add_argument("query")

    sub.add_parser("bench", help="run the nexmark benchmark")

    args = ap.parse_args(argv)
    if args.cmd == "run":
        return asyncio.run(_run(args))
    if args.cmd == "worker":
        return asyncio.run(_worker(args))
    if args.cmd == "node":
        return asyncio.run(_node(args))
    if args.cmd == "controller":
        return asyncio.run(_controller(args))
    if args.cmd == "api":
        return asyncio.run(_api(args))
    if args.cmd == "cluster":
        return asyncio.run(_cluster(args))
    if args.cmd == "visualize":
        return _visualize(args)
    if args.cmd == "bench":
        import subprocess

        return subprocess.call([sys.executable, "bench.py"])


def _load_sql(q: str) -> str:
    import os

    if os.path.exists(q) and q.endswith(".sql"):
        return open(q).read()
    return q


async def _run(args):
    """reference crates/arroyo/src/run.rs: embedded cluster, one query."""
    from .controller.controller import ControllerServer
    from .controller.scheduler import make_scheduler
    from .controller.state_machine import JobState
    from .sql import plan_query
    from .utils import init_logging

    init_logging()
    sql = _load_sql(args.query)
    plan_query(sql, parallelism=args.parallelism)  # validate before boot
    # idempotent reuse (reference crates/arroyo run.rs: pipelines are keyed
    # by query): with a state dir, the job id derives from the query text,
    # so re-running the same query resumes its own checkpoints and a
    # different query never collides with stale state
    if args.state_dir:
        import hashlib

        job_id = "q" + hashlib.sha256(sql.encode()).hexdigest()[:12]
        from .state import protocol
        from .state.storage import StorageProvider

        latest = protocol.resolve_latest(
            StorageProvider(args.state_dir), protocol.ProtocolPaths(job_id)
        )
        if latest:
            print(f"resuming pipeline {job_id} from epoch "
                  f"{latest['epoch']}")
        # pipeline metadata rides the state dir (reference MaybeLocalDb)
        from .api.db import ApiDb

        meta = ApiDb(remote_url=args.state_dir)
        if not any(p["query"] == sql for p in meta.list_pipelines()):
            meta.create_pipeline(job_id, sql, args.parallelism)
    else:
        job_id = "job_cli"
    import contextlib

    from .config import update

    cfg_ctx = contextlib.nullcontext()
    if args.autoscale:
        if not args.state_dir:
            print("--autoscale requires --state-dir: automatic rescales "
                  "stop with a checkpoint and restore from it",
                  file=sys.stderr)
            return 2
        autoscale = {"enabled": True}
        if args.max_parallelism:
            autoscale["max_parallelism"] = args.max_parallelism
        cfg_ctx = update(autoscale=autoscale)
    with cfg_ctx:
        controller = await ControllerServer(
            make_scheduler(args.scheduler)
        ).start()
        await controller.submit_job(
            job_id, sql=sql, storage_url=args.state_dir,
            n_workers=args.workers, parallelism=args.parallelism,
        )
        try:
            state = await controller.wait_for_state(
                job_id, JobState.FINISHED, JobState.FAILED,
                JobState.STOPPED, timeout=86400,
            )
            print(f"job {state.value.lower()}")
            return 0 if state != JobState.FAILED else 1
        except KeyboardInterrupt:
            await controller.stop_job(job_id, "checkpoint"
                                      if args.state_dir else "graceful")
            await controller.wait_for_state(
                job_id, JobState.STOPPED, JobState.FAILED, timeout=60
            )
            return 0
        finally:
            await controller.stop()


async def _node(args):
    from .controller.node import NodeServer
    from .utils import init_logging

    init_logging()
    node = await NodeServer(args.controller, slots=args.slots).start()
    try:
        await node.run_forever()
    except KeyboardInterrupt:
        await node.stop()
    return 0


async def _worker(args):
    from .engine.worker import worker_main
    from .utils import init_logging

    init_logging()
    await worker_main(args.controller)


async def _controller(args):
    from .config import config
    from .controller.controller import ControllerServer
    from .controller.scheduler import make_scheduler
    from .utils import init_logging

    init_logging()
    sched = make_scheduler(args.scheduler or config().controller.scheduler)
    c = ControllerServer(sched)
    if args.port:
        c.rpc.port = args.port
    await c.start()
    print(f"controller listening at {c.addr}")
    await asyncio.Event().wait()


async def _api(args):
    from .api.rest import serve_api
    from .utils import init_logging

    init_logging()
    await serve_api(port=args.port)


async def _cluster(args):
    import contextlib

    from .api.rest import serve_api
    from .config import config, update
    from .controller.controller import ControllerServer
    from .controller.scheduler import make_scheduler
    from .utils import init_logging

    init_logging()
    cfg_ctx = (update(autoscale={"enabled": True}) if args.autoscale
               else contextlib.nullcontext())
    with cfg_ctx:
        c = ControllerServer(make_scheduler(args.scheduler))
        await c.start()
        print(f"controller at {c.addr}")
        await serve_api(port=args.port, controller=c)


def _visualize(args):
    from .sql import plan_query

    plan = plan_query(_load_sql(args.query))
    g = plan.graph
    print("digraph pipeline {")
    for n in g.nodes.values():
        ops = " | ".join(op.operator.value for op in n.chain)
        print(f'  n{n.node_id} [label="{n.description}\\n{ops}\\n'
              f'p={n.parallelism}"];')
    for e in g.edges:
        print(f'  n{e.src} -> n{e.dst} [label="{e.edge_type.value}"];')
    print("}")
    return 0


if __name__ == "__main__":
    sys.exit(main() or 0)
