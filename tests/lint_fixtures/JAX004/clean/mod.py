"""MUST NOT fire JAX004: a genuinely stateless fusable operator, plus a
stateful operator that is (correctly) NOT registered fusable."""


class PureMapOp:
    fusable = True

    def __init__(self, fn, name="map"):
        self.fn = fn
        self.name = name
        self._seg_counters = None  # metric-handle memoization, not state

    async def process_batch(self, batch, ctx, collector, input_index=0):
        out = self.fn(batch)
        if out is not None and out.num_rows:
            await collector.collect(out)


class WindowedOp:
    # not fusable: free to keep state and checkpoint hooks
    fusable = False

    def __init__(self):
        self._state = {}

    def tables(self):
        return {"w": object()}

    async def handle_checkpoint(self, barrier, ctx, collector):
        table = await ctx.table("w")
        table.put(0, self._state)
