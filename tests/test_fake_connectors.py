"""The gated connectors driven end-to-end on in-memory fake clients:
the REAL operator code (offset checkpointing, transactional 2PC,
shard/sequence resume) executes through the engine — no broker needed
(reference precedent: broker-less sink tests in
/root/reference/crates/arroyo-connectors/src/kafka/sink/test.rs)."""

import asyncio
import json
import sys

import pytest

from arroyo_tpu.engine import Engine
from arroyo_tpu.sql import plan_query

from fake_clients import FakeKafkaBroker, FakeKinesisStream, FakeNatsServer


@pytest.fixture()
def kafka_broker(monkeypatch):
    broker = FakeKafkaBroker(partitions_per_topic=2)
    import arroyo_tpu.connectors.kafka as kmod

    monkeypatch.setattr(kmod, "_load_client", lambda: broker.make_module())
    return broker


def _preload(broker, topic, rows):
    for i, row in enumerate(rows):
        broker.append(topic, i % broker.partitions_per_topic, None,
                      json.dumps(row).encode(), committed=True, tx_id=None)


def _visible_rows(broker, topic):
    out = []
    for p in sorted(broker.topic(topic)):
        for m in broker.visible(topic, p):
            if m.committed:
                out.append(json.loads(m.value()))
    return out


KAFKA_SQL = """
CREATE TABLE src (
  n BIGINT
) WITH (
  connector = 'kafka', bootstrap_servers = 'fake:9092', topic = 'in',
  type = 'source', format = 'json', source.offset = 'earliest'
);
CREATE TABLE dst (
  n BIGINT
) WITH (
  connector = 'kafka', bootstrap_servers = 'fake:9092', topic = 'out',
  type = 'sink', format = 'json', sink.commit_mode = 'exactly_once'
);
INSERT INTO dst SELECT n * 10 as n FROM src;
"""


def test_kafka_source_to_transactional_sink(kafka_broker, tmp_path):
    """Consume -> transform -> produce through per-epoch transactions:
    output becomes visible only after the 2PC commit, exactly once."""
    _preload(kafka_broker, "in", [{"n": i} for i in range(100)])

    async def go():
        plan = plan_query(KAFKA_SQL, parallelism=1)
        eng = Engine(plan.graph, job_id="kfk",
                     storage_url=str(tmp_path / "ck")).start()
        # wait until the source drained the preloaded log
        for _ in range(400):
            await asyncio.sleep(0.01)
            if len(_visible_rows(kafka_broker, "out")) >= 0:
                pass
            done = all(
                len(kafka_broker.visible("in", p)) > 0
                for p in range(2)
            )
            if done:
                break
        await eng.checkpoint_and_wait()
        mid = sorted(r["n"] for r in _visible_rows(kafka_broker, "out"))
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)
        return mid

    mid = asyncio.run(go())
    # after the first commit every consumed row was visible exactly once
    assert mid == [i * 10 for i in range(len(mid))]
    final = sorted(r["n"] for r in _visible_rows(kafka_broker, "out"))
    assert final == [i * 10 for i in range(100)], (
        f"{len(final)} visible rows"
    )
    # no open transactions leaked
    assert not kafka_broker.open_tx


def test_kafka_offsets_restore_exactly_once(kafka_broker, tmp_path):
    """Stop with a checkpoint, produce more input, restore: consumption
    resumes at the checkpointed offsets — output has every row once."""
    _preload(kafka_broker, "in", [{"n": i} for i in range(40)])
    url = str(tmp_path / "ck")

    async def phase1():
        plan = plan_query(KAFKA_SQL, parallelism=1)
        eng = Engine(plan.graph, job_id="kfk2", storage_url=url).start()
        await asyncio.sleep(0.3)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(phase1())
    visible1 = len(_visible_rows(kafka_broker, "out"))
    assert visible1 == 40
    _preload(kafka_broker, "in", [{"n": i} for i in range(40, 70)])

    async def phase2():
        plan = plan_query(KAFKA_SQL, parallelism=1)
        eng = Engine(plan.graph, job_id="kfk2", storage_url=url).start()
        await asyncio.sleep(0.3)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(phase2())
    final = sorted(r["n"] for r in _visible_rows(kafka_broker, "out"))
    assert final == [i * 10 for i in range(70)], (
        "offsets restored wrong: duplicates or loss"
    )


def test_kafka_uncommitted_transaction_invisible(kafka_broker, tmp_path):
    """A crash-like IMMEDIATE stop leaves the in-flight transaction
    uncommitted: its rows stay invisible (read-committed), and the
    restored run re-emits them in a fresh transaction — exactly once."""
    from arroyo_tpu.types import StopMode

    _preload(kafka_broker, "in", [{"n": i} for i in range(30)])
    url = str(tmp_path / "ck")

    async def phase1():
        plan = plan_query(KAFKA_SQL, parallelism=1)
        eng = Engine(plan.graph, job_id="kfk3", storage_url=url).start()
        await asyncio.sleep(0.3)  # rows produced into the open epoch-0 tx
        await eng.stop(StopMode.IMMEDIATE)
        await eng.join(30)

    asyncio.run(phase1())
    assert _visible_rows(kafka_broker, "out") == [], (
        "uncommitted transaction leaked into read-committed visibility"
    )
    # the in-flight transaction ended without a commit: either aborted at
    # teardown (sink on_close) or left open for init_transactions to fence
    assert kafka_broker.aborted_tx or kafka_broker.open_tx, (
        "expected an uncommitted in-flight transaction"
    )

    async def phase2():
        plan = plan_query(KAFKA_SQL, parallelism=1)
        eng = Engine(plan.graph, job_id="kfk3", storage_url=url).start()
        await asyncio.sleep(0.3)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(phase2())
    final = sorted(r["n"] for r in _visible_rows(kafka_broker, "out"))
    assert final == [i * 10 for i in range(30)]


def test_kafka_zombie_producer_fenced(kafka_broker):
    """Protocol-shaped fencing: a new producer initializing the same
    transactional.id bumps the producer epoch; the zombie's in-flight
    transaction aborts, and every further call through it — produce,
    commit-after-fence, abort — raises."""
    mod = kafka_broker.make_module()
    a = mod.Producer({"transactional.id": "t1"})
    a.init_transactions()
    a.begin_transaction()
    a.produce("out", value=b"zombie")
    # resurrection: a replacement initializes the same transactional.id
    b = mod.Producer({"transactional.id": "t1"})
    b.init_transactions()
    with pytest.raises(mod.KafkaException, match="fenced"):
        a.produce("out", value=b"late")
    with pytest.raises(mod.KafkaException, match="fenced"):
        a.commit_transaction()
    with pytest.raises(mod.KafkaException, match="fenced"):
        a.abort_transaction()
    b.begin_transaction()
    b.produce("out", value=b"fresh")
    b.commit_transaction()
    vals = [m.value() for p in sorted(kafka_broker.topic("out"))
            for m in kafka_broker.visible("out", p) if m.committed]
    assert vals == [b"fresh"]
    assert "t1" in kafka_broker.aborted_tx


def test_kafka_duplicate_commit_idempotent(kafka_broker):
    """A replayed commit (2PC recovery) must neither error nor re-expose:
    the broker treats a commit for an already-committed transaction as a
    no-op; a commit with NO transaction history is an error."""
    mod = kafka_broker.make_module()
    p = mod.Producer({"transactional.id": "t2"})
    p.init_transactions()
    p.begin_transaction()
    p.produce("out", value=b"once")
    p.commit_transaction()
    p.commit_transaction()  # replay: idempotent, no error
    kafka_broker.commit_tx("t2", epoch=p.epoch)  # broker-level replay too
    vals = [m.value() for pt in sorted(kafka_broker.topic("out"))
            for m in kafka_broker.visible("out", pt) if m.committed]
    assert vals == [b"once"]
    q = mod.Producer({"transactional.id": "t3"})
    q.init_transactions()
    with pytest.raises(mod.KafkaException, match="open transaction"):
        q.commit_transaction()


def test_kafka_aborted_messages_skipped_by_read_committed(kafka_broker):
    """Read-committed consumers skip aborted-transaction messages (abort
    markers) instead of stalling at them, and still stop at the LSO of an
    OPEN transaction."""
    mod = kafka_broker.make_module()
    a = mod.Producer({"transactional.id": "t4"})
    a.init_transactions()
    a.begin_transaction()
    a.produce("t", value=b"aborted")  # partition 0
    a.abort_transaction()
    b = mod.Producer({})
    b.produce("t", value=b"plain")  # partition 0, after the aborted msg
    c = mod.Consumer({"auto.offset.reset": "earliest"})
    c.assign([mod.TopicPartition("t", 0)])
    msg = c.poll(0)
    assert msg is not None and msg.value() == b"plain"
    assert c.poll(0) is None


def test_kafka_recovery_replays_commit(kafka_broker, tmp_path):
    """Engine-level commit replay: after the 2PC commit lands, a
    controller failover re-delivering CommitMsg for the same epoch must
    be harmless — the sink has no pending producer for it and the
    visible output stays exactly-once."""
    _preload(kafka_broker, "in", [{"n": i} for i in range(20)])

    async def go():
        plan = plan_query(KAFKA_SQL, parallelism=1)
        eng = Engine(plan.graph, job_id="kfk4",
                     storage_url=str(tmp_path / "ck")).start()
        await asyncio.sleep(0.3)
        await eng.checkpoint_and_wait()  # epoch 1: tx sealed + committed
        before = sorted(r["n"] for r in _visible_rows(kafka_broker, "out"))
        await eng.commit(1)  # failover replay of the commit fan-out
        await asyncio.sleep(0.2)
        after = sorted(r["n"] for r in _visible_rows(kafka_broker, "out"))
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)
        return before, after

    before, after = asyncio.run(go())
    assert before == after, "replayed commit changed visibility"
    final = sorted(r["n"] for r in _visible_rows(kafka_broker, "out"))
    assert final == [i * 10 for i in range(20)]
    assert not kafka_broker.open_tx


def test_kinesis_source_resume_and_sink(tmp_path, monkeypatch):
    """Kinesis shard consumption with AFTER_SEQUENCE_NUMBER resume across
    a restore, and the sink's put_records batching."""
    stream = FakeKinesisStream(shards=2)
    monkeypatch.setitem(sys.modules, "boto3", stream.boto3())
    for i in range(60):
        stream.put(f"shardId-{i % 2:012d}", json.dumps({"n": i}).encode())
    # close the shards so the source drains and finishes (resharding end)
    stream.split_shard("shardId-000000000000", [])
    stream.split_shard("shardId-000000000001", [])
    out_stream = FakeKinesisStream(shards=1)
    # single fake boto3 serves both names; route by StreamName
    registry = {"in": stream, "out": out_stream}

    class _Boto3:
        @staticmethod
        def client(service, region_name=None):
            class _Router:
                def __getattr__(self, name):
                    def call(**kw):
                        target = registry[kw.get("StreamName", "in")]
                        client = target.boto3().client("kinesis")
                        return getattr(client, name)(**kw)

                    return call

            return _Router()

    monkeypatch.setitem(sys.modules, "boto3", _Boto3())
    sql = """
    CREATE TABLE src (n BIGINT) WITH (
      connector = 'kinesis', stream_name = 'in',
      source.init_position = 'earliest', type = 'source', format = 'json'
    );
    CREATE TABLE dst (n BIGINT) WITH (
      connector = 'kinesis', stream_name = 'out', type = 'sink',
      format = 'json'
    );
    INSERT INTO dst SELECT n FROM src;
    """

    async def go():
        plan = plan_query(sql, parallelism=1)
        eng = Engine(plan.graph, job_id="kin",
                     storage_url=str(tmp_path / "ck")).start()
        await eng.join(60)

    asyncio.run(go())
    got = sorted(
        json.loads(d)["n"]
        for s in out_stream.shards.values() for d in s
    )
    assert got == list(range(60))


def test_nats_jetstream_durable_resume(tmp_path, monkeypatch):
    """JetStream sequence positions checkpoint and restores resume after
    the acked sequence — no redelivery, no loss."""
    server = FakeNatsServer()
    monkeypatch.setitem(sys.modules, "nats", server.module())
    for i in range(25):
        server.publish(json.dumps({"n": i}).encode())
    # no stop_at: the subject stays open, so the source must keep serving
    # control (the stop-checkpoint) while idle
    url = str(tmp_path / "ck")
    sql = """
    CREATE TABLE src (n BIGINT) WITH (
      connector = 'nats', servers = 'fake:4222', subject = 's',
      'nats.stream' = 'st', type = 'source', format = 'json'
    );
    CREATE TABLE dst (n BIGINT) WITH (
      connector = 'single_file', path = '$OUT', format = 'json',
      type = 'sink'
    );
    INSERT INTO dst SELECT n FROM src;
    """.replace("$OUT", str(tmp_path / "out.json"))

    async def phase1():
        plan = plan_query(sql, parallelism=1)
        eng = Engine(plan.graph, job_id="nats", storage_url=url).start()
        await asyncio.sleep(0.2)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(phase1())
    for i in range(25, 40):
        server.publish(json.dumps({"n": i}).encode())
    server.stop_at = 40

    async def phase2():
        plan = plan_query(sql, parallelism=1)
        eng = Engine(plan.graph, job_id="nats", storage_url=url).start()
        await eng.join(60)

    asyncio.run(phase2())
    rows = sorted(
        json.loads(l)["n"]
        for l in open(tmp_path / "out.json") if l.strip()
    )
    assert rows == list(range(40)), f"{len(rows)} rows after resume"


def test_kafka_metadata_and_generated_columns(kafka_broker):
    """DDL `METADATA FROM 'key'` columns populate from the consumer
    (reference kafka metadata_defs, kafka/mod.rs:325) and GENERATED
    ALWAYS AS virtual columns compute after deserialization."""
    _preload(kafka_broker, "in", [{"n": i} for i in range(10)])
    sql = """
    CREATE TABLE src (
      n BIGINT,
      off BIGINT METADATA FROM 'offset_id',
      part INT METADATA FROM 'partition',
      top TEXT METADATA FROM 'topic',
      n2 BIGINT GENERATED ALWAYS AS (n * 2 + 1)
    ) WITH (
      connector = 'kafka', bootstrap_servers = 'fake:9092', topic = 'in',
      type = 'source', format = 'json', source.offset = 'earliest'
    );
    SELECT n, off, part, top, n2 FROM src;
    """
    rows = []

    async def go():
        plan = plan_query(sql, parallelism=1, preview_results=rows)
        eng = Engine(plan.graph).start()
        for _ in range(400):
            await asyncio.sleep(0.01)
            if len(rows) >= 10:
                break
        await eng.stop()
        await eng.join(30)

    asyncio.run(go())
    assert len(rows) == 10
    by_n = {r["n"]: r for r in rows}
    # rows preloaded round-robin over 2 partitions: n's partition = n % 2,
    # its offset within the partition = n // 2
    for n, r in by_n.items():
        assert r["part"] == n % 2
        assert r["off"] == n // 2
        assert r["top"] == "in"
        assert r["n2"] == n * 2 + 1


@pytest.fixture()
def mqtt_broker(monkeypatch):
    from fake_clients import FakeMqttBroker

    broker = FakeMqttBroker()
    import arroyo_tpu.connectors.mqtt as mmod

    monkeypatch.setattr(
        mmod, "require_client", lambda *names: broker.module()
    )
    return broker


def test_mqtt_session_resume_and_metadata(mqtt_broker):
    """A dropped connection reconnects with backoff; a durable session
    (client_id + clean_session=false) resumes delivery where it left off;
    METADATA FROM 'topic' columns populate."""
    mqtt_broker.preload("sensors/a", [
        json.dumps({"n": i}).encode() for i in range(6)
    ])
    mqtt_broker.drop_after = 3  # connection dies after 3 deliveries
    mqtt_broker.stop_at = 6
    sql = """
    CREATE TABLE src (
      n BIGINT,
      top TEXT METADATA FROM 'topic'
    ) WITH (
      connector = 'mqtt', url = 'mqtt://fake', topic = 'sensors/#',
      qos = '1', client_id = 'arroyo-test', type = 'source',
      format = 'json'
    );
    SELECT n, top FROM src;
    """
    rows = []

    async def go():
        plan = plan_query(sql, parallelism=1, preview_results=rows)
        eng = Engine(plan.graph).start()
        await eng.join(30)

    asyncio.run(go())
    assert sorted(r["n"] for r in rows) == list(range(6))
    assert all(r["top"] == "sensors/a" for r in rows)
    assert mqtt_broker.connects == 2  # one reconnect after the drop


def test_mqtt_sink_publishes_with_qos_and_retain(mqtt_broker):
    sql = """
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '1000', message_count = '5',
      start_time = '0'
    );
    CREATE TABLE out (counter BIGINT) WITH (
      connector = 'mqtt', url = 'mqtt://fake', topic = 'out/t',
      qos = '1', retain = 'true', type = 'sink', format = 'json'
    );
    INSERT INTO out SELECT counter FROM impulse;
    """

    async def go():
        plan = plan_query(sql, parallelism=1)
        eng = Engine(plan.graph).start()
        await eng.join(30)

    asyncio.run(go())
    assert len(mqtt_broker.published) == 5
    assert all(
        t == "out/t" and qos == 1 and retain
        for t, _p, qos, retain in mqtt_broker.published
    )
    vals = sorted(
        json.loads(p)["counter"] for _t, p, _q, _r in mqtt_broker.published
    )
    assert vals == list(range(5))


def test_kinesis_resharding_children_resume(tmp_path, monkeypatch):
    """A split closes the parent shard; its children are discovered on
    re-list, replay from TRIM_HORIZON, and drain to completion — no
    records lost across the reshard."""
    stream = FakeKinesisStream(shards=1)
    monkeypatch.setitem(sys.modules, "boto3", stream.boto3())
    parent = "shardId-000000000000"
    for i in range(20):
        stream.put(parent, json.dumps({"n": i}).encode())
    # reshard: parent -> two children, each with post-split records
    stream.split_shard(parent, ["shardId-000000000100",
                                "shardId-000000000101"])
    for i in range(20, 30):
        stream.put(f"shardId-0000000001{i % 2:02d}",
                   json.dumps({"n": i}).encode())
    # close the children too so the source finishes
    stream.split_shard("shardId-000000000100", [])
    stream.split_shard("shardId-000000000101", [])
    sql = """
    CREATE TABLE src (n BIGINT) WITH (
      connector = 'kinesis', stream_name = 'in',
      source.init_position = 'earliest', type = 'source', format = 'json'
    );
    SELECT n FROM src;
    """
    rows = []

    async def go():
        plan = plan_query(sql, parallelism=1, preview_results=rows)
        eng = Engine(plan.graph).start()
        await eng.join(60)

    asyncio.run(go())
    assert sorted(r["n"] for r in rows) == list(range(30))


@pytest.fixture()
def rabbit(monkeypatch):
    from fake_clients import FakeRabbit

    r = FakeRabbit()
    import arroyo_tpu.connectors.rabbitmq as rmod

    monkeypatch.setattr(rmod, "require_client", lambda *n: r.module())
    return r


def test_rabbitmq_source_acks_and_sink_publishes(rabbit, tmp_path):
    """The source sets consumer prefetch and acks its messages only at
    the checkpoint COMMIT phase (after the manifest is durable) or at
    end-of-stream; the sink publishes persistent messages with the
    configured routing key."""
    rabbit.queue_msgs = [json.dumps({"n": i}).encode() for i in range(8)]
    sql = """
    CREATE TABLE src (n BIGINT) WITH (
      connector = 'rabbitmq', url = 'amqp://fake', queue = 'in',
      prefetch = '17', type = 'source', format = 'json'
    );
    CREATE TABLE dst (n BIGINT) WITH (
      connector = 'rabbitmq', url = 'amqp://fake', queue = 'out',
      routing_key = 'out.rk', type = 'sink', format = 'json'
    );
    INSERT INTO dst SELECT n * 3 as n FROM src;
    """

    async def go():
        plan = plan_query(sql, parallelism=1)
        eng = Engine(plan.graph, job_id="rmq",
                     storage_url=str(tmp_path / "ck")).start()
        for _ in range(200):
            await asyncio.sleep(0.01)
            if len(rabbit.published) >= 8:
                break
        assert rabbit.acked == 0, "acked before any checkpoint committed"
        # checkpoint: acks ride the 2PC commit phase (dispatched async
        # after the manifest publish — poll briefly)
        await eng.checkpoint_and_wait()
        for _ in range(100):
            if rabbit.acked >= 8:
                break
            await asyncio.sleep(0.02)
        acked_mid = rabbit.acked
        rabbit.stop_at = 8
        await eng.join(30)
        return acked_mid

    acked_mid = asyncio.run(go())
    assert rabbit.prefetch == 17
    assert acked_mid == 8, "commit phase should have acked the epoch"
    assert rabbit.acked == 8
    assert len(rabbit.published) == 8
    assert all(rk == "out.rk" for _e, rk, _b in rabbit.published)
    vals = sorted(json.loads(b)["n"] for _e, _rk, b in rabbit.published)
    assert vals == [i * 3 for i in range(8)]


# -- redis ------------------------------------------------------------------

from fake_clients import FakeFluvioCluster, FakeRedisServer  # noqa: E402


@pytest.fixture()
def redis_server(monkeypatch):
    server = FakeRedisServer()
    import arroyo_tpu.connectors.redis as rmod

    monkeypatch.setattr(
        rmod, "require_client", lambda *m: server.make_module()
    )
    return server


@pytest.mark.parametrize("target", ["string", "list", "hash"])
def test_redis_sink_targets(redis_server, target, tmp_path):
    """Redis sink writes rows under prefix+key to the string/list/hash
    target (reference redis sink target enum,
    /root/reference/crates/arroyo-connectors/src/redis/)."""
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '1000000',
      message_count = '6', start_time = '0'
    );
    CREATE TABLE dst (counter BIGINT) WITH (
      connector = 'redis', address = 'redis://fake:6379',
      target = '{target}', \"target.key_prefix\" = 'row:',
      \"target.key_column\" = 'counter',
      type = 'sink', format = 'json'
    );
    INSERT INTO dst SELECT counter FROM impulse;
    """
    plan = plan_query(sql, parallelism=1)

    async def go():
        eng = Engine(plan.graph).start()
        await eng.join(30)

    asyncio.run(go())
    if target == "string":
        # last write per key wins
        assert sorted(redis_server.strings) == [f"row:{i}" for i in range(6)]
        assert json.loads(redis_server.strings["row:3"])["counter"] == 3
    elif target == "list":
        assert sorted(redis_server.lists) == [f"row:{i}" for i in range(6)]
        assert all(len(v) == 1 for v in redis_server.lists.values())
    else:
        assert sorted(redis_server.hashes) == [f"row:{i}" for i in range(6)]
        assert json.loads(
            redis_server.hashes["row:2"]["2"]
        )["counter"] == 2


def test_redis_lookup_join_with_cache(redis_server, tmp_path):
    """Lookup join against redis end to end; the TTL cache coalesces
    repeated keys into one GET each."""
    for i in range(4):
        redis_server.strings[f"u:{i}"] = json.dumps(
            {"uid": i, "name": f"user-{i}"}
        ).encode()
    sql = """
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '1000000',
      message_count = '20', start_time = '0'
    );
    CREATE TABLE users (
      uid BIGINT, name TEXT
    ) WITH (
      connector = 'redis', address = 'redis://fake:6379',
      type = 'lookup', lookup_key = 'uid', "target.key_prefix" = 'u:'
    );
    CREATE TABLE out (counter BIGINT, name TEXT) WITH (
      connector = 'single_file', path = '$out', format = 'json',
      type = 'sink'
    );
    INSERT INTO out
    SELECT counter, name FROM impulse
    JOIN users ON counter % 5 = users.uid;
    """.replace("$out", str(tmp_path / "out.json"))
    plan = plan_query(sql, parallelism=1)

    async def go():
        eng = Engine(plan.graph).start()
        await eng.join(30)

    asyncio.run(go())
    rows = [json.loads(l) for l in open(tmp_path / "out.json")]
    # counters 0..19 -> keys 0..4; uid 4 missing -> inner join drops 4 rows
    assert len(rows) == 16
    assert all(r["name"] == f"user-{r['counter'] % 5}" for r in rows)
    # 5 distinct keys, 20 lookups: the TTL cache made exactly 5 GETs
    # (misses cached too)
    assert redis_server.get_calls == 5


def test_redis_lookup_cache_ttl_expiry(redis_server):
    """The lookup cache re-fetches after its TTL: a changed value
    becomes visible, a fresh one doesn't."""
    import arroyo_tpu.connectors.redis as rmod

    redis_server.strings["k:a"] = b"v1"
    lk = rmod.RedisLookup("redis://fake:6379", "k:", ttl=0.05)
    assert lk.lookup("a") == b"v1"
    redis_server.strings["k:a"] = b"v2"
    assert lk.lookup("a") == b"v1", "cached value must serve inside TTL"
    import time as _t

    _t.sleep(0.06)
    assert lk.lookup("a") == b"v2", "expired entry must re-fetch"
    assert redis_server.get_calls == 2


# -- fluvio -----------------------------------------------------------------


@pytest.fixture()
def fluvio_cluster(monkeypatch):
    cluster = FakeFluvioCluster()
    import arroyo_tpu.connectors.fluvio as fmod

    monkeypatch.setattr(
        fmod, "require_client", lambda *m: cluster.make_module()
    )
    return cluster


FLUVIO_SQL = """
CREATE TABLE src (n BIGINT) WITH (
  connector = 'fluvio', topic = 'in', type = 'source', format = 'json'
);
CREATE TABLE dst (n BIGINT) WITH (
  connector = 'fluvio', topic = 'out', type = 'sink', format = 'json'
);
INSERT INTO dst SELECT n * 10 AS n FROM src;
"""


def _fluvio_rows(cluster, topic):
    return [json.loads(v) for v in cluster.records(topic, 0)]


def test_fluvio_source_resume_from_checkpoint(fluvio_cluster, tmp_path):
    """Stop with a checkpoint, produce more records, restart: the source
    resumes at the checkpointed offset — every row exactly once
    (reference fluvio source offset state,
    /root/reference/crates/arroyo-connectors/src/fluvio/)."""
    for i in range(25):
        fluvio_cluster.append("in", 0, json.dumps({"n": i}).encode())
    url = str(tmp_path / "ck")

    async def phase(n_sleep):
        plan = plan_query(FLUVIO_SQL, parallelism=1)
        eng = Engine(plan.graph, job_id="flv1", storage_url=url).start()
        await asyncio.sleep(n_sleep)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(phase(0.3))
    assert sorted(r["n"] for r in _fluvio_rows(fluvio_cluster, "out")) == [
        i * 10 for i in range(25)
    ]
    for i in range(25, 40):
        fluvio_cluster.append("in", 0, json.dumps({"n": i}).encode())
    asyncio.run(phase(0.3))
    final = sorted(r["n"] for r in _fluvio_rows(fluvio_cluster, "out"))
    assert final == [i * 10 for i in range(40)], (
        "fluvio offset restore lost or duplicated rows"
    )
