"""Mesh-sharded window state: the multi-chip execution path.

The reference scales keyed aggregation by running parallel subtasks wired
with a TCP shuffle (/root/reference/crates/arroyo-worker/src/
network_manager.rs; engine.rs:209-365 is the subtask wiring). The
TPU-native equivalent keeps ALL key shards' accumulator state resident on
a device mesh and replaces the network shuffle with one
`jax.lax.all_to_all` over ICI inside the jitted step:

    host: rows -> global slots   [MeshSlotDirectory: hash keys to an
                                  owning shard; per-shard directories
                                  assign local slots]
    device (shard_map over 1-D "keys" mesh):
        scatter-reduce into the local accumulator shard, rows arriving
        either pre-routed (host-fed dst-major [S, R] packing — the
        sharded host->device transfer IS the shuffle) or via an in-step
        all_to_all over ICI ([S, S, R] src-major packing, for
        device-resident producers and the multi-host shuffle)
    emission: jitted (shard, slot) gather -> host, once per watermark

One jitted step per batch; state never leaves HBM between batches. This is
an *engine execution mode*, not a demo: window operators construct this
pair when `tpu.mesh_devices >= 2` (operators/windows.py) and run their
normal assign/update/gather/checkpoint protocol against it — global slots
encode (shard, local slot) so every Accumulator API carries over.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import device as obs_device
from ..ops.aggregates import (
    Accumulator,
    AggSpec,
    _bucket,
    _neutral,
    _np_dtype,
)
from ..ops.directory import SlotDirectory
from ..types import hash_arrays, hash_column, server_for_hash_array

# global slot encoding: slot = shard * STRIDE + local. The stride is fixed
# (not the current capacity) so capacity growth never re-numbers live slots.
STRIDE = 1 << 32

# process-wide packed-exchange traffic diagnostics (direct [S, R] or
# all_to_all [S, S, R] layout, whichever each update used), aggregated
# across every ShardedAccumulator instance; bench --mesh reads these to
# report the padding overhead of the host->device/ICI row shipment and
# the dispatch amortization (device steps per engine update call).
# flushes_elided counts state reads that skipped the pre-read flush
# because no pending update row touched the slots being read.
MESH_STATS = {"rows_sent": 0, "rows_padded": 0,
              "dispatches": 0, "updates": 0, "flushes_elided": 0,
              "rows_combined": 0}


class MeshSlotDirectory:
    """SlotDirectory facade over per-shard directories: keys hash to an
    owning shard (same splitmix64 hashing as the host shuffle), the shard's
    directory assigns a local slot, and callers see global slots.

    Per-shard directories default to the python SlotDirectory; operators
    whose keys flatten to int64 words swap them to the native C++ table
    (`swap_to_native`) — round-5 mesh profile showed the python per-shard
    assigns + tuple-per-key emission as the largest host cost on the
    mesh path. Session windows keep python shards (imperative
    alloc_slot/free lists live there)."""

    def __init__(self, n_shards: int):
        self.n_shards = n_shards
        self.dirs = [SlotDirectory() for _ in range(n_shards)]
        self._native = False

    def swap_to_native(self, native_mod, n_keys: int) -> bool:
        """Replace the per-shard python directories with C++ tables
        (callable only while empty). Returns True on swap."""
        if native_mod is None or any(d.n_live for d in self.dirs):
            return False
        from ..ops.native import NativeSlotDirectory

        self.dirs = [
            NativeSlotDirectory(native_mod, n_keys=n_keys)
            for _ in range(self.n_shards)
        ]
        self._native = True
        # bound as instance attributes so the window operators' array
        # fast paths (attribute probes) engage exactly when arrays exist
        self.take_bin_arrays = self._take_bin_arrays
        self.bin_entries_multi = self._bin_entries_multi
        return True

    @property
    def n_live(self) -> int:
        return sum(d.n_live for d in self.dirs)

    @property
    def by_bin(self):
        # truthiness/membership probe ("anything live?", "which bins?") —
        # values are True like the native directory, not per-key maps, so
        # the per-watermark check stays O(bins) not O(keys)
        return {b: True for d in self.dirs for b in d.by_bin}

    def required_capacity(self) -> int:
        """Per-shard capacity needed (max across shards, + scratch)."""
        return max(d.required_capacity() for d in self.dirs)

    def owners_for(self, key_cols: List[np.ndarray], n_rows: int) -> np.ndarray:
        if not key_cols:
            return np.zeros(n_rows, dtype=np.int64)
        return server_for_hash_array(
            hash_arrays([hash_column(c) for c in key_cols]), self.n_shards
        )

    def assign(
        self, bins: np.ndarray, key_cols: List[np.ndarray]
    ) -> np.ndarray:
        n = len(bins)
        if n == 0:
            return np.empty(0, dtype=np.int64)
        owners = self.owners_for(key_cols, n)
        out = np.empty(n, dtype=np.int64)
        for shard in range(self.n_shards):
            sel = np.nonzero(owners == shard)[0]
            if len(sel) == 0:
                continue
            local = self.dirs[shard].assign(
                bins[sel], [c[sel] for c in key_cols]
            )
            out[sel] = shard * STRIDE + local
        return out

    def bins_up_to(self, bin_exclusive: int) -> List[int]:
        bins = set()
        for d in self.dirs:
            bins.update(b for b in d.by_bin if b < bin_exclusive)
        return sorted(bins)

    def live_bins(self) -> List[int]:
        bins = set()
        for d in self.dirs:
            bins.update(d.by_bin)
        return sorted(bins)

    def peek_bin(self, b: int) -> Optional[dict]:
        out = {}
        for shard, d in enumerate(self.dirs):
            m = d.peek_bin(b)
            if m:
                for key, slot in m.items():
                    out[key] = shard * STRIDE + slot
        return out or None

    def bin_entries(self, b: int):
        if self._native:
            # native shards return int64 key MATRICES — concatenating
            # them keeps the emission path vectorized end to end (the
            # sliding merge branches on ndarray keys)
            mats: List[np.ndarray] = []
            slot_chunks = []
            for shard, d in enumerate(self.dirs):
                kmat, s = d.bin_entries(b)
                if len(s):
                    mats.append(kmat)
                    slot_chunks.append(s + shard * STRIDE)
            if not slot_chunks:
                return (np.empty((0, self.dirs[0]._stride), dtype=np.int64),
                        np.empty(0, dtype=np.int64))
            return np.concatenate(mats), np.concatenate(slot_chunks)
        keys: List[tuple] = []
        slot_chunks = []
        for shard, d in enumerate(self.dirs):
            k, s = d.bin_entries(b)
            keys.extend(k)
            slot_chunks.append(s + shard * STRIDE)
        return keys, (
            np.concatenate(slot_chunks)
            if slot_chunks
            else np.empty(0, dtype=np.int64)
        )

    def take_bin(self, b: int) -> Tuple[List[tuple], np.ndarray]:
        keys: List[tuple] = []
        slot_chunks: List[np.ndarray] = []
        for shard, d in enumerate(self.dirs):
            k, s = d.take_bin(b)
            keys.extend(k)
            slot_chunks.append(s + shard * STRIDE)
        return keys, (
            np.concatenate(slot_chunks)
            if slot_chunks
            else np.empty(0, dtype=np.int64)
        )

    def _take_bin_arrays(self, b: int):
        """Vectorized take (native shards only — bound as
        `take_bin_arrays` by swap_to_native so the attribute probe in
        the window watermark path engages exactly when arrays exist).
        One C call per shard; outputs fill preallocated buffers."""
        per_shard: List[tuple] = []  # (shard, key cols, local slots)
        total = 0
        for shard, d in enumerate(self.dirs):
            cols, s = d.take_bin_arrays(b)
            if len(s):
                per_shard.append((shard, cols, s))
                total += len(s)
        stride = self.dirs[0]._stride
        if not per_shard:
            z = np.empty(0, dtype=np.int64)
            return [z for _ in range(stride)], z
        out_cols = [np.empty(total, dtype=np.int64) for _ in range(stride)]
        out_slots = np.empty(total, dtype=np.int64)
        off = 0
        for shard, cols, s in per_shard:
            n = len(s)
            for j, c in enumerate(cols):
                out_cols[j][off:off + n] = c
            np.add(s, shard * STRIDE, out=out_slots[off:off + n])
            off += n
        return out_cols, out_slots

    def _bin_entries_multi(self, bins) -> Tuple[np.ndarray, np.ndarray]:
        """Concatenated (key matrix, global slots) over SEVERAL bins in
        one native C call per shard (the sliding merge reads width/slide
        bins per emission; per-bin calls cost S x k crossings). Native
        shards only — bound by swap_to_native like take_bin_arrays."""
        bins_arr = np.ascontiguousarray(np.asarray(bins, dtype=np.int64))
        mats: List[np.ndarray] = []
        slot_chunks: List[np.ndarray] = []
        for shard, d in enumerate(self.dirs):
            kmat, s = d.bin_entries_multi(bins_arr)
            if len(s):
                mats.append(kmat)
                slot_chunks.append(s + shard * STRIDE)
        if not slot_chunks:
            return (np.empty((0, self.dirs[0]._stride), dtype=np.int64),
                    np.empty(0, dtype=np.int64))
        return np.concatenate(mats), np.concatenate(slot_chunks)

    def items(self):
        for shard, d in enumerate(self.dirs):
            base = shard * STRIDE
            if self._native:
                # one C call per shard; tuple building and iteration
                # stay in C-level passes (_rows_to_tuples + zip)
                bins, kmat, slots = d.entries_arrays()
                yield from zip(bins.tolist(), d._rows_to_tuples(kmat),
                               (slots + base).tolist())
            else:
                for b, key, slot in d.items():
                    yield b, key, base + slot

    def keys_for_slots(self, slots: np.ndarray):
        """(bin, key) per global slot via the shard directories' reverse
        maps (updating-aggregate dirty tracking); dispatched per shard so
        native shards answer in one C call, results scattered back with
        one object-array assignment per shard."""
        slots = np.asarray(slots, dtype=np.int64)
        out = np.empty(len(slots), dtype=object)
        shards = slots // STRIDE
        locs = slots % STRIDE
        for shard in range(self.n_shards):
            idx = np.nonzero(shards == shard)[0]
            if not len(idx):
                continue
            res = self.dirs[shard].keys_for_slots(locs[idx])
            # element-wise object fill (a bare out[idx] = res would let
            # numpy reshape the (bin, key) 2-tuples into a 2-D array)
            tmp = np.empty(len(res), dtype=object)
            tmp[:] = res
            out[idx] = tmp
        return out.tolist()

    def slots_for_keys(self, b: int, keys: List[tuple]) -> Dict[tuple, int]:
        """Point lookups across shards: each key lives on exactly one
        shard, so probe all shards with the full list and merge (native
        shards share ONE key matrix and answer in one C lookup each; the
        merge is a zip over the hit indices, no per-key method calls)."""
        if not keys:
            return {}
        out: Dict[tuple, int] = {}
        if self._native:
            flat = np.ascontiguousarray(
                self.dirs[0]._keys_to_matrix(keys).reshape(-1)
            )
            for shard, d in enumerate(self.dirs):
                present, slots_raw = d._d.lookup(int(b), flat)
                pres = np.frombuffer(present, dtype=np.uint8)
                hit = np.nonzero(pres)[0]
                if not len(hit):
                    continue
                gslots = np.frombuffer(slots_raw, dtype=np.int64)[hit]
                out.update(zip(
                    (keys[i] for i in hit.tolist()),
                    (gslots + shard * STRIDE).tolist(),
                ))
            return out
        for shard, d in enumerate(self.dirs):
            sub = d.slots_for_keys(b, keys)
            if sub:
                base = shard * STRIDE
                out.update((k, base + int(v)) for k, v in sub.items())
        return out

    def remove(self, b: int, keys: List[tuple]) -> np.ndarray:
        """Remove keys from a bin across shards; each key lives in exactly
        one shard, so per-shard removal of the full list is safe. Native
        shards share one key matrix (built once, one C call per shard).
        Returns freed GLOBAL slots."""
        if not keys:
            return np.empty(0, dtype=np.int64)
        freed = []
        if self._native:
            flat = np.ascontiguousarray(
                self.dirs[0]._keys_to_matrix(keys).reshape(-1)
            )
            for shard, d in enumerate(self.dirs):
                f = np.frombuffer(d._d.remove(int(b), flat), dtype=np.int64)
                if len(f):
                    freed.append(f + shard * STRIDE)
        else:
            for shard, d in enumerate(self.dirs):
                f = d.remove(b, keys)
                if len(f):
                    freed.append(f + shard * STRIDE)
        return (
            np.concatenate(freed) if freed else np.empty(0, dtype=np.int64)
        )

    # -- imperative slot allocation (session windows) -----------------------

    def alloc_slot(self, shard_hint: int) -> int:
        """Allocate one slot on a shard (round-robin hint from the caller);
        session bookkeeping assigns slots imperatively rather than through
        assign(). Python shards only (sessions never swap to native —
        the imperative free lists live in the python directory)."""
        if self._native:
            raise RuntimeError(
                "imperative slot allocation requires python shards"
            )
        d = self.dirs[shard_hint % self.n_shards]
        local = d.free.pop() if d.free else d._alloc()
        return (shard_hint % self.n_shards) * STRIDE + local

    def alloc_slots(self, n: int, shard_hint: int = 0) -> np.ndarray:
        """Vectorized round-robin block allocation: one call allocates n
        slots dealt evenly across shards (the session operator's slot
        pool refill — replaces one Python alloc_slot call per session)."""
        shards = (np.arange(n, dtype=np.int64) + shard_hint) % self.n_shards
        out = np.empty(n, dtype=np.int64)
        for shard in range(self.n_shards):
            idx = np.nonzero(shards == shard)[0]
            if not len(idx):
                continue
            block = self.dirs[shard].alloc_block(len(idx))
            out[idx] = np.asarray(block, dtype=np.int64) + shard * STRIDE
        return out

    def free_slot(self, slot: int):
        self.dirs[int(slot) // STRIDE].free.append(int(slot) % STRIDE)

    def free_slots(self, slots: np.ndarray):
        """Batch free: one list-extend per shard (session expiry waves
        and the session operator's slot-pool return at checkpoint)."""
        slots = np.asarray(slots, dtype=np.int64)
        if not len(slots):
            return
        shards = slots // STRIDE
        locs = slots % STRIDE
        for shard in range(self.n_shards):
            sel = np.nonzero(shards == shard)[0]
            if len(sel):
                self.dirs[shard].free.extend(locs[sel].tolist())


def _pow2_ladder(cap: int, floor: int = 16) -> tuple:
    """Bucket rungs from `floor` up to and including `cap`: power-of-2 at
    the very bottom, then progressively finer fractional steps as the
    octaves grow — quarter rungs (x1.25/x1.5/x1.75) from 32, eighth rungs
    from 128, sixteenth rungs from 512. Worst-case bucket overshoot is
    bounded by the rung spacing: 100% below 32, 25% to 128, 12.5% to 512,
    6.25% above — so the large packed buffers, where padded rows actually
    cost host->device/ICI bytes, average ~3% padding while the tiny
    buffers near the floor keep the compiled-program count low. The extra
    rungs cost one XLA program each only when actually hit, and compiled
    programs persist across processes (tpu.compilation_cache_dir)."""
    rb, b = [], floor
    while b < cap:
        rb.append(b)
        if b >= 512:
            num, denom = range(17, 32), 16
        elif b >= 128:
            num, denom = range(9, 16), 8
        elif b >= 32:
            num, denom = range(5, 8), 4
        else:
            num, denom = (), 1
        rb.extend(x for x in (b * s // denom for s in num) if x < cap)
        b *= 2
    rb.append(cap)
    return tuple(sorted(set(x for x in rb if x <= cap)))


def _get_shard_map():
    """jax.shard_map moved out of experimental in newer jax; support
    both homes (the 0.4.x line only ships jax.experimental.shard_map)."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    return shard_map


def _donate_state() -> tuple:
    """donate_argnums for the state-consuming jitted programs. On the
    jax 0.4.x line (shard_map still experimental) donating sharded
    int64 state buffers corrupts the allocator across repeated engine
    runs (glibc "corrupted double-linked list", observed on 0.4.37-cpu
    whenever a mesh run shares a process with another engine run), so
    donation only engages where shard_map has moved into core jax."""
    try:
        from jax import shard_map  # noqa: F401

        return (0,)
    except ImportError:
        return ()


def _scatter_body(phys, jnp):
    """Shared per-shard scatter-reduce: applies (flat_slots, valid, vals)
    rows into each physical accumulator row. Rows arrive PRE-REDUCED by
    the host combiner (one row per slot per flush): `valid` carries the
    segment's summed signs (row count for append-only streams, 0 for
    padding), add-source values arrive sign-folded (0 for padding), and
    min/max sources replace padding with the op's neutral."""

    def scatter(state_shards, flat_slots, valid_r, vals_r):
        out = []
        vi = 0
        for (op, dt, src, si), s in zip(phys, state_shards):
            row = s[0]
            if src == "one":
                v = valid_r.astype(row.dtype)
            else:
                v = vals_r[vi]
                vi += 1
                if op != "add":
                    v = jnp.where(valid_r != 0, v, _neutral(op, dt))
            if op == "add":
                row = row.at[flat_slots].add(v.astype(row.dtype))
            elif op == "min":
                row = row.at[flat_slots].min(v.astype(row.dtype))
            else:
                row = row.at[flat_slots].max(v.astype(row.dtype))
            out.append(row[None, :])
        return tuple(out)

    return scatter


class SharedMeshSlotDirectory:
    """Slot directory for SALTED mesh aggregation (low-cardinality
    groups, e.g. q5/q7's MAX-per-window stage where every key is the
    window itself): one flat host directory allocates GLOBALLY-unique
    local ids, the nominal owner shard derives as local % S, and the
    salted accumulator spreads each update row across ALL shards at the
    same local index, folding across the shard axis at gather. Without
    this, hash ownership puts every row of a window on one shard — at
    most #windows of S shards ever receive rows (the round-4 mesh
    padding analysis)."""

    def __init__(self, n_shards: int):
        self.n_shards = n_shards
        self._flat = SlotDirectory()

    def swap_to_native(self, native_mod, n_keys: int) -> bool:
        """Swap the flat python directory for the C++ table (callable
        only while empty): the salted window-only groupings flatten
        their window struct to int64 words, and the python per-row
        interning + dict assign showed up as the salted stage's largest
        host cost in the mesh profile. Session operators never swap —
        their imperative alloc_slot/free lists live python-side."""
        if native_mod is None or self._flat.n_live:
            return False
        from ..ops.native import NativeSlotDirectory

        self._flat = NativeSlotDirectory(native_mod, n_keys=n_keys)
        # bound as instance attributes so the window operators' array
        # fast paths (attribute probes) engage exactly when arrays exist
        self.take_bin_arrays = self._take_bin_arrays
        self.bin_entries_multi = self._bin_entries_multi
        return True

    def _take_bin_arrays(self, b: int):
        cols, slots = self._flat.take_bin_arrays(b)
        return cols, self._g(slots)

    def _bin_entries_multi(self, bins) -> Tuple[np.ndarray, np.ndarray]:
        kmat, slots = self._flat.bin_entries_multi(bins)
        return kmat, self._g(slots)

    def _g(self, locals_: np.ndarray) -> np.ndarray:
        locals_ = np.asarray(locals_, dtype=np.int64)
        return (locals_ % self.n_shards) * STRIDE + locals_

    def _g1(self, local: int) -> int:
        return (local % self.n_shards) * STRIDE + local

    @property
    def n_live(self) -> int:
        return self._flat.n_live

    @property
    def by_bin(self):
        return {b: True for b in self._flat.by_bin}

    def required_capacity(self) -> int:
        return self._flat.required_capacity()

    def assign(self, bins, key_cols) -> np.ndarray:
        return self._g(self._flat.assign(bins, key_cols))

    def bins_up_to(self, limit):
        return self._flat.bins_up_to(limit)

    def live_bins(self):
        return self._flat.live_bins()

    def peek_bin(self, b):
        m = self._flat.peek_bin(b)
        if not m:
            return None
        return {k: self._g1(s) for k, s in m.items()}

    def bin_entries(self, b):
        keys, slots = self._flat.bin_entries(b)
        return keys, self._g(slots)

    def take_bin(self, b):
        keys, slots = self._flat.take_bin(b)
        return keys, self._g(slots)

    def items(self):
        for b, key, s in self._flat.items():
            yield b, key, self._g1(s)

    def keys_for_slots(self, slots):
        return self._flat.keys_for_slots(
            np.asarray(slots, dtype=np.int64) % STRIDE
        )

    def remove(self, b, keys):
        return self._g(self._flat.remove(b, keys))

    def alloc_slot(self, shard_hint: int = 0) -> int:
        return self._g1(self._flat.alloc_slot())

    def alloc_slots(self, n: int, shard_hint: int = 0) -> np.ndarray:
        return self._g(self._flat.alloc_slots(n))

    def free_slot(self, slot: int):
        self._flat.free_slot(int(slot) % STRIDE)

    def free_slots(self, slots: np.ndarray):
        self._flat.free_slots(np.asarray(slots, dtype=np.int64) % STRIDE)


class ShardedAccumulator(Accumulator):
    """Accumulator whose slot arrays live sharded across a 1-D device mesh;
    updates route rows to their owning device with an in-step all_to_all.
    Slots are MeshSlotDirectory global slots (shard * STRIDE + local)."""

    def __init__(
        self,
        specs: List[AggSpec],
        mesh,
        capacity_per_shard: int = 4096,
        rows_per_shard: int = 1024,
        host_fed: bool = True,
        salted: bool = False,
        flush_rows: int = 0,
    ):
        # initialize host-side bookkeeping via the base class with backend
        # 'numpy' (cheap), then replace the state with mesh-sharded arrays
        super().__init__(specs, capacity=capacity_per_shard, backend="numpy")
        self.backend = "jax-mesh"
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_shards = mesh.devices.size
        self.rows_per_shard = rows_per_shard
        # per-cell row counts are bucketed so the packed [S, S, R] buffer
        # is sized to the BATCH, not to the configured maximum: a 8192-row
        # batch on 8 shards packs into R=128 (8192 rows total) instead of
        # the old fixed R=1024 (65536 rows, 87% padding). Power-of-2 rungs
        # cap padding at 50% past the floor and bound the distinct
        # compiled step programs at log2(rows_per_shard/16) + 1 per
        # accumulator layout; in steady state only the rungs matching the
        # pipeline's characteristic batch sizes ever compile.
        # floor 2: post-combiner flushes can be a handful of rows (the
        # salted low-cardinality stage combines a whole flush down to
        # its few windows), and the old floor of 16 made such dispatches
        # ship 8x-64x filler
        self._r_buckets = _pow2_ladder(rows_per_shard, floor=2)
        # batches that arrive from the HOST are already globally visible,
        # so the hash-shuffle can happen in numpy at packing time: rows
        # are laid out dst-major [S, R] and the sharded transfer routes
        # each shard's block straight to its device — no all_to_all, and
        # the buffer is S x smaller than the [S, S, R] exchange layout.
        # The all_to_all path remains for device-resident producers
        # (chained device operators, multi-host ICI shuffle) where rows
        # are born sharded by SOURCE and must route by KEY on-device.
        self.host_fed = host_fed
        self._r_buckets_direct = _pow2_ladder(
            rows_per_shard * self.n_shards, floor=2
        )
        # emission/reset/restore padding uses the accumulator's OWN
        # power-of-2 ladder rather than the coarse global
        # tpu.shape_buckets (whose big rungs exist for the TPU-relay
        # compile budget of the single-device path): a ~2k-slot
        # watermark gather padded to an 8192 bucket wastes 4x gather
        # work + device->host bytes per emission. Plain pow2 (not the
        # fine fractional rungs of the packing ladders): gather padding
        # is cheap index work, while every distinct shape costs a
        # python-side trace per process — emission sizes vary per wave,
        # so coarse rungs keep the program count (and per-run fixed
        # tracing cost) low where fine rungs buy nothing.
        self._buckets = tuple(1 << i for i in range(4, 21))
        # salted mode (SharedMeshSlotDirectory): update rows spread
        # row-position round-robin across ALL shards at the slot's local
        # index — perfectly balanced regardless of key skew — and gather
        # folds across the shard axis. Requires globally-unique locals
        # and fold-able phys ops (add/min/max; no host-state aggregates).
        self.salted = salted
        # padding diagnostics (VERDICT r3: "document rows-sent vs
        # rows-padded"): rows_sent counts real rows pushed through the
        # packed exchange (either layout); rows_padded counts the
        # neutral filler rows shipped alongside them
        self.rows_sent = 0
        self.rows_padded = 0
        # micro-batching: update() buffers rows host-side and ships one
        # packed exchange + scatter per `flush_rows` rows instead of per
        # engine batch; every state read (gather/reset/restore) that
        # touches a pending slot flushes first, so observers never see
        # stale state — reads of untouched slots keep buffering (the
        # watermark-emission gathers otherwise force a flush per engine
        # batch and pin dispatches/updates near 1). 0 = immediate.
        self.flush_rows = int(flush_rows)
        self._pending: List[tuple] = []   # (slots, vals_list, signs)
        self._pending_rows = 0
        # observed engine-batch row EWMA: the effective flush threshold
        # auto-tunes to >= 4 batches so a configured threshold below the
        # pipeline's natural batch size still coalesces dispatches
        self._ewma_rows = 0
        # multi-host: the mesh may span devices owned by several
        # processes (jax.distributed — parallel/multihost.py). All host
        # buffers then enter the device as GLOBAL arrays (each process
        # materializes only its addressable shards) and every mesh
        # process runs the same steps in lockstep.
        from .multihost import is_multiprocess_mesh

        self._multiproc = is_multiprocess_mesh(mesh)
        self._sharding = self._make_sharding()
        self.state = self._fresh_state(capacity_per_shard)
        self._step = self._make_step()
        self._direct_step = self._make_direct_step()
        self._mesh_gather_fn = None
        self._mesh_take_fn = None
        self._mesh_reset_fn = None
        self._mesh_restore_fn = None

    def _make_sharding(self):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        return NamedSharding(self.mesh, P(self.axis, None))

    def _fresh_state(self, capacity: int):
        from jax.sharding import PartitionSpec as P

        from .mesh import _get_jnp
        from .multihost import put_global

        _get_jnp()  # enable x64 before any placement
        return [
            put_global(
                np.full(
                    (self.n_shards, capacity),
                    _neutral(op, dt),
                    dtype=_np_dtype(dt),
                ),
                self.mesh,
                P(self.axis, None),
            )
            for op, dt, _, _ in self.phys
        ]

    def _to_dev(self, arr: np.ndarray, shard_dim0: bool):
        """Host buffer -> device array for step/gather inputs: sharded on
        dim 0 over the mesh axis (packed row buffers) or replicated
        (index vectors). Single-process fast path: plain jnp.asarray —
        jit re-shards as needed."""
        from .mesh import _get_jnp

        jnp = _get_jnp()
        if not self._multiproc:
            return jnp.asarray(arr)
        from jax.sharding import PartitionSpec as P

        from .multihost import put_global

        return put_global(arr, self.mesh,
                          P(self.axis) if shard_dim0 else P())

    def _decompose(self, slots: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        return slots // STRIDE, slots % STRIDE

    # -- capacity -----------------------------------------------------------

    def grow(self, min_capacity: int):
        """Grow every shard's local capacity (4x steps). Global slot ids are
        stride-encoded, so no live slot is re-numbered; the old per-shard
        scratch slot is reset to neutral before it becomes allocatable."""
        new_cap = self.capacity
        while new_cap < min_capacity:
            new_cap *= 4
        if new_cap == self.capacity:
            return
        import jax

        from .mesh import _get_jnp

        jnp = _get_jnp()
        old_cap = self.capacity
        phys = list(self.phys)
        n_shards = self.n_shards

        # one jitted program for ALL columns, with explicit out_shardings:
        # valid in both single- and multi-process mode (eager concatenate
        # of a global sharded array with a process-local pad is not).
        # grow() is rare (4x capacity steps), so a compile per call is
        # acceptable; a single program per grow beats one per column.
        @partial(jax.jit, donate_argnums=_donate_state(), out_shardings=self._sharding)
        def grow_fn(state):
            out = []
            for (op, dt, _, _), x in zip(phys, state):
                pad = jnp.full(
                    (n_shards, new_cap - old_cap), _neutral(op, dt),
                    dtype=_np_dtype(dt),
                )
                g = jnp.concatenate([x, pad], axis=1)
                out.append(g.at[:, old_cap - 1].set(_neutral(op, dt)))
            return out

        self.state = grow_fn(list(self.state))
        self.capacity = new_cap

    # -- update (hot path) --------------------------------------------------

    def update(
        self,
        slots: np.ndarray,
        cols: Dict[int, np.ndarray],
        signs: Optional[np.ndarray] = None,
    ):
        n = len(slots)
        if n == 0:
            return
        self._check_signed(signs)
        self._update_host(slots, cols, signs)
        if not self.phys:
            return
        MESH_STATS["updates"] += 1
        slots = np.asarray(slots)
        max_local = int((slots % STRIDE).max())
        if max_local >= self.capacity - 1:
            # jit scatters silently drop out-of-bounds updates — callers
            # must grow() first (windows.py _ensure_capacity does);
            # checked at update() time (capacity only ever grows before a
            # deferred flush, so the buffered check stays valid)
            raise ValueError(
                f"shard accumulator capacity exceeded: local slot "
                f"{max_local} >= capacity-1={self.capacity - 1}"
            )
        from ..ops.aggregates import _src_values

        vals = [
            np.asarray(_src_values(self.specs[si], src, cols))
            for op, dt, src, si in self.phys if src != "one"
        ]
        self._ewma_rows = (
            n if not self._ewma_rows else (self._ewma_rows * 7 + n) // 8
        )
        thr = self._flush_threshold()
        if thr <= n and not self._pending:
            self._dispatch_rows(slots, vals, signs)
            return
        self._pending.append(
            (slots, vals, None if signs is None else np.asarray(signs))
        )
        self._pending_rows += n
        if self._pending_rows >= thr:
            self.flush()

    def _flush_threshold(self) -> int:
        """Effective micro-batch threshold: the configured
        tpu.mesh_flush_rows, auto-raised to ~4 observed engine batches
        (bounded) so a threshold tuned for one workload still coalesces
        dispatches when the pipeline feeds bigger batches. 0 disables
        buffering entirely (immediate dispatch)."""
        if self.flush_rows <= 0:
            return 0
        return max(self.flush_rows, min(4 * self._ewma_rows, 1 << 20))

    def _flush_if_touches(self, slots: np.ndarray):
        """Flush pending update rows only when one could affect `slots`.
        State reads (gather/reset/restore) of slots no pending row
        touches keep buffering — correctness holds because every read
        path comes through here first, and the eventual flush applies
        the buffered scatters in their original order relative to any
        elided read (disjoint slot sets commute)."""
        if not self._pending:
            return
        slots = np.asarray(slots)
        if len(slots):
            for p_slots, _, _ in self._pending:
                if np.isin(p_slots, slots, assume_unique=False).any():
                    self.flush()
                    return
        MESH_STATS["flushes_elided"] += 1

    def flush(self):
        """Ship any buffered update rows to the device (one packed
        exchange covering every pending engine batch)."""
        if not self._pending:
            return
        if len(self._pending) == 1:
            slots, vals, signs = self._pending[0]
        else:
            slots = np.concatenate([p[0] for p in self._pending])
            vals = [
                np.concatenate([p[1][i] for p in self._pending])
                for i in range(len(self._pending[0][1]))
            ]
            if any(p[2] is not None for p in self._pending):
                signs = np.concatenate([
                    p[2] if p[2] is not None
                    else np.ones(len(p[0]), dtype=np.int64)
                    for p in self._pending
                ])
            else:
                signs = None
        self._pending = []
        self._pending_rows = 0
        self._dispatch_rows(slots, vals, signs)

    def _prereduce(self, slots: np.ndarray, vals: List[np.ndarray],
                   signs: Optional[np.ndarray]):
        """Host-side combiner: rows sharing a slot within one flush
        collapse into a single packed row — add sources sum (sign-
        weighted), min/max take their extremum, and the valid word
        carries the segment's summed signs (= row count on append-only
        streams). The packed exchange then ships O(unique slots) rows:
        hot keys no longer skew the per-destination counts that size the
        padded [S, R] buffer (the dominant residual padding source), and
        shipped bytes drop with the dedup ratio. Integer accumulators
        are exact under the reassociation; float sums see the same
        reordering class as XLA's scatter reduction."""
        n = len(slots)
        if n == 0:
            return slots, vals, signs
        # one argsort does all the segmenting work (np.unique would sort
        # a second time and build an inverse nothing needs): sorted-run
        # boundaries give the unique slots and the reduceat bounds
        order = np.argsort(slots, kind="stable")
        s_sorted = slots[order]
        new_seg = np.empty(n, dtype=bool)
        new_seg[0] = True
        np.not_equal(s_sorted[1:], s_sorted[:-1], out=new_seg[1:])
        bounds = np.nonzero(new_seg)[0]
        uniq = s_sorted[bounds]
        MESH_STATS["rows_combined"] += n - len(uniq)
        if len(uniq) == n:
            # no duplicates: only fold signs into add-source values so
            # the kernel's uniform pre-reduced semantics hold
            if signs is not None:
                out_vals = []
                vi = 0
                for op, dt, src, si in self.phys:
                    if src == "one":
                        continue
                    v = vals[vi]
                    vi += 1
                    out_vals.append(
                        v * signs.astype(v.dtype) if op == "add" else v
                    )
                vals = out_vals
            return slots, vals, signs
        sgn = signs[order] if signs is not None else None
        out_vals = []
        vi = 0
        for op, dt, src, si in self.phys:
            if src == "one":
                continue
            v = vals[vi][order]
            vi += 1
            if op == "add":
                if sgn is not None:
                    v = v * sgn.astype(v.dtype)
                out_vals.append(np.add.reduceat(v, bounds))
            elif op == "min":
                out_vals.append(np.minimum.reduceat(v, bounds))
            else:
                out_vals.append(np.maximum.reduceat(v, bounds))
        # per-slot summed signs (plain row count when unsigned): the
        # count word and the padding discriminator. Signed streams only
        # carry add phys (non-invertible aggregates replay host-side),
        # so a zero sum contributes zero everywhere — still correct.
        if sgn is not None:
            counts = np.add.reduceat(sgn, bounds)
        else:
            counts = np.diff(np.append(bounds, n))
        return uniq, out_vals, counts.astype(np.int64, copy=False)

    def _dispatch_rows(self, slots: np.ndarray, vals: List[np.ndarray],
                       signs: Optional[np.ndarray]):
        slots, vals, signs = self._prereduce(slots, vals, signs)
        n = len(slots)
        S, R = self.n_shards, self.rows_per_shard
        owners, locals_ = self._decompose(slots)
        if self.salted:
            # balanced spread: every shard takes ~n/S rows of each group;
            # the cross-shard fold happens at gather
            owners = np.arange(n, dtype=np.int64) % S
        order = np.argsort(owners, kind="stable")
        so = owners[order]
        starts = np.searchsorted(so, so, side="left")
        pos = np.arange(n, dtype=np.int64) - starts   # rank within owner
        if self.host_fed:
            # dst-major [S, R] direct layout: the host already sees every
            # row, so the key shuffle happens at packing time and the
            # sharded host->device transfer IS the routing.
            r_cap = self.rows_per_shard * S
            chunk = pos // r_cap
            for c in range(int(chunk.max()) + 1):
                in_chunk = chunk == c
                rows = order[in_chunk]
                pm = pos[in_chunk] - c * r_cap
                r_c = _bucket(int(pm.max()) + 1, self._r_buckets_direct)
                flat = so[in_chunk] * r_c + pm
                self._note_traffic(len(rows), S * r_c,
                                   "mesh.step_direct", r_c)
                self._dispatch(self._direct_step, (S, r_c), rows, flat,
                               locals_, vals, signs)
            return
        # Balanced packing into the [src, dst, row] all_to_all layout:
        # each destination shard's rows are dealt round-robin across the
        # S source positions, so every (src, dst) cell carries
        # ceil(count_dst / S) rows and the per-cell row budget R shrinks
        # to the bucketed max — the buffer is sized to the batch (plus
        # skew), not to the configured ceiling. Splits into multiple
        # steps only when the hottest destination overflows S *
        # rows_per_shard rows.
        srcs = pos % S
        cell = pos // S                               # row within cell
        chunk = cell // R
        for c in range(int(chunk.max()) + 1):
            in_chunk = chunk == c
            rows = order[in_chunk]
            cm = cell[in_chunk] - c * R
            r_c = _bucket(int(cm.max()) + 1, self._r_buckets)
            flat = (srcs[in_chunk] * S + so[in_chunk]) * r_c + cm
            self._note_traffic(len(rows), S * S * r_c, "mesh.step", r_c)
            self._dispatch(self._step, (S, S, r_c), rows, flat, locals_,
                           vals, signs)

    def _note_traffic(self, sent: int, shipped: int,
                      program: str = "mesh.step", rung: int = 0):
        self.rows_sent += sent
        self.rows_padded += shipped - sent
        MESH_STATS["rows_sent"] += sent
        MESH_STATS["rows_padded"] += shipped - sent
        # per-(program, rung) waste gauge: which packing rungs the
        # exchange actually hits and how much filler each ships
        obs_device.note_padding(program, rung, sent, shipped)

    def _dispatch(self, step, shape, rows, flat, locals_, vals, signs):
        """Pack (slots, valid, per-source values) buffers of `shape` and
        run one jitted step. Buffers enter the device sharded on dim 0
        (the destination-shard dimension in both layouts). `vals` holds
        one value array per non-count physical accumulator, pre-extracted
        at update() time so buffered flushes just concatenate."""
        MESH_STATS["dispatches"] += 1
        total = int(np.prod(shape))
        slots_l = np.full(total, self.capacity - 1, dtype=np.int64)
        slots_l[flat] = locals_[rows]
        valid = np.zeros(total, dtype=np.int64)
        valid[flat] = 1 if signs is None else signs[rows]
        inputs = []
        vi = 0
        for op, dt, src, si in self.phys:
            if src == "one":
                continue
            v = np.full(
                total,
                0 if op == "add" else _neutral(op, dt),
                dtype=_np_dtype(dt),
            )
            # sign application happens in-kernel: add-sources multiply by
            # valid (0 padding / ±1 append-retract)
            v[flat] = vals[vi][rows]
            vi += 1
            inputs.append(self._to_dev(v.reshape(shape), True))
        self.state = step(
            self.state,
            self._to_dev(slots_l.reshape(shape), True),
            self._to_dev(valid.reshape(shape), True),
            *inputs,
            rung=shape[-1],
        )

    def _make_step(self):
        import jax

        from .mesh import _get_jnp

        jnp = _get_jnp()
        phys = list(self.phys)
        axis = self.axis

        scatter = _scatter_body(phys, jnp)

        def local_update(state_shards, slots, valid, *vals):
            # local views: state [1, cap]; slots/valid/vals [1, S, R] where
            # dim1 indexes the destination shard. all_to_all over the mesh
            # axis exchanges those blocks (the ICI shuffle): afterwards
            # [S, R] holds the rows every source shard sent to THIS shard.
            def exchange(x):
                return jax.lax.all_to_all(x[0], axis, 0, 0, tiled=True)

            valid_r = exchange(valid).reshape(-1)
            flat_slots = exchange(slots).reshape(-1)
            vals_r = [exchange(v).reshape(-1) for v in vals]
            return scatter(state_shards, flat_slots, valid_r, vals_r)

        n_state = len(self.phys)

        @partial(jax.jit, donate_argnums=_donate_state(), static_argnums=())
        def step(state, slots, valid, *vals):
            from jax.sharding import PartitionSpec as P

            f = _get_shard_map()(
                local_update,
                mesh=self.mesh,
                in_specs=(
                    tuple(P(axis, None) for _ in range(n_state)),
                    P(axis, None),
                    P(axis, None),
                )
                + tuple(P(axis, None) for _ in vals),
                out_specs=tuple(P(axis, None) for _ in range(n_state)),
            )
            return list(f(tuple(state), slots, valid, *vals))

        return obs_device.InstrumentedJit("mesh.step", step)

    def _make_direct_step(self):
        """Step for host-fed dst-major [S, R] batches: rows were routed to
        their owner shard at packing time, so each shard scatters its own
        block — no collective in the program at all."""
        import jax

        from .mesh import _get_jnp

        jnp = _get_jnp()
        phys = list(self.phys)
        axis = self.axis
        scatter = _scatter_body(phys, jnp)

        def local_update(state_shards, slots, valid, *vals):
            # local views: state [1, cap]; slots/valid/vals [1, R] — this
            # shard's rows, already in place after the sharded transfer
            return scatter(
                state_shards, slots[0], valid[0], [v[0] for v in vals]
            )

        n_state = len(self.phys)

        @partial(jax.jit, donate_argnums=_donate_state(), static_argnums=())
        def step(state, slots, valid, *vals):
            from jax.sharding import PartitionSpec as P

            f = _get_shard_map()(
                local_update,
                mesh=self.mesh,
                in_specs=(
                    tuple(P(axis, None) for _ in range(n_state)),
                    P(axis),
                    P(axis),
                )
                + tuple(P(axis) for _ in vals),
                out_specs=tuple(P(axis, None) for _ in range(n_state)),
            )
            return list(f(tuple(state), slots, valid, *vals))

        return obs_device.InstrumentedJit("mesh.step_direct", step)

    # -- drain --------------------------------------------------------------

    def gather(self, slots: np.ndarray,
               materialize: bool = True) -> List[np.ndarray]:
        self._flush_if_touches(slots)
        self._gather_slots = np.asarray(slots)
        self._segment_udaf = None
        self._segment_multiset = None
        if len(slots) == 0:
            return [
                np.empty(0, dtype=_np_dtype(dt))
                for _, dt, _, _ in self.phys
            ]
        import jax

        from .multihost import to_host

        if self._mesh_gather_fn is None:
            if self.salted:
                phys = list(self.phys)

                def gather_fn(state, sh, loc):
                    # fold across the shard axis; padding rows point at
                    # the scratch slot, neutral on every shard
                    out = []
                    for (op, dt, _, _), s in zip(phys, state):
                        cols = s[:, loc]
                        if op == "add":
                            out.append(cols.sum(axis=0))
                        elif op == "min":
                            out.append(cols.min(axis=0))
                        else:
                            out.append(cols.max(axis=0))
                    return out
            else:

                def gather_fn(state, sh, loc):
                    return [s[sh, loc] for s in state]

            if self._multiproc:
                # emission values must be readable on EVERY process:
                # pin the outputs replicated so each host reads its
                # local copy (multihost.to_host)
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                gather_fn = jax.jit(
                    gather_fn,
                    out_shardings=NamedSharding(self.mesh, P()),
                )
            else:
                gather_fn = jax.jit(gather_fn)
            self._mesh_gather_fn = obs_device.InstrumentedJit(
                "mesh.gather", gather_fn
            )
        sh, loc = self._decompose(np.asarray(slots))
        padded = _bucket(len(slots), self._buckets)
        sh_p = np.zeros(padded, dtype=np.int64)
        loc_p = np.full(padded, self.capacity - 1, dtype=np.int64)
        sh_p[: len(slots)] = sh
        loc_p[: len(slots)] = loc
        obs_device.note_padding("mesh.gather", padded, len(slots), padded)
        outs = self._mesh_gather_fn(
            self.state, self._to_dev(sh_p, False),
            self._to_dev(loc_p, False), rung=padded,
        )
        if not materialize:
            if self._multiproc:
                # replicated outputs span remote devices; hand back this
                # process's local copy so later slicing / np.asarray work
                outs = [o.addressable_data(0) for o in outs]
            return [o[: len(slots)] for o in outs]
        return [to_host(o)[: len(slots)] for o in outs]

    def gather_and_reset(self, slots: np.ndarray,
                         materialize: bool = True) -> List[np.ndarray]:
        """Fused drain: ONE jitted program gathers the slots' values and
        writes them back to neutral — the tumbling/session emission path
        otherwise pays two device dispatches per watermark wave, and on
        the CPU mesh every dispatch costs milliseconds of XLA launch.
        Host-side per-slot state is NOT dropped here: the caller
        finalizes first (finalize reads the stores), then calls
        drop_host_state."""
        self._flush_if_touches(slots)
        self._gather_slots = np.asarray(slots)
        self._segment_udaf = None
        self._segment_multiset = None
        if len(slots) == 0 or not self.phys:
            return [
                np.empty(0, dtype=_np_dtype(dt))
                for _, dt, _, _ in self.phys
            ]
        import jax

        from .multihost import to_host

        if self._mesh_take_fn is None:
            phys = list(self.phys)
            salted = self.salted

            def take_fn(state, sh, loc):
                outs, new = [], []
                for (op, dt, _, _), s in zip(phys, state):
                    if salted:
                        cols = s[:, loc]
                        if op == "add":
                            outs.append(cols.sum(axis=0))
                        elif op == "min":
                            outs.append(cols.min(axis=0))
                        else:
                            outs.append(cols.max(axis=0))
                        # a salted slot's state lives on EVERY shard
                        new.append(s.at[:, loc].set(_neutral(op, dt)))
                    else:
                        outs.append(s[sh, loc])
                        new.append(s.at[sh, loc].set(_neutral(op, dt)))
                return outs, new

            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            self._mesh_take_fn = obs_device.InstrumentedJit(
                "mesh.take",
                jax.jit(
                    take_fn,
                    donate_argnums=_donate_state(),
                    # outs replicated (each process reads its local
                    # copy), state stays row-sharded
                    out_shardings=(
                        [NamedSharding(self.mesh, P())] * len(self.phys),
                        [self._sharding] * len(self.phys),
                    ),
                ),
            )
        sh, loc = self._decompose(np.asarray(slots))
        padded = _bucket(len(slots), self._buckets)
        sh_p = np.zeros(padded, dtype=np.int64)
        loc_p = np.full(padded, self.capacity - 1, dtype=np.int64)
        sh_p[: len(slots)] = sh
        loc_p[: len(slots)] = loc
        obs_device.note_padding("mesh.take", padded, len(slots), padded)
        outs, self.state = self._mesh_take_fn(
            self.state, self._to_dev(sh_p, False),
            self._to_dev(loc_p, False), rung=padded,
        )
        if not materialize:
            if self._multiproc:
                outs = [o.addressable_data(0) for o in outs]
            return [o[: len(slots)] for o in outs]
        return [to_host(o)[: len(slots)] for o in outs]

    def reset_slots(self, slots: np.ndarray):
        self._flush_if_touches(slots)
        self._drop_udaf_slots(slots)
        if len(slots) == 0 or not self.phys:
            return
        import jax

        if self._mesh_reset_fn is None:
            phys = list(self.phys)
            salted = self.salted

            @partial(jax.jit, donate_argnums=_donate_state(),
                     out_shardings=self._sharding)
            def reset_fn(state, sh, loc):
                if salted:
                    # a salted slot's state lives on EVERY shard
                    return [
                        s.at[:, loc].set(_neutral(op, dt))
                        for s, (op, dt, _, _) in zip(state, phys)
                    ]
                return [
                    s.at[sh, loc].set(_neutral(op, dt))
                    for s, (op, dt, _, _) in zip(state, phys)
                ]

            self._mesh_reset_fn = obs_device.InstrumentedJit(
                "mesh.reset", reset_fn
            )
        sh, loc = self._decompose(np.asarray(slots))
        padded = _bucket(len(slots), self._buckets)
        sh_p = np.zeros(padded, dtype=np.int64)
        loc_p = np.full(padded, self.capacity - 1, dtype=np.int64)
        sh_p[: len(slots)] = sh
        loc_p[: len(slots)] = loc
        self.state = self._mesh_reset_fn(
            self.state, self._to_dev(sh_p, False),
            self._to_dev(loc_p, False), rung=padded,
        )

    def restore(self, slots: np.ndarray, values: List[np.ndarray]):
        self._flush_if_touches(slots)
        values = self._restore_udaf_cols(slots, values)
        if len(slots) == 0 or not self.phys:
            return
        import jax

        if self._mesh_restore_fn is None:
            phys = list(self.phys)
            salted = self.salted

            @partial(jax.jit, donate_argnums=_donate_state(),
                     out_shardings=self._sharding)
            def restore_fn(state, sh, loc, *vals):
                if salted:
                    # restored value lands whole on the nominal shard;
                    # the other shards go neutral so the cross-shard
                    # fold reproduces it
                    return [
                        s.at[:, loc].set(_neutral(op, dt))
                        .at[sh, loc].set(v)
                        for (op, dt, _, _), s, v in zip(phys, state, vals)
                    ]
                return [
                    s.at[sh, loc].set(v) for s, v in zip(state, vals)
                ]

            self._mesh_restore_fn = obs_device.InstrumentedJit(
                "mesh.restore", restore_fn
            )
        sh, loc = self._decompose(np.asarray(slots))
        # bucket-pad like gather/reset so restore chunk sizes don't each
        # specialize the jitted scatter; padding rows write the neutral
        # value into the scratch slot
        n = len(slots)
        padded = _bucket(n, self._buckets)
        sh_p = np.zeros(padded, dtype=np.int64)
        loc_p = np.full(padded, self.capacity - 1, dtype=np.int64)
        sh_p[:n] = sh
        loc_p[:n] = loc
        vals_p = []
        for (op, dt, _, _), v in zip(self.phys, values):
            vp = np.full(padded, _neutral(op, dt), dtype=_np_dtype(dt))
            vp[:n] = np.asarray(v)
            vals_p.append(vp)
        self.state = self._mesh_restore_fn(
            self.state,
            self._to_dev(sh_p, False),
            self._to_dev(loc_p, False),
            *[self._to_dev(v, False) for v in vals_p],
            rung=padded,
        )
