"""Worker-side serve views: epoch-consistent keyed read snapshots.

A `ServeView` hangs off one keyed operator instance (one per subtask)
and mirrors the operator's *emitted* aggregates as a key -> value map
with three layers:

  * `stage` — rows emitted since the last checkpoint barrier. Written
    by the operator's emission path (window results at watermark
    drains, updating-aggregate flushes); never visible to reads.
  * `pending[epoch]` — rows sealed at capture of `epoch` (the runner
    calls `seal_op` right after `handle_checkpoint`, i.e. at the exact
    point PR 8's `serialize_delta` stamps dirty state with the epoch).
  * `served` — the fold of every pending epoch <= the read's published
    epoch. Reads fold lazily, so the view needs no notification when
    the controller publishes a manifest: the published epoch rides in
    on each QueryState request from the gateway.

Durability alignment: state the controller published at epoch P is
exactly what the operators had captured at P's barrier, so folding
pending epochs <= P reproduces the last durable view — a read can never
observe a half-captured epoch, a torn value, or (after recovery fenced
a generation) anything newer than the state the restore will replay.
Jobs WITHOUT durable state (no checkpoint barriers ever) run their
views in live mode: staged rows apply immediately and reads see the
latest emission, which is the only consistent level such a job has.

Routing: `owner_subtask` mirrors the engine's shuffle partitioning
exactly — per-column `types.hash_column` (splitmix64 / pandas siphash),
`hash_arrays` combine, `server_for_hash_array` hash-range map — so the
gateway's key -> subtask routing and a worker's local ownership check
agree with `parallel/sharded_state.py owners_for` by construction.
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ..analysis.model.effects import protocol_effect
from ..config import config
from ..types import hash_arrays, hash_column, server_for_hash_array
from ..utils.logging import get_logger

logger = get_logger("serve")

_TOMB = object()  # sealed deletion marker (updating-aggregate retraction)

# Follower read replicas (ISSUE 20): every viewed operator on a durable
# job mirrors its sealed view rows into a dedicated `__serve__`
# GlobalTable. seal_op runs inside the runner's capture span BEFORE
# table_manager.capture, so mirror writes land in the SAME epoch's delta
# chain as the operator state they reflect — a follower tailing the
# published chains reconstructs exactly the view a worker serves at that
# published epoch. The reserved meta key carries the view's describe()
# so a follower can serve without the compiled program.
SERVE_TABLE = "__serve__"
META_KEY = "__serve_meta__"

# key-column kinds: how request/staged values canonicalize + hash.
#   i = signed int / timestamp-as-int   u = unsigned int
#   f = float   s = string   o = other (unroutable; fan-out reads)
_KIND_DTYPE = {"i": np.int64, "u": np.uint64, "f": np.float64}


def _kind_of(arrow_type) -> str:
    import pyarrow as pa

    if pa.types.is_unsigned_integer(arrow_type):
        return "u"
    if pa.types.is_integer(arrow_type) or pa.types.is_timestamp(arrow_type):
        return "i"
    if pa.types.is_floating(arrow_type):
        return "f"
    if pa.types.is_string(arrow_type) or pa.types.is_large_string(arrow_type):
        return "s"
    return "o"


def canon_value(v, kind: str):
    """Canonical python form of one key component: the same value staged
    from an arrow column and parsed from a JSON request must compare AND
    hash identically."""
    if kind in ("i", "u"):
        if isinstance(v, datetime.datetime):
            return int(np.datetime64(v, "ns").astype(np.int64))
        return int(v)
    if kind == "f":
        return float(v)
    if kind == "s":
        return str(v)
    return _hashable(v)


def _hashable(v):
    """Hashable canonical form of an 'o'-kind key component (struct
    keys arrive as dicts from arrow, as lists from JSON requests)."""
    if isinstance(v, dict):
        return tuple(_hashable(v[k]) for k in sorted(v))
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, datetime.datetime):
        return int(np.datetime64(v, "ns").astype(np.int64))
    if isinstance(v, np.generic):
        return v.item()
    return v


def _plain(v):
    """Msgpack/JSON-safe deep conversion of a staged value."""
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, datetime.datetime):
        return int(np.datetime64(v, "ns").astype(np.int64))
    if isinstance(v, dict):
        return {str(k): _plain(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_plain(x) for x in v]
    if isinstance(v, np.generic):
        return v.item()
    return str(v)


def owner_subtask(key: Tuple, kinds: Tuple[str, ...], parallelism: int) -> int:
    """Owning subtask index for one canonical key tuple — the §2.9-2.11
    routing contract: per-column splitmix64/siphash, seeded xor-mix
    combine, contiguous hash-range map (types.server_for_hash_array)."""
    if parallelism <= 1 or not key:
        return 0
    cols = []
    for v, k in zip(key, kinds):
        dtype = _KIND_DTYPE.get(k)
        if dtype is not None:
            arr = np.asarray([v]).astype(dtype)
        else:
            arr = np.array([v], dtype=object)
        cols.append(hash_column(arr))
    return int(server_for_hash_array(hash_arrays(cols), parallelism)[0])


class ServeView:
    """One subtask's epoch-consistent keyed view of an operator's
    emitted aggregates (see module docstring for the layer semantics)."""

    def __init__(self, *, job_id: str, table: str, node_id: int,
                 task_index: int, parallelism: int,
                 key_names: List[str], key_kinds: Tuple[str, ...],
                 value_names: List[str], kind: str, live_mode: bool):
        self.job_id = job_id
        self.table = table
        self.node_id = node_id
        self.task_index = task_index
        self.parallelism = parallelism
        self.key_names = list(key_names)
        self.key_kinds = tuple(key_kinds)
        self.value_names = list(value_names)
        self.kind = kind  # "window" | "updating"
        self.live_mode = live_mode
        self.routable = all(k in _KIND_DTYPE or k == "s"
                            for k in self.key_kinds)
        self.served: Dict[Tuple, Any] = {}
        self.served_epoch = 0          # highest epoch folded into served
        self.pending: Dict[int, Dict[Tuple, Any]] = {}
        self._stage: Dict[Tuple, Any] = {}
        self._max_pending = max(1, int(config().serve.max_pending_epochs))

    # -- write side (operator emission + runner capture) ---------------------

    def canon_key(self, values) -> Tuple:
        return tuple(
            canon_value(v, k) for v, k in zip(values, self.key_kinds)
        )

    def stage(self, key: Tuple, value):
        if self.live_mode:
            self.served[key] = value
        else:
            self._stage[key] = value

    def stage_tomb(self, key: Tuple):
        if self.live_mode:
            self.served.pop(key, None)
        else:
            self._stage[key] = _TOMB

    def has_staged(self, key: Tuple) -> bool:
        return key in self._stage

    def seal(self, epoch: int) -> Optional[Dict[Tuple, Any]]:
        """Move the staged rows under `epoch` (called at checkpoint
        capture, synchronously at the barrier). Bounded: past
        serve.max_pending_epochs the oldest pending epoch folds forward
        (publication stalled far beyond the inflight window). Returns
        the sealed delta (None when nothing was staged) — seal_op
        mirrors it into the `__serve__` state table for followers."""
        if not self._stage:
            return None
        sealed = self._stage
        self.pending.setdefault(epoch, {}).update(sealed)
        self._stage = {}
        while len(self.pending) > self._max_pending:
            self._fold_one(min(self.pending))
        return sealed

    def _fold_one(self, epoch: int):
        for k, v in self.pending.pop(epoch).items():
            if v is _TOMB:
                self.served.pop(k, None)
            else:
                self.served[k] = v
        self.served_epoch = max(self.served_epoch, epoch)

    def fold_to(self, epoch: int):
        for e in sorted(self.pending):
            if e > epoch:
                break
            self._fold_one(e)

    # -- read side -----------------------------------------------------------

    @protocol_effect("serve.read")
    def read(self, key: Tuple, epoch: Optional[int]):
        """(found, value) at the given published epoch (None = live
        mode: serve whatever has been folded/staged so far). Rows sealed
        at epochs > `epoch` stay invisible — the no-torn-read contract
        the model checker's reader actor pins."""
        if epoch is not None and not self.live_mode:
            self.fold_to(epoch)
        if key in self.served:
            return True, self.served[key]
        return False, None

    def stats(self) -> dict:
        return {
            "table": self.table,
            "task_index": self.task_index,
            "keys": len(self.served),
            "pending_epochs": len(self.pending),
            "staged": len(self._stage),
            "served_epoch": self.served_epoch,
        }

    def describe(self) -> dict:
        return {
            "table": self.table,
            "node_id": self.node_id,
            "parallelism": self.parallelism,
            "key_fields": self.key_names,
            "key_kinds": list(self.key_kinds),
            "value_fields": self.value_names,
            "kind": self.kind,
            "routable": self.routable,
            "live_mode": self.live_mode,
        }


# -- operator integration -----------------------------------------------------


def _view_plan(op, task_info) -> Optional[tuple]:
    """(kind, key_names, key_kinds, value_names) for an operator that
    gets a serve view, else None. Shared by register_op (attach at task
    start) and serve_mirror_tables (declare the `__serve__` mirror
    table BEFORE TableManager.open runs — both must agree, or a viewed
    operator would have no chain for followers to tail)."""
    from ..operators.updating import UpdatingAggregateOperator
    from ..operators.updating_join import UpdatingJoinOperator
    from ..operators.windows import WindowOperatorBase
    from ..schema import TIMESTAMP_FIELD

    if isinstance(op, UpdatingAggregateOperator):
        kind = "updating"
    elif isinstance(op, WindowOperatorBase):
        kind = "window"
    elif isinstance(op, UpdatingJoinOperator):
        # join views (ISSUE 20 satellite): key -> current joined row
        # set. Residual (non-equi) predicates filter EMITTED rows only;
        # serving the stored match set would show rows the residual
        # rejected, so such joins stay unserved rather than wrong.
        if op.residual is not None:
            return None
        kind = "join"
    else:
        return None
    if kind == "join":
        key_names = [f"__key{i}" for i in range(op.n_keys)]
    else:
        key_names = list(getattr(op, "_key_names", None) or [])
    if not key_names and task_info.parallelism > 1:
        # keyless aggregate on a parallel node: every subtask holds a
        # PARTIAL — no single owner can answer, so no view
        return None
    schema = op.out_schema.schema
    name_to_type = {f.name: f.type for f in schema}
    key_kinds = tuple(
        _kind_of(name_to_type[n]) if n in name_to_type else "o"
        for n in key_names
    )
    # every non-key output column is value payload EXCEPT the row
    # timestamp and the updating meta column; planner-internal aggregate
    # outputs (__agg_out_N) stay — they ARE the aggregate, the friendly
    # alias often lives on a downstream projection node
    if kind == "updating":
        # updating flushes stage (key -> finalized spec values) directly,
        # so the value names must align with the accumulator spec order
        value_names = [s.name for s in op.specs]
    else:
        # join views serve {"rows": [{field: value}]}; value_names
        # documents the per-row payload fields either way
        value_names = [
            f.name for f in schema
            if f.name not in key_names and f.name != TIMESTAMP_FIELD
            and f.name != "__updating_meta"
        ]
    return kind, key_names, key_kinds, value_names


def _mirror_eligible(op, task_info) -> bool:
    """Will this operator (ever) carry a serve view? The open-time
    twin of _view_plan's gate: serve_mirror_tables runs BEFORE
    on_start, when window/updating operators haven't captured their
    key NAMES yet (`_key_names` lands in _capture_key_meta), so
    keyedness is judged from construction-time attributes instead
    (`key_cols` / `n_keys`). Erring open is harmless — an unwritten
    mirror table captures empty and followers skip it for lack of a
    `__serve_meta__` record; erring closed would leave a viewed
    operator with no chain for followers to tail."""
    from ..operators.updating_join import UpdatingJoinOperator
    from ..operators.windows import WindowOperatorBase

    if isinstance(op, UpdatingJoinOperator):
        if op.residual is not None:
            return False
        keyed = int(op.n_keys) > 0
    elif isinstance(op, WindowOperatorBase):  # updating subclasses it
        keyed = bool(getattr(op, "key_cols", None)
                     or getattr(op, "_key_names", None))
    else:
        return False
    return keyed or task_info.parallelism == 1


def serve_mirror_tables(op, task_info) -> Dict[str, Any]:
    """Extra table configs the runner merges into op.tables() at open:
    viewed operators on durable jobs get the `__serve__` mirror
    GlobalTable (see module constants). Empty for everything else."""
    if not config().serve.enabled:
        return {}
    if not _mirror_eligible(op, task_info):
        return {}
    from ..state.table_config import global_table

    return {SERVE_TABLE: global_table(SERVE_TABLE)}


def register_op(op, ctx) -> Optional[ServeView]:
    """Attach a ServeView to a keyed operator at task start (called by
    the runner after on_start, once restore has run). Returns None —
    and leaves the operator untouched — when serving is disabled, the
    operator kind has no keyed view, or the view would be meaningless
    (keyless state on a parallel node holds per-subtask partials)."""
    if not config().serve.enabled:
        return None
    ti = ctx.task_info
    plan = _view_plan(op, ti)
    if plan is None:
        return None
    kind, key_names, key_kinds, value_names = plan
    view = ServeView(
        job_id=ti.job_id, table=op.name, node_id=ti.node_id,
        task_index=ti.task_index, parallelism=ti.parallelism,
        key_names=key_names, key_kinds=key_kinds,
        value_names=value_names, kind=kind,
        live_mode=ctx.table_manager is None,
    )
    op._serve_view = view
    if ctx.table_manager is not None:
        # restore seeding from the mirror table: the restored `__serve__`
        # chain IS the last published epoch's view (window finals,
        # session partials, join row sets alike) — without it a
        # recovered job would 404 every key until re-emission. The
        # restore unions ALL subtasks' chains; keep only owned keys so
        # per-subtask memory stays O(owned), not O(table).
        mirror = ctx.table_manager.tables.get(SERVE_TABLE)
        if mirror is not None:
            for k, v in mirror.items():
                if k == META_KEY or not isinstance(k, tuple):
                    continue
                if (view.routable and view.parallelism > 1
                        and owner_subtask(k, view.key_kinds,
                                          view.parallelism)
                        != view.task_index):
                    continue
                view.served[k] = v
    if kind == "updating" and getattr(op, "emitted", None):
        # restore seeding (pre-mirror jobs): the restored `emitted` map
        # is authoritative for updating aggregates — overwrite any
        # mirror-seeded copy
        for k, vals in op.emitted.items():
            try:
                key = view.canon_key(op._key_tuple_to_values(k))
            except Exception:  # noqa: BLE001 - exotic key shape
                continue
            view.served[key] = {
                n: _plain(v) for n, v in zip(view.value_names, vals)
            }
    return view


def _fast_pylist(col) -> list:
    """to_pylist with temporal values pre-cast to epoch nanos. Staged
    values land as int nanos anyway (_plain / canon_value), and int64
    to_pylist skips the per-element pandas Timestamp round-trip that
    dominates the staging hot path — including inside struct columns
    (window bounds are struct<start, end> of timestamps)."""
    if pa.types.is_timestamp(col.type):
        col = col.cast(pa.timestamp("ns")).cast(pa.int64())
    elif pa.types.is_struct(col.type) and col.null_count == 0:
        fields = [col.type.field(j).name
                  for j in range(col.type.num_fields)]
        children = [_fast_pylist(col.field(j))
                    for j in range(col.type.num_fields)]
        return [dict(zip(fields, row)) for row in zip(*children)]
    return col.to_pylist()


def stage_batch(view: ServeView, batch, partial: bool = False) -> list:
    """Stage every row of an emitted output batch into the view (the
    window operators' hook: one call per emitted window batch). Key
    columns index by the view's key order; all other non-internal
    columns become the value dict. `partial=True` (session-window open
    sessions) flags each value dict with `partial: True` — finals carry
    no flag. Returns the canonical keys staged (partial bookkeeping)."""
    names = batch.schema.names
    cols = {n: _fast_pylist(batch.column(i)) for i, n in enumerate(names)}
    vnames = [n for n in view.value_names if n in cols]
    knames = view.key_names
    # column-wise canonicalization: one pass per column, not one
    # isinstance chain per cell (this runs inside the checkpoint
    # capture span — per-row overhead is barrier latency)
    kcols = [[canon_value(v, k) for v in cols[n]]
             for n, k in zip(knames, view.key_kinds)]
    vcols = [(n, [_plain(v) for v in cols[n]]) for n in vnames]
    stage = view.stage
    staged = []
    for r in range(batch.num_rows):
        key = tuple(c[r] for c in kcols)
        value = {n: c[r] for n, c in vcols}
        if partial:
            value["partial"] = True
        stage(key, value)
        staged.append(key)
    return staged


def seal_op(op, epoch: int, table_manager=None) -> None:
    """Runner hook at checkpoint capture: seal the operator's staged
    rows under this barrier's epoch (no-op without a view). Operators
    exposing `serve_stage_snapshot` (session partials, join row sets)
    stage their snapshot delta first — inside the same barrier, so the
    snapshot rides this epoch. With a table manager, the sealed delta
    mirrors into the `__serve__` GlobalTable before capture serializes
    it, keeping the follower-visible chain in lockstep with the view."""
    view = getattr(op, "_serve_view", None)
    if view is None:
        return
    snap = getattr(op, "serve_stage_snapshot", None)
    if snap is not None:
        try:
            snap(view)
        except Exception:  # noqa: BLE001 - serving must not fail a barrier
            logger.exception("serve snapshot staging failed for %s",
                             view.table)
    sealed = view.seal(epoch)
    if table_manager is None or view.live_mode:
        return
    mirror = table_manager.tables.get(SERVE_TABLE)
    if mirror is None:
        return
    desc = view.describe()
    if mirror.get(META_KEY) != desc:
        mirror.put(META_KEY, desc)
    for k, v in (sealed or {}).items():
        if v is _TOMB:
            mirror.delete(k)
        else:
            mirror.put(k, v)


# -- the worker read handler --------------------------------------------------


def _views_of(program) -> Dict[str, Dict[int, ServeView]]:
    """{table: {task_index: view}} over one job's local subtasks. Table
    names qualify as `{name}@{node_id}` as well; the bare name resolves
    when it is unique across nodes."""
    out: Dict[str, Dict[int, ServeView]] = {}
    nodes: Dict[str, set] = {}
    for sub in program.subtasks:
        for op in sub.runner.ops:
            view = getattr(op, "_serve_view", None)
            if view is None:
                continue
            out.setdefault(f"{view.table}@{view.node_id}", {})[
                view.task_index] = view
            nodes.setdefault(view.table, set()).add(view.node_id)
    for name, nids in nodes.items():
        if len(nids) == 1:
            out[name] = out[f"{name}@{next(iter(nids))}"]
    return out


def worker_read(program, req: dict) -> dict:
    """Answer one QueryState request against a job's local views —
    synchronous dict work only, nothing here blocks the batch loop.

    Modes: `tables` lists the views this worker hosts; `get` resolves
    each key to its owning subtask (same hash the gateway used) and
    reads the local view at the request's published epoch. A key whose
    owner is not hosted here answers `not_owned` (gateway mis-route or
    rescale race — retriable)."""
    if not config().serve.enabled:
        return {"error": "serving disabled", "retriable": False}
    views = _views_of(program)
    if req.get("mode") == "tables":
        seen = []
        for name, by_task in sorted(views.items()):
            if "@" in name:
                continue
            any_view = next(iter(by_task.values()))
            seen.append(any_view.describe())
        for name, by_task in sorted(views.items()):
            if "@" in name and name.split("@")[0] not in views:
                seen.append(next(iter(by_task.values())).describe())
        return {"tables": seen}
    table = req.get("table") or ""
    by_task = views.get(table)
    if by_task is None:
        # retriable: the gateway only routes tables its (fresh) listing
        # knows, so a worker-side miss is a startup race — the runner
        # has not reached on_start/register yet (recovery, rescale).
        # Unknown table NAMES fail fast at the gateway, not here.
        return {"error": f"no such table {table!r} (yet)",
                "retriable": True}
    epoch = req.get("epoch")  # None = live mode
    max_keys = int(config().serve.max_keys)
    keys = req.get("keys") or []
    if len(keys) > max_keys:
        return {"error": f"too many keys (> {max_keys})",
                "retriable": False}
    any_view = next(iter(by_task.values()))
    results = []
    for raw in keys:
        vals = raw if isinstance(raw, (list, tuple)) else [raw]
        if len(vals) != len(any_view.key_kinds):
            results.append({"key": raw, "found": False,
                            "error": "key arity mismatch",
                            "retriable": False})
            continue
        try:
            key = any_view.canon_key(vals)
        except (TypeError, ValueError):
            results.append({"key": raw, "found": False,
                            "error": "bad key", "retriable": False})
            continue
        if any_view.routable:
            owner = owner_subtask(key, any_view.key_kinds,
                                  any_view.parallelism)
            view = by_task.get(owner)
            if view is None:
                results.append({"key": raw, "found": False,
                                "error": "not_owned", "retriable": True,
                                "owner": owner})
                continue
            found, value = view.read(key, epoch)
        else:
            # unroutable key shape: check every local subtask's view
            found, value = False, None
            for view in by_task.values():
                found, value = view.read(key, epoch)
                if found:
                    break
        results.append({"key": raw, "found": found, "value": value})
    return {"results": results, "epoch": epoch}


def view_stats(program) -> List[dict]:
    """Admin surface: per-view occupancy of one job's local views."""
    return [
        v.stats()
        for name, by_task in sorted(_views_of(program).items())
        if "@" in name
        for v in by_task.values()
    ]
