"""Generation/epoch checkpoint protocol: fencing, manifests, 2PC records.

Capability parity with the reference's arroyo-state-protocol crate
(/root/reference/crates/arroyo-state-protocol/src/workflow.rs): a new
*generation* is initialized each time a job (re)starts its controller
(:223 initialize_generation) — generation files are CAS-created so exactly
one writer owns a generation; checkpoint manifests are CAS-published
(:527 publish_checkpoint) under the owning generation, so a zombie
controller from an older generation cannot publish after failover;
sink commits are authorized by per-epoch records (:428 prepare_commit,
:495 complete_commit) so a 2PC commit happens exactly once even across
controller failover. Path layout mirrors ProtocolPaths (lib.rs:22-70).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Dict, List, Optional

from .. import chaos
from ..analysis.model.effects import protocol_effect
from .storage import CasConflict, StorageProvider


class Fenced(Exception):
    """The caller's generation is no longer current."""


class ProtocolPaths:
    def __init__(self, job_id: str):
        self.job_id = job_id

    @property
    def current_generation(self) -> str:
        return f"{self.job_id}/current-generation.json"

    def generation(self, gen: int) -> str:
        return f"{self.job_id}/generations/gen-{gen:05d}.json"

    def checkpoint_dir(self, epoch: int) -> str:
        return f"{self.job_id}/checkpoints/checkpoint-{epoch:07d}"

    def manifest(self, epoch: int) -> str:
        return f"{self.checkpoint_dir(epoch)}/checkpoint-manifest.json"

    def data_file(
        self, epoch: int, node_id: int, op_idx: int, table: str,
        subtask: int, ext: str, gen: Optional[int] = None,
    ) -> str:
        # the generation component fences zombie writers at the DATA
        # level: with multiple checkpoint flushes in flight, a paused
        # old-generation worker's late upload must not overwrite the new
        # incarnation's file for the same (epoch, table, subtask) — a
        # fenced writer's bytes land at a path no live manifest will
        # ever reference, and GC sweeps them
        g = f"-g{gen:05d}" if gen is not None else ""
        return (
            f"{self.checkpoint_dir(epoch)}/data/"
            f"{node_id:03d}-{op_idx}-{table}-{subtask:03d}{g}.{ext}"
        )

    def compacted_file(self, epoch: int, node_id: int, op_idx: int,
                       table: str) -> str:
        return (
            f"{self.job_id}/compacted/"
            f"{node_id:03d}-{op_idx}-{table}-epoch{epoch:07d}-"
            f"{uuid.uuid4().hex[:8]}.parquet"
        )

    @property
    def latest(self) -> str:
        return f"{self.job_id}/latest.json"

    def commit_pending(self, epoch: int) -> str:
        return f"{self.job_id}/commits/epoch-{epoch:07d}-pending.json"

    def commit_done(self, epoch: int) -> str:
        return f"{self.job_id}/commits/epoch-{epoch:07d}-done.json"


# -- generations ------------------------------------------------------------


@protocol_effect("storage.new_generation")
def initialize_generation(storage: StorageProvider, paths: ProtocolPaths) -> int:
    """Claim the next generation; the CAS-created generation file is the
    fencing token (reference workflow.rs:223)."""
    cur = read_json(storage, paths.current_generation)
    gen = (cur["generation"] if cur else 0) + 1
    while True:
        try:
            storage.put_if_not_exists(
                paths.generation(gen),
                _enc({"generation": gen, "claimed_at": time.time()}),
            )
            break
        except CasConflict:
            gen += 1  # another controller raced us; take the next slot
    storage.put(paths.current_generation, _enc({"generation": gen}))
    return gen


@protocol_effect("storage.check_fence")
def check_current(storage: StorageProvider, paths: ProtocolPaths, gen: int):
    if chaos.fire("protocol.fenced_zombie", generation=gen,
                  job_id=paths.job_id):
        # zombie-writer resurrect: behave exactly as if another controller
        # claimed a newer generation while this caller was paused
        raise Fenced(
            f"chaos[protocol.fenced_zombie]: generation {gen} treated as "
            "superseded (injected zombie fencing)"
        )
    cur = read_json(storage, paths.current_generation)
    if cur is None or cur["generation"] != gen:
        raise Fenced(f"generation {gen} superseded by {cur}")


# -- checkpoints ------------------------------------------------------------


@protocol_effect("storage.publish_manifest")
def publish_checkpoint(
    storage: StorageProvider,
    paths: ProtocolPaths,
    gen: int,
    epoch: int,
    manifest: Dict[str, Any],
):
    """CAS-publish a checkpoint manifest under the owning generation
    (reference workflow.rs:527). Raises Fenced for zombie writers."""
    check_current(storage, paths, gen)
    manifest = {**manifest, "epoch": epoch, "generation": gen,
                "published_at": time.time()}
    try:
        storage.put_if_not_exists(paths.manifest(epoch), _enc(manifest))
    except CasConflict:
        existing = read_json(storage, paths.manifest(epoch))
        if existing and existing.get("generation") == gen:
            return  # idempotent re-publish by the same generation
        raise Fenced(f"epoch {epoch} already published by {existing}")
    check_current(storage, paths, gen)  # re-check: fence the slow path
    storage.put(paths.latest, _enc({"epoch": epoch, "generation": gen}))


def resolve_latest(
    storage: StorageProvider, paths: ProtocolPaths
) -> Optional[Dict[str, Any]]:
    latest = read_json(storage, paths.latest)
    if latest is None:
        return None
    return read_json(storage, paths.manifest(latest["epoch"]))


def load_manifest(
    storage: StorageProvider, paths: ProtocolPaths, epoch: int
) -> Optional[Dict[str, Any]]:
    return read_json(storage, paths.manifest(epoch))


def cleanup_checkpoints(
    storage: StorageProvider, paths: ProtocolPaths, min_epoch: int,
    known_epochs: List[int],
):
    """Drop checkpoints older than min_epoch (reference gc.rs:19). Files
    referenced by newer manifests live outside the deleted dirs (compacted/
    or newer epochs' data dirs) except carried-forward incremental files —
    so only epochs whose data is no longer referenced may be passed here."""
    for e in known_epochs:
        if e < min_epoch:
            storage.delete_directory(paths.checkpoint_dir(e))


# -- 2PC commit records -----------------------------------------------------


@protocol_effect("storage.prepare_commit")
def prepare_commit(
    storage: StorageProvider, paths: ProtocolPaths, gen: int, epoch: int,
    committing: Dict[str, Any],
):
    """Record intent-to-commit (reference workflow.rs:428)."""
    check_current(storage, paths, gen)
    try:
        storage.put_if_not_exists(
            paths.commit_pending(epoch),
            _enc({"epoch": epoch, "generation": gen, "committing": committing}),
        )
    except CasConflict:
        pass  # already prepared (recovery replays are fine pre-commit)


@protocol_effect("storage.claim_commit")
def claim_commit(
    storage: StorageProvider, paths: ProtocolPaths, gen: int, epoch: int
) -> bool:
    """Exactly-once commit authorization (reference claim_epoch_record
    workflow.rs:829): returns True iff this caller owns the commit."""
    try:
        storage.put_if_not_exists(
            paths.commit_done(epoch),
            _enc({"epoch": epoch, "generation": gen, "committed_at": time.time()}),
        )
        return True
    except CasConflict:
        return False


def pending_commit(
    storage: StorageProvider, paths: ProtocolPaths, epoch: int
) -> Optional[Dict[str, Any]]:
    if storage.get(paths.commit_done(epoch)) is not None:
        return None  # already committed
    return read_json(storage, paths.commit_pending(epoch))


# -- helpers ----------------------------------------------------------------


def read_json(storage: StorageProvider, key: str) -> Optional[dict]:
    data = storage.get(key)
    return None if data is None else json.loads(data)


def _enc(obj: dict) -> bytes:
    return json.dumps(obj).encode()
