--udf=udfs.py
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE udaf (
  median DOUBLE,
  none_value DOUBLE,
  max_product BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO udaf
SELECT median, none_value, max_product FROM (
  SELECT tumble(interval '30 second') as window,
         my_median(counter) as median,
         none_udf(counter) as none_value,
         max_product(counter, subtask_index) as max_product
  FROM impulse_source
  GROUP BY 1
);
