"""Must NOT fire CFG002: every field carries a comment or docstring
mention."""
import dataclasses


@dataclasses.dataclass
class PipelineConfig:
    batch_size: int = 512  # rows per source batch
    # seconds a partial batch may linger before flushing
    linger: float = 0.1


@dataclasses.dataclass
class Config:
    """Sections: pipeline."""

    pipeline: PipelineConfig = dataclasses.field(default_factory=PipelineConfig)
