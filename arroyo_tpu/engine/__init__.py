from .construct import construct_chain, register_operator  # noqa: F401
from .engine import Engine, RunningEngine  # noqa: F401
from .program import Program  # noqa: F401
