"""SQL tokenizer."""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional


class SqlError(Exception):
    """User-facing SQL error (parse or plan time)."""


@dataclasses.dataclass
class Token:
    kind: str  # ident | number | string | op | punct | eof
    value: str
    pos: int  # character offset (for error messages)
    upper: str = ""

    def __post_init__(self):
        self.upper = self.value.upper()


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*|/\*.*?\*/)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*"|`[^`]*`)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><=|>=|<>|!=|==|\|\||->>|->|[+\-*/%<>=])
  | (?P<punct>[(),.;\[\]])
    """,
    re.VERBOSE | re.DOTALL,
)


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise SqlError(f"unexpected character {sql[pos]!r} at offset {pos}")
        kind = m.lastgroup
        text = m.group()
        if kind != "ws":
            if kind == "qident":
                out.append(Token("ident", text[1:-1].replace('""', '"'), pos))
            elif kind == "string":
                out.append(Token("string", text[1:-1].replace("''", "'"), pos))
            else:
                out.append(Token(kind, text, pos))
        pos = m.end()
    out.append(Token("eof", "", pos))
    return out


class TokenStream:
    def __init__(self, tokens: List[Token], sql: str = ""):
        self.tokens = tokens
        self.i = 0
        self.sql = sql

    def peek(self, ahead: int = 0) -> Token:
        return self.tokens[min(self.i + ahead, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.peek()
        if t.kind != "eof":
            self.i += 1
        return t

    def at_keyword(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.upper in words

    def accept_keyword(self, *words: str) -> Optional[Token]:
        if self.at_keyword(*words):
            return self.next()
        return None

    def expect_keyword(self, word: str) -> Token:
        t = self.next()
        if t.kind != "ident" or t.upper != word:
            raise SqlError(
                f"expected {word}, found {t.value!r} at offset {t.pos}"
            )
        return t

    def accept(self, kind: str, value: Optional[str] = None) -> Optional[Token]:
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            return self.next()
        return None

    def expect(self, kind: str, value: Optional[str] = None) -> Token:
        t = self.next()
        if t.kind != kind or (value is not None and t.value != value):
            want = value or kind
            raise SqlError(
                f"expected {want!r}, found {t.value or t.kind!r} at offset {t.pos}"
            )
        return t

    def error(self, message: str) -> SqlError:
        t = self.peek()
        return SqlError(f"{message} (near {t.value!r} at offset {t.pos})")
