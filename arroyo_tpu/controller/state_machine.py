"""Job state machine.

Capability parity with the reference's controller states
(/root/reference/crates/arroyo-controller/src/states/mod.rs:98-186):
Created -> Scheduling -> Running with Recovering (task/worker failure ->
teardown -> reschedule from the latest durable checkpoint), Rescaling
(checkpoint-stop -> reschedule with new parallelism), Restarting
(safe|force), Stopping/CheckpointStopping, and terminal
Stopped/Finished/Failed; retryable transitions with bounded backoff
(states/mod.rs:559).
"""

from __future__ import annotations

import enum


class JobState(enum.Enum):
    CREATED = "Created"
    COMPILING = "Compiling"
    SCHEDULING = "Scheduling"
    RUNNING = "Running"
    RESCALING = "Rescaling"
    RESTARTING = "Restarting"
    RECOVERING = "Recovering"
    STOPPING = "Stopping"
    CHECKPOINT_STOPPING = "CheckpointStopping"
    FINISHING = "Finishing"
    FAILING = "Failing"
    STOPPED = "Stopped"
    FINISHED = "Finished"
    FAILED = "Failed"

    def is_terminal(self) -> bool:
        return self in (JobState.STOPPED, JobState.FINISHED, JobState.FAILED)


# legal transitions (superset; the controller drives the actual flow)
TRANSITIONS = {
    JobState.CREATED: {JobState.COMPILING, JobState.SCHEDULING, JobState.FAILED},
    JobState.COMPILING: {JobState.SCHEDULING, JobState.FAILED},
    # scheduling is retryable (reference states/mod.rs:559 bounded
    # backoff): a worker dying between registration and StartExecution
    # recovers instead of crashing the driver
    JobState.SCHEDULING: {
        JobState.RUNNING, JobState.FAILED, JobState.STOPPED,
        JobState.RECOVERING,
    },
    JobState.RUNNING: {
        JobState.RECOVERING,
        JobState.RESCALING,
        JobState.RESTARTING,
        JobState.STOPPING,
        JobState.CHECKPOINT_STOPPING,
        JobState.FINISHING,
        JobState.FAILING,
        JobState.FINISHED,
        # direct error sink: a crashed job driver fails the job from
        # wherever it was (every other non-terminal state already declares
        # FAILED; the graceful path remains FAILING -> FAILED)
        JobState.FAILED,
    },
    JobState.RECOVERING: {JobState.SCHEDULING, JobState.FAILED},
    # a rescale whose stop checkpoint fails (worker killed mid-rescale,
    # storage fault) recovers from the latest durable manifest instead of
    # failing — the autoscaler retries once rates re-stabilize. The
    # RUNNING edge is the generation-overlap activation (ISSUE 15): the
    # new incarnation was staged and restored WHILE the old one drained,
    # so a successful overlap rescale never passes through SCHEDULING.
    JobState.RESCALING: {
        JobState.SCHEDULING, JobState.RUNNING, JobState.FAILED,
        JobState.RECOVERING,
    },
    JobState.RESTARTING: {JobState.SCHEDULING, JobState.FAILED},
    JobState.STOPPING: {JobState.STOPPED, JobState.FAILED},
    # a stop checkpoint whose publish fails (storage fault, fencing) must
    # not drop state silently: it recovers and retries the stop
    JobState.CHECKPOINT_STOPPING: {
        JobState.STOPPED, JobState.FAILED, JobState.RECOVERING,
    },
    JobState.FINISHING: {JobState.FINISHED, JobState.FAILED},
    JobState.FAILING: {JobState.FAILED},
}


class IllegalTransition(Exception):
    pass


def check_transition(cur: JobState, nxt: JobState):
    if nxt not in TRANSITIONS.get(cur, set()):
        raise IllegalTransition(f"{cur.value} -> {nxt.value}")
