"""Kafka connector: source with checkpointed offsets, exactly-once sink.

Capability parity with the reference's kafka connector
(/root/reference/crates/arroyo-connectors/src/kafka/, 2,468 LoC): the
source assigns partitions across subtasks, stores consumed offsets in
checkpointed state (restores seek exactly, reference source/mod.rs:49
KafkaState); the sink supports exactly_once via transactions opened per
(epoch, subtask) and committed in the 2PC commit phase (reference
sink/mod.rs:51-160) or at_least_once flush-on-checkpoint. SASL options and
a Confluent schema-registry hook are parsed and validated.

The runtime client is gated: this environment has no Kafka client library
(confluent_kafka/aiokafka) and no network egress, so operators raise a
clear error at start; config validation, planning and the API surface work
without it.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from .base import ConnectionSchema, Connector, register_connector


def _load_client():
    try:
        import confluent_kafka  # noqa: F401

        return confluent_kafka
    except ImportError:
        raise RuntimeError(
            "kafka connector requires the confluent_kafka client library, "
            "which is not available in this environment"
        )


class KafkaSource(SourceOperator):
    # DDL `METADATA FROM 'key'` surface (reference kafka metadata_defs,
    # kafka/mod.rs:325): key -> per-message extractor
    METADATA_KEYS = ("offset_id", "partition", "topic", "timestamp", "key")

    def __init__(self, bootstrap: str, topic: str, group_id: Optional[str],
                 offset_mode: str, client_configs: Dict[str, str],
                 schema, format: str, bad_data: str, framing: Optional[str],
                 proto_descriptor: Optional[dict] = None,
                 schema_registry: Optional[str] = None,
                 avro_schema: Optional[str] = None,
                 metadata_fields: Optional[Dict[str, str]] = None):
        super().__init__("kafka_source")
        self.metadata_fields = metadata_fields or {}
        for col, key in self.metadata_fields.items():
            if key not in self.METADATA_KEYS:
                raise ValueError(
                    f"kafka metadata key {key!r} (column {col}) is not one "
                    f"of {self.METADATA_KEYS}"
                )
        self.bootstrap = bootstrap
        self.topic = topic
        self.group_id = group_id
        self.offset_mode = offset_mode  # earliest | latest | group
        self.client_configs = client_configs
        self.out_schema = schema
        self.format = format
        self.bad_data = bad_data
        self.framing = framing
        self.proto_descriptor = proto_descriptor
        self.schema_registry = schema_registry
        self.avro_schema = avro_schema
        # partition -> next offset (checkpointed per partition)
        self.offsets: Dict[int, int] = {}
        # partitions assigned to THIS subtask (set by run); checkpoints
        # persist only these — writing a restored foreign partition's
        # offset would stamp a stale copy over its live owner's progress
        self._mine: Optional[set] = None

    def tables(self):
        from ..state.table_config import global_table

        return {"k": global_table("k")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            from . import splits as splits_mod

            table = await ctx.table("k")
            # offsets are keyed PER PARTITION (ISSUE 15 source
            # elasticity): any parallelism re-reads the partitions it is
            # assigned from the replicated union, so a rescale at the
            # checkpoint boundary neither gaps nor replays. Kafka splits
            # never subdivide (partitions are broker-side), so elasticity
            # here is reassignment only.
            for k, v in table.items():
                if isinstance(k, str) and k.startswith(splits_mod.SPLIT_PREFIX):
                    payload = dict(v)
                    self.offsets[int(payload["partition"])] = int(
                        payload["offset"]
                    )
            if not self.offsets:
                # legacy layout: one {partition: offset} dict per subtask
                # index — union every entry (rescale-safe upgrade: the
                # partitions this subtask is NOT assigned are ignored by
                # run()'s assignment filter)
                for k, v in table.items():
                    if isinstance(k, int) and isinstance(v, dict):
                        for p, o in v.items():
                            self.offsets[int(p)] = max(
                                int(o), self.offsets.get(int(p), 0)
                            )

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            from . import splits as splits_mod

            table = await ctx.table("k")
            for p, o in self.offsets.items():
                if self._mine is not None and int(p) not in self._mine:
                    continue
                table.put(
                    splits_mod.split_key(f"p{int(p)}"),
                    {"partition": int(p), "offset": int(o)},
                )

    async def run(self, ctx, collector) -> SourceFinishType:
        kafka = _load_client()
        registry = None
        if self.schema_registry:
            from ..formats.schema_registry import SchemaRegistryClient

            registry = SchemaRegistryClient(
                self.schema_registry, subject=f"{self.topic}-value"
            )
        deser = Deserializer(self.out_schema, format=self.format or "json",
                             bad_data=self.bad_data, framing=self.framing,
                             proto_descriptor=self.proto_descriptor,
                             avro_schema=self.avro_schema,
                             schema_registry=registry)
        consumer = kafka.Consumer(
            {
                "bootstrap.servers": self.bootstrap,
                "group.id": self.group_id or f"arroyo-{ctx.task_info.job_id}",
                "enable.auto.commit": False,
                "auto.offset.reset": (
                    "earliest" if self.offset_mode != "latest" else "latest"
                ),
                **self.client_configs,
            }
        )
        import asyncio

        meta = consumer.list_topics(self.topic, timeout=10)
        partitions = sorted(meta.topics[self.topic].partitions)
        mine = [
            p for i, p in enumerate(partitions)
            if i % ctx.task_info.parallelism == ctx.task_info.task_index
        ]
        self._mine = set(mine)
        tps = []
        for p in mine:
            tp = kafka.TopicPartition(self.topic, p)
            if p in self.offsets:
                tp.offset = self.offsets[p]
            tps.append(tp)
        consumer.assign(tps)
        try:
            while True:
                finish = await ctx.check_control(collector)
                if finish is not None:
                    return finish
                msg = consumer.poll(0)
                if msg is None:
                    await self.flush_buffer(ctx, collector)
                    await asyncio.sleep(0.01)
                    continue
                if msg.error():
                    ctx.error_reporter.report("kafka error", str(msg.error()))
                    continue
                ts_type, ts_ms = msg.timestamp()
                ts = ts_ms * 1_000_000 if ts_ms > 0 else None
                meta = None
                if self.metadata_fields:
                    vals = {
                        "offset_id": msg.offset(),
                        "partition": msg.partition(),
                        "topic": msg.topic(),
                        "timestamp": ts_ms if ts_ms > 0 else None,
                        "key": (
                            msg.key().decode("utf-8", "replace")
                            if msg.key() is not None else None
                        ),
                    }
                    meta = {
                        col: vals[k]
                        for col, k in self.metadata_fields.items()
                    }
                for row in deser.deserialize_slice(
                    msg.value(), timestamp=ts,
                    error_reporter=ctx.error_reporter,
                ):
                    if meta:
                        row.update(meta)
                    ctx.buffer_row(row)
                self.offsets[msg.partition()] = msg.offset() + 1
                if ctx.should_flush():
                    await self.flush_buffer(ctx, collector)
        finally:
            consumer.close()


class KafkaSink(Operator):
    def __init__(self, bootstrap: str, topic: str, semantics: str,
                 client_configs: Dict[str, str], format: str,
                 key_field: Optional[str],
                 proto_descriptor: Optional[dict] = None,
                 schema_registry: Optional[str] = None,
                 avro_schema: Optional[str] = None):
        super().__init__("kafka_sink")
        self.bootstrap = bootstrap
        self.topic = topic
        self.semantics = semantics  # exactly_once | at_least_once
        self.client_configs = client_configs
        registry = None
        if schema_registry:
            from ..formats.schema_registry import SchemaRegistryClient

            registry = SchemaRegistryClient(
                schema_registry, subject=f"{topic}-value"
            )
        self.serializer = Serializer(format=format or "json",
                                     proto_descriptor=proto_descriptor,
                                     avro_schema=avro_schema,
                                     schema_registry=registry)
        self.key_field = key_field
        self.producer = None
        self.epoch = 0
        # epoch -> producer whose open transaction holds that epoch's rows,
        # awaiting phase-2 commit (reference: transactional-id per
        # epoch+subtask, sink/mod.rs:127-160)
        self._pending_tx = {}

    def _make_producer(self, ctx, epoch: int):
        kafka = _load_client()
        conf = {"bootstrap.servers": self.bootstrap, **self.client_configs}
        if self.semantics == "exactly_once":
            conf["transactional.id"] = (
                f"arroyo-{ctx.task_info.job_id}-{ctx.task_info.node_id}"
                f"-{ctx.task_info.task_index}-{epoch}"
            )
        p = kafka.Producer(conf)
        if self.semantics == "exactly_once":
            p.init_transactions(30)
            p.begin_transaction()
        return p

    async def on_start(self, ctx):
        self.producer = self._make_producer(ctx, self.epoch)

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        keys = (
            batch.column(batch.schema.names.index(self.key_field)).to_pylist()
            if self.key_field and self.key_field in batch.schema.names
            else None
        )
        for i, rec in enumerate(self.serializer.serialize(batch)):
            key = str(keys[i]).encode() if keys is not None else None
            self.producer.produce(self.topic, value=rec, key=key)
        self.producer.poll(0)

    async def handle_checkpoint(self, barrier, ctx, collector):
        self.producer.flush(30)
        if self.semantics == "exactly_once":
            # seal this epoch's transaction: messages produced after the
            # barrier go into a NEW producer/transaction, so the phase-2
            # commit exposes exactly the pre-barrier rows
            self._pending_tx[barrier.epoch] = self.producer
            self.epoch = barrier.epoch + 1
            self.producer = self._make_producer(ctx, self.epoch)
            ctx.commit_data = json.dumps({"epoch": barrier.epoch}).encode()

    async def on_close(self, ctx, collector, is_eod: bool):
        """Abort the current open transaction on teardown: it holds only
        post-barrier rows no checkpoint covers, so exactly-once semantics
        require them re-emitted by a restore, never half-exposed. (A real
        broker would do this via transaction timeout / fencing; doing it
        eagerly keeps the broker's open-transaction table clean.)"""
        if self.semantics == "exactly_once" and self.producer is not None:
            try:
                self.producer.flush(5)
                self.producer.abort_transaction(5)
            except Exception:  # noqa: BLE001 - already fenced/closed is fine
                pass
        elif self.producer is not None:
            self.producer.flush(30)
        return None

    async def handle_commit(self, epoch, commit_data, ctx):
        if self.semantics != "exactly_once":
            return
        p = self._pending_tx.pop(epoch, None)
        if p is None:
            # same acknowledged limitation as the reference
            # (sink/mod.rs:361): a commit replayed after a crash has no
            # open producer to complete — restoring from the commit phase
            # is not implemented
            from ..utils.logging import get_logger

            get_logger("kafka").warning(
                "commit for epoch %s without a producer to complete; "
                "restoring from the commit phase is not implemented", epoch,
            )
            return
        p.commit_transaction(30)


SASL_OPTIONS = (
    "sasl.mechanism", "sasl.username", "sasl.password", "security.protocol",
)


@register_connector
class KafkaConnector(Connector):
    name = "kafka"
    metadata_keys = ("offset_id", "partition", "topic", "timestamp", "key")
    description = "Kafka source and sink (exactly-once via transactions)"
    source = True
    sink = True
    config_schema = {
        "bootstrap_servers": {"type": "string", "required": True},
        "topic": {"type": "string", "required": True},
        "group_id": {"type": "string"},
        "source.offset": {"type": "string", "enum": ["earliest", "latest", "group"]},
        "sink.commit_mode": {
            "type": "string", "enum": ["exactly_once", "at_least_once"]
        },
        "key_field": {"type": "string"},
        "schema_registry.endpoint": {"type": "string"},
        "avro.schema": {"type": "string"},
    }

    def validate_options(self, options, schema):
        if "bootstrap_servers" not in options:
            raise ValueError("kafka requires bootstrap_servers")
        if "topic" not in options:
            raise ValueError("kafka requires a topic")
        client_configs = {
            k[len("client_configs."):]: v
            for k, v in options.items()
            if k.startswith("client_configs.")
        }
        for k in SASL_OPTIONS:
            if k in options:
                client_configs[k] = options[k]
        return {
            "bootstrap": options["bootstrap_servers"],
            "topic": options["topic"],
            "group_id": options.get("group_id"),
            "offset_mode": options.get("source.offset", "group"),
            "semantics": options.get("sink.commit_mode", "at_least_once"),
            "client_configs": client_configs,
            "key_field": options.get("key_field"),
            "schema_registry": options.get("schema_registry.endpoint"),
            "avro_schema": options.get("avro.schema"),
        }

    def make_source(self, config, schema: ConnectionSchema):
        return KafkaSource(
            config["bootstrap"], config["topic"], config.get("group_id"),
            config.get("offset_mode", "group"),
            config.get("client_configs", {}), config.get("schema"),
            config.get("format"), config.get("bad_data", "fail"),
            config.get("framing"),
            proto_descriptor=config.get("proto_descriptor"),
            schema_registry=config.get("schema_registry"),
            avro_schema=config.get("avro_schema"),
            metadata_fields=config.get("metadata_fields"),
        )

    def make_sink(self, config, schema: ConnectionSchema):
        return KafkaSink(
            config["bootstrap"], config["topic"],
            config.get("semantics", "at_least_once"),
            config.get("client_configs", {}), config.get("format"),
            config.get("key_field"),
            proto_descriptor=config.get("proto_descriptor"),
            schema_registry=config.get("schema_registry"),
            avro_schema=config.get("avro_schema"),
        )

    def test(self, config):
        try:
            _load_client()
        except RuntimeError as e:
            return False, str(e)
        return True, "ok"


@register_connector
class ConfluentConnector(KafkaConnector):
    """Profile wrapper over kafka (reference confluent connector)."""

    name = "confluent"
    description = "Confluent Cloud (kafka profile)"
