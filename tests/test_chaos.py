"""Chaos subsystem: deterministic fault plans, injector seams, registry
coverage, and the fast exactly-once smoke drill (the full acceptance
drill — worker SIGKILL across 3 goldens — is in test_chaos_drill.py,
marked slow)."""

import asyncio
import json
import os
import re

import pytest

from arroyo_tpu import chaos
from arroyo_tpu.chaos import FAULT_POINTS, FaultPlan, UnknownFaultPoint

HERE = os.path.dirname(os.path.abspath(__file__))
PKG = os.path.join(os.path.dirname(HERE), "arroyo_tpu")


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    chaos.clear()
    yield
    chaos.clear()


# -- plan determinism --------------------------------------------------------


def test_plan_seeded_is_deterministic():
    points = ["network.drop_connection", "worker.kill", "storage.cas_conflict"]
    a = FaultPlan.seeded(77, points)
    b = FaultPlan.seeded(77, points)
    assert a.to_json() == b.to_json()
    assert FaultPlan.seeded(78, points).to_json() != a.to_json()


def test_plan_fires_at_hit_and_only_max_fires():
    plan = FaultPlan(1).add("storage.write_fail", at_hits=(3,))
    chaos.install(plan)
    fires = [bool(chaos.fire("storage.write_fail", key="k")) for _ in range(6)]
    assert fires == [False, False, True, False, False, False]
    assert plan.comparable_log() == plan.expected_log()
    assert not plan.unfired()


def test_plan_match_filters_hit_counting():
    plan = FaultPlan(1).add(
        "storage.cas_conflict", at_hits=(2,), match={"key": "manifest"}
    )
    chaos.install(plan)
    # non-matching hits don't advance the spec's counter
    assert not chaos.fire("storage.cas_conflict", key="gen-00001.json")
    assert not chaos.fire("storage.cas_conflict", key="a/manifest.json")
    assert not chaos.fire("storage.cas_conflict", key="gen-00002.json")
    assert chaos.fire("storage.cas_conflict", key="b/manifest.json")


def test_plan_json_roundtrip_and_unknown_point():
    plan = FaultPlan(5).add("worker.kill", at_hits=(4,), params={"x": 1})
    assert FaultPlan.from_json(plan.to_json()).to_json() == plan.to_json()
    with pytest.raises(UnknownFaultPoint):
        FaultPlan(0).add("worker.explode")
    with pytest.raises(UnknownFaultPoint):
        chaos.install(FaultPlan(0))
        chaos.fire("not.a.point")


def test_fire_is_noop_without_plan():
    assert chaos.installed() is None
    assert chaos.fire("worker.kill") is None


def test_install_from_config(tmp_path):
    from arroyo_tpu.config import update

    plan_file = tmp_path / "plan.json"
    plan_file.write_text(
        json.dumps({"faults": [{"point": "worker.kill", "at_hits": [2]}]})
    )
    with update(chaos={"plan": str(plan_file), "seed": 9}):
        plan = chaos.install_from_config()
    assert plan is not None and plan.seed == 9
    assert plan.specs[0].point == "worker.kill"
    chaos.clear()
    # inline JSON form
    with update(chaos={"plan": plan.to_json()}):
        plan2 = chaos.install_from_config()
    assert plan2.specs[0].at_hits == (2,)
    chaos.clear()
    # unset -> no plan
    assert chaos.install_from_config() is None


def test_install_from_config_dedupes_across_incarnations(tmp_path, monkeypatch):
    """Carried robustness bug (ISSUE 15 satellite): a RESPAWNED worker
    (spawn generation > 0, stamped by the process scheduler) must NOT
    re-arm a config-installed plan — re-arming gave every incarnation
    fresh hit counters and turned a heartbeat-hit worker.kill into a
    kill loop. Plans opt back in with "rearm": true."""
    from arroyo_tpu.config import update

    plan_json = json.dumps(
        {"faults": [{"point": "worker.kill", "at_hits": [2]}]}
    )
    # a respawned incarnation: the plan stays un-armed
    monkeypatch.setenv("ARROYO_CHAOS_SPAWN_GEN", "3")
    with update(chaos={"plan": plan_json}):
        assert chaos.install_from_config() is None
        assert chaos.installed() is None
    # explicit opt-in re-arms
    rearm_json = json.dumps(
        {"rearm": True,
         "faults": [{"point": "worker.kill", "at_hits": [2]}]}
    )
    with update(chaos={"plan": rearm_json}):
        assert chaos.install_from_config() is not None
    chaos.clear()
    # first incarnation (gen 0) arms as always
    monkeypatch.setenv("ARROYO_CHAOS_SPAWN_GEN", "0")
    with update(chaos={"plan": plan_json}):
        assert chaos.install_from_config() is not None
    chaos.clear()


def test_process_scheduler_stamps_spawn_generations(monkeypatch):
    """The process scheduler marks pool REPLACEMENTS (and per-job respawn
    rounds) with an increasing spawn generation, which is what suppresses
    chaos-plan re-arming across incarnations."""
    from arroyo_tpu.config import update
    from arroyo_tpu.controller import scheduler as sched_mod

    spawns = []

    class FakeProc:
        def __init__(self, gen):
            self.gen = gen
            self.dead = False

        def poll(self):
            return 1 if self.dead else None

    def fake_spawn(addr, wid, extra_env=None, spawn_generation=0):
        p = FakeProc(spawn_generation)
        spawns.append(p)
        return p

    monkeypatch.setattr(sched_mod, "spawn_worker", fake_spawn)

    async def go():
        s = sched_mod.ProcessScheduler()
        with update(cluster={"multiplexing": "on",
                             "worker_pool_size": 2}):
            await s.start_workers("127.0.0.1:1", 2, "j1")
            assert [p.gen for p in spawns] == [0, 0]
            # a pool worker dies; the replacement is generation 1
            spawns[0].dead = True
            await s.start_workers("127.0.0.1:1", 2, "j2")
            assert [p.gen for p in spawns] == [0, 0, 1]
        spawns.clear()
        with update(cluster={"multiplexing": "off"}):
            s2 = sched_mod.ProcessScheduler()
            await s2.start_workers("127.0.0.1:1", 1, "j3")
            # recovery reschedule of the same job: respawn round 1
            await s2.start_workers("127.0.0.1:1", 1, "j3")
            assert [p.gen for p in spawns] == [0, 1]

    asyncio.run(go())


# -- registry coverage: every seam is listed, every listing has a seam ------


def test_fault_point_registry_matches_call_sites():
    """`tools/chaos_drill.py --list` (FAULT_POINTS) must enumerate exactly
    the fault points the code injects: a new chaos.fire() seam without a
    registry entry — or a registry entry whose seam was deleted — fails
    here, so coverage can't silently rot."""
    called = set()
    for root, _dirs, files in os.walk(PKG):
        if os.path.basename(root) == "chaos":
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            src = open(os.path.join(root, fn)).read()
            called.update(re.findall(r'chaos\.fire\(\s*"([^"]+)"', src))
    assert called == set(FAULT_POINTS), (
        f"registry drift: seams without registry entry: "
        f"{sorted(called - set(FAULT_POINTS))}; registry entries without "
        f"a seam: {sorted(set(FAULT_POINTS) - called)}"
    )


def test_drill_tool_lists_fault_points():
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(PKG), "tools",
                                      "chaos_drill.py"), "--list"],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    for name in FAULT_POINTS:
        assert name in out.stdout


# -- injector seams (unit level) --------------------------------------------


def test_storage_injectors(tmp_path):
    from arroyo_tpu.state.storage import CasConflict, StorageProvider

    sp = StorageProvider(str(tmp_path / "s"))
    chaos.install(
        FaultPlan(0)
        .add("storage.write_fail", at_hits=(1,))
        .add("storage.cas_conflict", at_hits=(1,))
    )
    with pytest.raises(IOError, match="chaos\\[storage.write_fail\\]"):
        sp.put("a", b"x")
    sp.put("a", b"x")  # transient: second attempt succeeds
    assert sp.get("a") == b"x"
    with pytest.raises(CasConflict):
        sp.put_if_not_exists("b", b"y")
    # the injected conflict must NOT have created the key
    assert not sp.exists("b")
    sp.put_if_not_exists("b", b"y")
    assert sp.get("b") == b"y"


def test_protocol_zombie_fencing(tmp_path):
    from arroyo_tpu.state import protocol
    from arroyo_tpu.state.protocol import Fenced, ProtocolPaths
    from arroyo_tpu.state.storage import StorageProvider

    storage = StorageProvider(str(tmp_path / "s"))
    paths = ProtocolPaths("job")
    gen = protocol.initialize_generation(storage, paths)
    chaos.install(FaultPlan(0).add("protocol.fenced_zombie", at_hits=(1,)))
    with pytest.raises(Fenced, match="zombie"):
        protocol.publish_checkpoint(storage, paths, gen, 1, {"tasks": {}})
    # the fenced publish must not have produced a manifest or moved latest
    assert protocol.load_manifest(storage, paths, 1) is None
    assert protocol.resolve_latest(storage, paths) is None
    # next attempt (fault exhausted) publishes fine
    protocol.publish_checkpoint(storage, paths, gen, 1, {"tasks": {}})
    assert protocol.resolve_latest(storage, paths)["epoch"] == 1


def test_network_partial_frame_never_delivers():
    """A torn frame injected at the sender must surface as a pump failure
    and the receiver must deliver nothing."""
    from arroyo_tpu.engine.network import DataPlaneServer, RemoteEdgeSender
    from arroyo_tpu.operators.queues import BatchQueue

    import pyarrow as pa

    async def go():
        server = DataPlaneServer()
        port = await server.start()
        inbox = BatchQueue(8, 1 << 20)
        quad = (1, 0, 2, 0)
        server.register(quad, inbox)
        outbox = BatchQueue(8, 1 << 20)
        errors = []
        sender = RemoteEdgeSender(
            f"127.0.0.1:{port}", quad, outbox,
            on_error=lambda q, e: errors.append((q, e)),
        )
        chaos.install(
            FaultPlan(0).add("network.partial_frame", at_hits=(2,))
        )
        await sender.start()
        batch = pa.record_batch([pa.array([1, 2, 3])], names=["n"])
        await outbox.send(batch)   # frame 1: delivered
        await outbox.send(batch)   # frame 2: torn, connection dropped
        await asyncio.gather(sender.task, return_exceptions=True)
        await asyncio.sleep(0.1)
        got = [await inbox.recv() for _ in range(inbox.qsize())]
        await server.stop()
        return got, errors

    got, errors = asyncio.run(go())
    assert len(got) == 1  # the torn frame was never delivered
    assert len(errors) == 1 and isinstance(errors[0][1], ConnectionResetError)


def test_multihost_init_failure_names_coordinator(monkeypatch):
    """ADVICE r5: a lost pick_coordinator bind-then-close race must raise
    an error naming the coordinator address and the tpu.mesh_coordinator
    pin, not jax's bare connect failure."""
    from arroyo_tpu import parallel
    from arroyo_tpu.config import update
    from arroyo_tpu.parallel import multihost

    import jax

    monkeypatch.setattr(multihost, "_initialized", None)

    def boom(**kw):
        raise RuntimeError("DEADLINE_EXCEEDED: connect failed")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with update(tpu={"mesh_coordinator": "10.0.0.7:4612",
                     "mesh_processes": 2, "mesh_process_id": 1}):
        with pytest.raises(RuntimeError) as err:
            multihost.ensure_initialized()
    msg = str(err.value)
    assert "10.0.0.7:4612" in msg
    assert "tpu.mesh_coordinator" in msg
    assert "ARROYO__TPU__MESH_COORDINATOR" in msg


# -- the fast smoke drill (default suite) -----------------------------------


def test_stall_extreme_hold_never_double_emits(tmp_path):
    """An extreme `runner.stall` hold — an operator wedged for seconds
    mid-stream with barriers still flowing, and NO restart — must delay
    window emission, never repeat it: every (key, window) pair emits
    exactly once and the output is byte-identical to the unstalled run
    (ISSUE 16: the shared-plan gate reasons about stalled tenants, so
    the stall seam itself must be emission-safe without recovery)."""
    from arroyo_tpu.chaos import drill

    def sql(out):
        return f"""
        CREATE TABLE impulse WITH (
          connector = 'impulse', event_rate = '5000',
          message_count = '1500', start_time = '0'
        );
        CREATE TABLE out (k BIGINT UNSIGNED, start TIMESTAMP, cnt BIGINT)
        WITH (
          connector = 'single_file', path = '{out}', format = 'json',
          type = 'sink'
        );
        INSERT INTO out
        SELECT k, window.start as start, cnt FROM (
          SELECT counter % 4 as k,
                 tumble(interval '100 millisecond') as window,
                 count(*) as cnt
          FROM impulse GROUP BY 1, 2
        );
        """

    clean = str(tmp_path / "clean.json")
    drill._run_embedded(sql(clean), "stall-clean", None, 1, 1,
                        max_restarts=0, heartbeat_interval=0.1,
                        heartbeat_timeout=30.0, checkpoint_interval=60.0,
                        timeout=60.0)

    stalled = str(tmp_path / "stalled.json")
    plan = FaultPlan(7).add(
        "runner.stall", at_hits=(2, 3, 4), match={"job": "stall-hold"},
        params={"delay": 1.5}, max_fires=3,
    )
    chaos.install(plan)
    try:
        restarts = drill._run_embedded(
            sql(stalled), "stall-hold", str(tmp_path / "ck"), 1, 1,
            max_restarts=0, heartbeat_interval=0.1,
            heartbeat_timeout=30.0, checkpoint_interval=0.2,
            timeout=60.0,
        )
    finally:
        chaos.clear()
    assert restarts == 0  # the hold is a delay, never a recovery path
    assert not plan.unfired()

    def rows(path):
        return sorted(open(path).read().splitlines())

    got = rows(stalled)
    assert got and got == rows(clean)
    keys = [(json.loads(r)["k"], json.loads(r)["start"]) for r in got]
    assert len(keys) == len(set(keys)), "a window emitted twice"


def test_fast_smoke_drill(tmp_path):
    """1 golden, 2 faults (data-plane drop + manifest CAS loss) through
    the real embedded cluster: output identical to the fault-free run,
    the fired-fault log equals the seed's deterministic schedule, and
    every fired fault lands in the flight recorder as a span event
    (ISSUE 4: drill timelines show fault -> detection -> recovery)."""
    from arroyo_tpu import obs
    from arroyo_tpu.chaos import drill

    obs.reset()
    res = drill.run_drill(
        drill.DEFAULT_DRILL_QUERIES[0], seed=1234, workdir=str(tmp_path),
        plan_factory=drill.fast_plan, throttle=400.0,
    )
    assert res.passed, res.error
    assert res.restarts >= 1  # at least one fault forced a recovery
    assert res.comparable_log == res.expected_log
    # reproducibility: the schedule is a pure function of the seed
    assert res.expected_log == drill.fast_plan(1234).expected_log()
    assert res.expected_log != drill.fast_plan(4321).expected_log()
    # every fired fault is a chaos.fire:<point> instant in the recorder
    fired_points = {e["point"] for e in res.fired}
    recorded = {
        s["name"].removeprefix("chaos.fire:")
        for s in obs.recorder().snapshot()
        if s["name"].startswith("chaos.fire:")
    }
    assert fired_points <= recorded, (fired_points, recorded)
    # the CAS-conflict fire happens INSIDE the manifest publish: it must
    # attach to the live checkpoint trace, not float free
    cas_events = [
        s for s in obs.recorder().snapshot()
        if s["name"] == "chaos.fire:storage.cas_conflict"
    ]
    assert any("/ck-" in s["trace_id"] for s in cas_events), cas_events
    obs.reset()
