"""Project-wide interprocedural call graph with async-context propagation.

This is what grows arroyolint beyond per-file AST rules: one shared
analysis (built once per `Project`, cached — all four RACE rules and the
``--call-graph`` debug dump reuse it) that answers three questions the
RACE00x family needs:

  roots      which *task-spawn roots* can a function run under?  Every
             ``asyncio.ensure_future(...)`` / ``create_task(...)`` call
             site defines a root named after the spawned coroutine (the
             runner loop, the worker heartbeat, the response pump, the
             checkpoint flush chain, the TimerWheel loop, the job drive
             task...). Root membership propagates through call edges —
             but NOT through spawn edges: the spawned task is a new
             concurrent context, which is the whole point. Functions
             reachable from no spawn site run under the implicit
             ``main`` root (the submitting / RPC-serving context).

  locksets   which locks are held at a statement?  Intraprocedurally a
             bare ``with self._lock:`` / ``async with self._lock:``
             contributes its attribute name; interprocedurally a
             function's *entry lockset* is the intersection over all
             call sites of (caller entry lockset | locks held at the
             site) — the classic Eraser-style conservative summary.
             Spawned functions enter lock-free by definition.

  accesses   where are the ``shared_state``/``guarded_by`` declared
             fields read and written?  Matching is by attribute name
             (Python has no types to resolve receivers), which is why
             the DSL — and the deliberately distinctive field names it
             declares — bounds the false-positive surface: undeclared
             fields are invisible to the rules.

Call-edge resolution is heuristic by necessity: ``self.m()`` binds to
the enclosing class (then same-file classes); bare names bind within the
module; ``obj.m()`` binds to any method named ``m`` project-wide unless
the name is too ambiguous (> _AMBIG_CAP candidates), in which case the
edge is dropped rather than poisoning reachability. ``--call-graph``
exists so a surprising finding can be traced to the exact edges and
roots that produced it.
"""

from __future__ import annotations

import ast
import dataclasses
import weakref
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from ..core import FileContext, Project, dotted_name

MAIN_ROOT = "main"

# beyond this many same-named method candidates an obj.m() edge is noise
_AMBIG_CAP = 4

_SPAWN_CALLS = {
    "asyncio.ensure_future", "ensure_future",
    "asyncio.create_task", "create_task",
}

# calls that mutate a container field in place
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "clear",
    "update", "setdefault", "add", "discard", "pop", "popitem",
    "put_nowait",
}

_CONSTRUCTORS = {"__init__", "__post_init__"}


@dataclasses.dataclass(frozen=True)
class FieldDecl:
    """One field declared via shared_state()/guarded_by() on some class."""

    field: str
    cls: str
    path: str
    guard: Optional[str]      # lock attribute name, or None
    multi_writer: bool


@dataclasses.dataclass
class Access:
    field: str
    kind: str                 # "read" | "write"
    path: str
    line: int
    col: int
    lockset: FrozenSet[str]   # locks held at the site (intraprocedural)
    receiver: str = "?"       # dotted receiver expr ("self", "job", "?")


@dataclasses.dataclass
class AwaitSite:
    line: int
    col: int
    lockset: FrozenSet[str]


@dataclasses.dataclass
class FuncInfo:
    qualname: str             # "path::Class.name" | "path::name"
    path: str
    cls: Optional[str]
    name: str
    node: ast.AST
    is_async: bool
    calls: List[Tuple[str, str, FrozenSet[str]]]  # (kind, name, lockset)
    spawns: List[Tuple[str, str, int]]            # (kind, name, line)
    accesses: List[Access]
    awaits: List[AwaitSite]


def _literal_strs(nodes: Iterable[ast.AST]) -> List[str]:
    out = []
    for n in nodes:
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append(n.value)
    return out


def extract_decls(project: Project) -> Dict[str, FieldDecl]:
    """field name -> declaration, from decorator ASTs across the project.

    A field name declared on two classes keeps the first declaration but
    merges pessimistically (multi_writer only if both said so; a guard
    from either) — name-keyed analysis cannot tell the receivers apart.
    """
    decls: Dict[str, FieldDecl] = {}
    for ctx in project:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                name = dotted_name(dec.func)
                base = name.split(".")[-1] if name else None
                if base == "shared_state":
                    multi = set()
                    for kw in dec.keywords:
                        if kw.arg == "multi_writer" and isinstance(
                            kw.value, (ast.Tuple, ast.List)
                        ):
                            multi.update(_literal_strs(kw.value.elts))
                    for f in _literal_strs(dec.args):
                        _merge_decl(decls, FieldDecl(
                            f, node.name, ctx.path, None, f in multi
                        ))
                elif base == "guarded_by":
                    strs = _literal_strs(dec.args)
                    if len(strs) >= 2:
                        lock, fields = strs[0], strs[1:]
                        for f in fields:
                            _merge_decl(decls, FieldDecl(
                                f, node.name, ctx.path, lock, True
                            ))
    return decls


def _merge_decl(decls: Dict[str, FieldDecl], d: FieldDecl) -> None:
    prev = decls.get(d.field)
    if prev is None:
        decls[d.field] = d
        return
    decls[d.field] = FieldDecl(
        d.field, prev.cls, prev.path,
        prev.guard or d.guard,
        prev.multi_writer and d.multi_writer,
    )


# -- per-function extraction -------------------------------------------------


class _FuncScan:
    """One pass over a function body (nested defs excluded) tracking the
    with-lock stack, collecting call edges, spawn sites, awaits, and
    declared-field accesses."""

    def __init__(self, ctx: FileContext, fields: Set[str]):
        self.ctx = ctx
        self.fields = fields
        self.calls: List[Tuple[str, str, FrozenSet[str]]] = []
        self.spawns: List[Tuple[str, str, int]] = []
        self.accesses: List[Access] = []
        self.awaits: List[AwaitSite] = []

    def scan(self, fn: ast.AST) -> None:
        for stmt in fn.body:
            self._stmt(stmt, frozenset())

    # locks: bare Name/Attribute with-contexts ("with self._lock:") count;
    # calls ("with open(p) as f:") don't — locks are held, not created here
    def _with_locks(self, node, locks: FrozenSet[str]) -> FrozenSet[str]:
        extra = set()
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, (ast.Name, ast.Attribute)):
                name = dotted_name(expr)
                if name:
                    extra.add(name.split(".")[-1])
        return locks | extra

    def _stmt(self, node: ast.AST, locks: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # a nested scope is its own FuncInfo
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = self._with_locks(node, locks)
            for item in node.items:
                self._expr(item.context_expr, locks)
            if isinstance(node, ast.AsyncWith):
                self.awaits.append(AwaitSite(node.lineno, node.col_offset,
                                             locks))
            for s in node.body:
                self._stmt(s, inner)
            return
        if isinstance(node, ast.AsyncFor):
            self._expr(node.iter, locks)
            self.awaits.append(AwaitSite(node.lineno, node.col_offset, locks))
            for s in node.body + node.orelse:
                self._stmt(s, locks)
            return
        # generic statement: expressions at this lockset, then children
        for field_name, value in ast.iter_fields(node):
            if isinstance(value, ast.AST) and not isinstance(value, ast.stmt):
                self._expr(value, locks)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        self._stmt(v, locks)
                    elif isinstance(v, ast.excepthandler):
                        if v.type is not None:
                            self._expr(v.type, locks)
                        for s in v.body:
                            self._stmt(s, locks)
                    elif isinstance(v, ast.AST):
                        self._expr(v, locks)

    def _expr(self, node: ast.AST, locks: FrozenSet[str]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue  # deferred execution context
            if isinstance(sub, ast.Await):
                self.awaits.append(
                    AwaitSite(sub.lineno, sub.col_offset, locks)
                )
            elif isinstance(sub, ast.Call):
                self._call(sub, locks)
            elif isinstance(sub, ast.Attribute) and sub.attr in self.fields:
                self._access(sub, locks)

    def _call(self, node: ast.Call, locks: FrozenSet[str]) -> None:
        name = dotted_name(node.func)
        if name is None:
            return
        if name in _SPAWN_CALLS or name.endswith(".create_task"):
            target = node.args[0] if node.args else None
            kind_name = None
            if isinstance(target, ast.Call):
                kind_name = self._callee(target.func)
            elif isinstance(target, ast.Name):
                kind_name = ("plain", target.id)
            if kind_name:
                self.spawns.append(
                    (kind_name[0], kind_name[1], node.lineno)
                )
            return
        kn = self._callee(node.func)
        if kn:
            self.calls.append((kn[0], kn[1], locks))

    @staticmethod
    def _callee(func: ast.AST) -> Optional[Tuple[str, str]]:
        name = dotted_name(func)
        if name is None:
            if isinstance(func, ast.Attribute):  # call on a call result etc
                return ("attr", func.attr)
            return None
        parts = name.split(".")
        if len(parts) == 1:
            return ("plain", parts[0])
        if parts[0] == "self" and len(parts) == 2:
            return ("self", parts[1])
        return ("attr", parts[-1])

    def _access(self, node: ast.Attribute, locks: FrozenSet[str]) -> None:
        parent = self.ctx.parent(node)
        recv = dotted_name(node.value) or "?"
        kind = "read"
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            kind = "write"
            if isinstance(parent, ast.AugAssign) and parent.target is node:
                # x.f += 1 is a read AND a write
                self.accesses.append(Access(
                    node.attr, "read", self.ctx.path, node.lineno,
                    node.col_offset, locks, recv,
                ))
        elif isinstance(parent, ast.Attribute) and parent.attr in _MUTATORS:
            gp = self.ctx.parent(parent)
            if isinstance(gp, ast.Call) and gp.func is parent:
                kind = "write"  # x.f.append(...) mutates f in place
        elif isinstance(parent, ast.Subscript) and parent.value is node:
            if isinstance(parent.ctx, (ast.Store, ast.Del)):
                kind = "write"  # x.f[k] = v / del x.f[k]
        self.accesses.append(Access(
            node.attr, kind, self.ctx.path, node.lineno, node.col_offset,
            locks, recv,
        ))


# -- the graph ---------------------------------------------------------------


class CallGraph:
    def __init__(self, project: Project):
        self.project = project
        self.decls: Dict[str, FieldDecl] = extract_decls(project)
        self.funcs: Dict[str, FuncInfo] = {}
        self._by_method: Dict[str, List[str]] = {}
        self._by_plain: Dict[Tuple[str, str], str] = {}
        self._by_class: Dict[Tuple[str, str, str], str] = {}
        self._extract()
        self.edges: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {
            q: self._resolve_edges(f) for q, f in self.funcs.items()
        }
        self.roots_of: Dict[str, Set[str]] = {}
        self.root_spawn_sites: Dict[str, List[Tuple[str, int]]] = {}
        self._propagate_roots()
        self.entry_locks: Dict[str, FrozenSet[str]] = {}
        self._propagate_locksets()

    # -- extraction ----------------------------------------------------------

    def _extract(self) -> None:
        fields = set(self.decls)
        for ctx in self.project:
            self._extract_file(ctx, fields)

    def _extract_file(self, ctx: FileContext, fields: Set[str]) -> None:
        class_stack: List[str] = []

        def visit(node):
            if isinstance(node, ast.ClassDef):
                class_stack.append(node.name)
                for child in node.body:
                    visit(child)
                class_stack.pop()
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = class_stack[-1] if class_stack else None
                qual = (f"{ctx.path}::{cls}.{node.name}" if cls
                        else f"{ctx.path}::{node.name}")
                scan = _FuncScan(ctx, fields)
                scan.scan(node)
                info = FuncInfo(
                    qualname=qual, path=ctx.path, cls=cls, name=node.name,
                    node=node, is_async=isinstance(node, ast.AsyncFunctionDef),
                    calls=scan.calls, spawns=scan.spawns,
                    accesses=scan.accesses, awaits=scan.awaits,
                )
                # first definition wins on qualname collisions (overloads
                # via if TYPE_CHECKING etc. are rare and equivalent here)
                self.funcs.setdefault(qual, info)
                if cls:
                    self._by_method.setdefault(node.name, []).append(qual)
                    self._by_class[(ctx.path, cls, node.name)] = qual
                else:
                    self._by_plain.setdefault((ctx.path, node.name), qual)
                    self._by_method.setdefault(node.name, []).append(qual)
                for child in node.body:
                    visit(child)  # nested defs become their own FuncInfo
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(ctx.tree)

    # -- resolution ----------------------------------------------------------

    def _resolve(self, kind: str, name: str, frm: FuncInfo) -> List[str]:
        if kind == "self" and frm.cls:
            q = self._by_class.get((frm.path, frm.cls, name))
            if q:
                return [q]
            same_file = [
                x for x in self._by_method.get(name, ())
                if x.startswith(frm.path + "::")
            ]
            if same_file:
                return same_file
            kind = "attr"
        if kind == "plain":
            q = self._by_plain.get((frm.path, name))
            return [q] if q else []
        cands = self._by_method.get(name, ())
        return list(cands) if 0 < len(cands) <= _AMBIG_CAP else []

    def _resolve_edges(self, f: FuncInfo):
        out = []
        for kind, name, locks in f.calls:
            for target in self._resolve(kind, name, f):
                out.append((target, locks))
        return out

    # -- roots ---------------------------------------------------------------

    def _propagate_roots(self) -> None:
        for f in self.funcs.values():
            for kind, name, line in f.spawns:
                targets = self._resolve(kind, name, f) or [
                    f"{f.path}:{line}:<spawn>"
                ]
                for t in targets:
                    root = t
                    self.roots_of.setdefault(t, set()).add(root)
                    self.root_spawn_sites.setdefault(root, []).append(
                        (f.path, line)
                    )
        work = [q for q in self.roots_of if q in self.funcs]
        while work:
            q = work.pop()
            mine = self.roots_of[q]
            for callee, _locks in self.edges.get(q, ()):
                have = self.roots_of.setdefault(callee, set())
                before = len(have)
                have |= mine
                if len(have) != before:
                    work.append(callee)

    def roots(self, qualname: str) -> Set[str]:
        """Task roots `qualname` can run under; `main` when unspawned."""
        return self.roots_of.get(qualname) or {MAIN_ROOT}

    # -- locksets ------------------------------------------------------------

    def _propagate_locksets(self) -> None:
        incoming: Dict[str, int] = {q: 0 for q in self.funcs}
        for q, edges in self.edges.items():
            for callee, _ in edges:
                if callee in incoming:
                    incoming[callee] += 1
        empty: FrozenSet[str] = frozenset()
        work: List[str] = []
        for q in self.funcs:
            # entry points: never called, or spawned DIRECTLY as a task
            # (a task starts on a fresh stack — spawn-site locks are NOT
            # held). A direct spawn target carries its own qualname in
            # its root set; functions that merely inherit a root through
            # call edges keep their callers' locksets.
            if incoming[q] == 0 or q in self.roots_of.get(q, ()):
                self.entry_locks[q] = empty
                work.append(q)
        while work:
            q = work.pop()
            base = self.entry_locks[q]
            for callee, site_locks in self.edges.get(q, ()):
                if callee not in self.funcs:
                    continue
                new = base | site_locks
                cur = self.entry_locks.get(callee)
                if cur is None:
                    self.entry_locks[callee] = new
                    work.append(callee)
                elif not (cur <= new):
                    self.entry_locks[callee] = cur & new
                    work.append(callee)

    def entry_lockset(self, qualname: str) -> FrozenSet[str]:
        return self.entry_locks.get(qualname, frozenset())

    # -- queries -------------------------------------------------------------

    def field_writes(self, field: str) -> List[Tuple[FuncInfo, Access]]:
        out = []
        for f in self.funcs.values():
            if f.name in _CONSTRUCTORS:
                continue  # construction precedes sharing
            for a in f.accesses:
                if a.field == field and a.kind == "write":
                    out.append((f, a))
        return out

    def field_accesses(self, field: str) -> List[Tuple[FuncInfo, Access]]:
        out = []
        for f in self.funcs.values():
            for a in f.accesses:
                if a.field == field:
                    out.append((f, a))
        return out

    # -- debug dump (tools/lint.py --call-graph) -----------------------------

    def to_debug_json(self) -> dict:
        roots: Dict[str, dict] = {}
        for q, f in sorted(self.funcs.items()):
            for root in sorted(self.roots(q)):
                entry = roots.setdefault(root, {
                    "spawned_at": [
                        f"{p}:{ln}" for p, ln in
                        sorted(self.root_spawn_sites.get(root, ()))
                    ],
                    "functions": [],
                    "shared_accesses": [],
                })
                entry["functions"].append(q)
                for a in f.accesses:
                    entry["shared_accesses"].append({
                        "field": a.field,
                        "kind": a.kind,
                        "site": f"{a.path}:{a.line}",
                        "function": q,
                        "lockset": sorted(
                            self.entry_lockset(q) | a.lockset
                        ),
                    })
        return {
            "declared_fields": {
                name: {
                    "class": d.cls, "path": d.path, "guard": d.guard,
                    "multi_writer": d.multi_writer,
                }
                for name, d in sorted(self.decls.items())
            },
            "n_functions": len(self.funcs),
            "roots": roots,
        }


# one graph per Project: the four RACE rules and the --call-graph dump all
# reuse it, which is the cache that keeps full-tree --strict wall time at
# ~1 extra pass instead of 4+ (ISSUE 18 satellite)
_CACHE: "weakref.WeakKeyDictionary[Project, CallGraph]" = (
    weakref.WeakKeyDictionary()
)


def build(project: Project) -> CallGraph:
    graph = _CACHE.get(project)
    if graph is None:
        graph = CallGraph(project)
        _CACHE[project] = graph
    return graph
