"""SQL abstract syntax tree."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

# -- expressions ------------------------------------------------------------


@dataclasses.dataclass
class Expr:
    pass


@dataclasses.dataclass
class Column(Expr):
    name: str
    table: Optional[str] = None  # qualifier

    def __str__(self):
        return f"{self.table}.{self.name}" if self.table else self.name


@dataclasses.dataclass
class FieldAccess(Expr):
    """struct.field access (e.g. window.start)."""

    base: Expr
    field: str

    def __str__(self):
        return f"{self.base}.{self.field}"


@dataclasses.dataclass
class Literal(Expr):
    value: Any  # python value; None for NULL

    def __str__(self):
        return repr(self.value)


@dataclasses.dataclass
class Interval(Expr):
    nanos: int

    def __str__(self):
        return f"INTERVAL {self.nanos}ns"


@dataclasses.dataclass
class BinaryOp(Expr):
    op: str  # + - * / % = != < <= > >= AND OR || ->> ->
    left: Expr
    right: Expr

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclasses.dataclass
class UnaryOp(Expr):
    op: str  # - NOT
    operand: Expr

    def __str__(self):
        return f"{self.op}({self.operand})"


@dataclasses.dataclass
class FuncCall(Expr):
    name: str  # lowercased
    args: List[Expr]
    distinct: bool = False
    star: bool = False  # count(*)
    # window-function OVER clause (None for plain calls)
    over: Optional["OverClause"] = None

    def __str__(self):
        inner = "*" if self.star else ", ".join(map(str, self.args))
        d = "DISTINCT " if self.distinct else ""
        return f"{self.name}({d}{inner})"


@dataclasses.dataclass
class OverClause:
    partition_by: List[Expr]
    order_by: List[Tuple[Expr, bool]]  # (expr, descending)


@dataclasses.dataclass
class Cast(Expr):
    operand: Expr
    type_name: str

    def __str__(self):
        return f"CAST({self.operand} AS {self.type_name})"


@dataclasses.dataclass
class Case(Expr):
    operand: Optional[Expr]
    branches: List[Tuple[Expr, Expr]]  # (when, then)
    else_: Optional[Expr]


@dataclasses.dataclass
class InList(Expr):
    operand: Expr
    items: List[Expr]
    negated: bool = False


@dataclasses.dataclass
class Between(Expr):
    operand: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclasses.dataclass
class IsNull(Expr):
    operand: Expr
    negated: bool = False


@dataclasses.dataclass
class Star(Expr):
    table: Optional[str] = None  # t.* qualifier


# -- relations --------------------------------------------------------------


@dataclasses.dataclass
class Relation:
    pass


@dataclasses.dataclass
class TableRef(Relation):
    name: str
    alias: Optional[str] = None


@dataclasses.dataclass
class SubqueryRef(Relation):
    query: "Select"
    alias: Optional[str] = None


@dataclasses.dataclass
class Join(Relation):
    left: Relation
    right: Relation
    join_type: str  # inner | left | right | full
    condition: Optional[Expr]


@dataclasses.dataclass
class Unnest(Relation):
    expr: Expr
    alias: Optional[str] = None


# -- statements -------------------------------------------------------------


@dataclasses.dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclasses.dataclass
class Select:
    items: List[SelectItem]
    from_: Optional[Relation]
    where: Optional[Expr] = None
    group_by: List[Expr] = dataclasses.field(default_factory=list)
    having: Optional[Expr] = None
    distinct: bool = False
    # UNION ALL chain: additional selects unioned onto this one
    unions: List["Select"] = dataclasses.field(default_factory=list)
    order_by: List[Tuple[Expr, bool]] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None


@dataclasses.dataclass
class ColumnDef:
    name: str
    type_name: str
    nullable: bool = True
    # generated/virtual column expression (col AS (expr))
    generated: Optional[Expr] = None
    metadata_key: Optional[str] = None


@dataclasses.dataclass
class CreateTable:
    name: str
    columns: List[ColumnDef]
    options: Dict[str, str]  # WITH (...) connector options


@dataclasses.dataclass
class CreateView:
    name: str
    query: Select


@dataclasses.dataclass
class Insert:
    table: str
    query: Select


Statement = Any  # CreateTable | CreateView | Insert | Select
