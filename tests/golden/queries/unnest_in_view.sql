CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE unnest_output (
  counter BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
CREATE VIEW unnest_view AS
SELECT unnest(counters) as counter FROM (
  SELECT array_agg(counter) as counters, tumble(interval '30 second') as w
  FROM impulse_source GROUP BY w
);
INSERT INTO unnest_output SELECT counter FROM unnest_view;
