"""single_file connector — deterministic line-by-line file IO.

Capability parity with the reference's single_file connector
(/root/reference/crates/arroyo-connectors/src/single_file/, 462 LoC): it
exists for the smoke-test harness — the source reads a JSON-lines file in
order with the read position checkpointed (restores resume exactly), and
the sink appends JSON lines with the byte offset checkpointed (restores
truncate, so a restored run never duplicates output).
"""

from __future__ import annotations

import os
from typing import Optional

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from .base import ConnectionSchema, Connector, register_connector


class SingleFileSource(SourceOperator):
    def __init__(self, path: str, schema, format: str, bad_data: str,
                 throttle_per_sec: Optional[float] = None):
        super().__init__("single_file_source")
        self.path = path
        self.out_schema = schema
        self.deserializer = Deserializer(
            schema, format=format or "json", bad_data=bad_data,
            framing=None,
        )
        # test hook: cap read rate so harnesses can checkpoint mid-stream
        self.throttle_per_sec = throttle_per_sec
        self.lines_read = 0

    def tables(self):
        from ..state.table_config import global_table

        return {"f": global_table("f")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("f")
            stored = table.get(ctx.task_info.task_index)
            if stored is not None:
                self.lines_read = stored

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("f")
            table.put(ctx.task_info.task_index, self.lines_read)

    async def run(self, ctx, collector) -> SourceFinishType:
        if ctx.task_info.task_index != 0:
            # the file is read by exactly one subtask
            return SourceFinishType.FINAL
        with open(self.path, "rb") as f:
            for i, line in enumerate(f):
                if i < self.lines_read:
                    continue
                finish = await ctx.check_control(collector)
                if finish is not None:
                    return finish
                line = line.strip()
                if not line:
                    self.lines_read = i + 1
                    continue
                for row in self.deserializer.deserialize_slice(
                    line, error_reporter=ctx.error_reporter
                ):
                    ctx.buffer_row(row)
                self.lines_read = i + 1
                if self.throttle_per_sec:
                    import asyncio

                    await self.flush_buffer(ctx, collector)
                    await asyncio.sleep(1.0 / self.throttle_per_sec)
                elif ctx.should_flush():
                    await self.flush_buffer(ctx, collector)
        await self.flush_buffer(ctx, collector)
        return SourceFinishType.FINAL


class SingleFileSink(Operator):
    def __init__(self, path: str, format: str):
        super().__init__("single_file_sink")
        self.path = path
        self.serializer = Serializer(format=format or "json")
        self.offset = 0
        self._fh = None

    def tables(self):
        from ..state.table_config import global_table

        return {"o": global_table("o")}

    async def on_start(self, ctx):
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        restored = None
        if ctx.table_manager is not None:
            table = await ctx.table("o")
            restored = table.get(ctx.task_info.task_index)
        if restored is not None and os.path.exists(self.path):
            # truncate to the checkpointed offset: drop uncheckpointed output
            with open(self.path, "rb+") as f:
                f.truncate(restored)
            self.offset = restored
            self._fh = open(self.path, "ab")
        else:
            self._fh = open(self.path, "wb")
            self.offset = 0

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        for rec in self.serializer.serialize(batch):
            self._fh.write(rec + b"\n")
            self.offset += len(rec) + 1
        # flush per batch: a multiplexed per-job teardown cancels this
        # subtask at an await point, and a GC-finalized file object later
        # FLUSHES whatever the buffer still holds — interleaving stale
        # bytes into the restarted incarnation's file. An empty buffer at
        # every await point makes the finalizer a no-op.
        self._fh.flush()

    async def handle_checkpoint(self, barrier, ctx, collector):
        self._fh.flush()
        os.fsync(self._fh.fileno())
        if ctx.table_manager is not None:
            table = await ctx.table("o")
            table.put(ctx.task_info.task_index, self.offset)

    async def on_close(self, ctx, collector, is_eod: bool):
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
        return None


@register_connector
class SingleFileConnector(Connector):
    name = "single_file"
    description = "deterministic line-by-line file source/sink (testing)"
    source = True
    sink = True
    config_schema = {
        "path": {"type": "string", "required": True},
    }

    def validate_options(self, options, schema):
        if "path" not in options:
            raise ValueError("single_file requires a path option")
        out = {"path": options["path"]}
        if "throttle_per_sec" in options:
            out["throttle_per_sec"] = float(options["throttle_per_sec"])
        if "lookup_key" in options:
            out["lookup_key"] = options["lookup_key"]
        return out

    def make_source(self, config, schema: ConnectionSchema):
        return SingleFileSource(
            config["path"],
            config.get("schema"),
            config.get("format"),
            config.get("bad_data", "fail"),
            throttle_per_sec=config.get("throttle_per_sec"),
        )

    def make_sink(self, config, schema: ConnectionSchema):
        return SingleFileSink(config["path"], config.get("format"))

    def make_lookup(self, config):
        """Lookup-join support for tests: the JSON-lines file loads into a
        dict keyed by the `lookup_key` field."""
        import json

        key_field = config.get("lookup_key", "key")
        table = {}
        with open(config["path"]) as f:
            for line in f:
                if line.strip():
                    row = json.loads(line)
                    table[str(row[key_field])] = row
        return _DictLookup(table)


class _DictLookup:
    def __init__(self, table: dict):
        self.table = table

    def lookup(self, key: str):
        return self.table.get(key)
