"""Loader + wrapper for the native (C++) slot directory.

The native path handles the common single-int64-key case; everything else
falls back to the python SlotDirectory. Build happens lazily on first use
(g++ is in the image); failures degrade silently to the python
implementation.
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

import numpy as np

_native = None
_tried = False


def load_native():
    global _native, _tried
    if _tried:
        return _native
    _tried = True
    if os.environ.get("ARROYO_DISABLE_NATIVE"):
        return None
    try:
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        native_dir = os.path.join(repo_root, "native")
        sys.path.insert(0, native_dir)
        try:
            # always run the (mtime-cached) build first: importing an
            # existing .so without the check would silently use a stale
            # binary after slotdir.cpp changes
            from importlib import invalidate_caches

            build_py = os.path.join(native_dir, "build.py")
            import importlib.util

            spec = importlib.util.spec_from_file_location("_anb", build_py)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            mod.build()
            invalidate_caches()
            import arroyo_native  # noqa: F401
        finally:
            # the extension stays imported; nothing else should resolve
            # through native/ (it contains a generic build.py)
            try:
                sys.path.remove(native_dir)
            except ValueError:
                pass
        _native = arroyo_native
    except Exception:  # noqa: BLE001 - silent fallback to python impl
        _native = None
    return _native


def _i64_view(c: np.ndarray) -> np.ndarray:
    c = np.asarray(c)
    if c.dtype == np.uint64:
        return c.view(np.int64)
    if c.dtype.kind == "M":
        return c.view("i8")
    return c


class NativeSlotDirectory:
    """N-int64-key directory over the C++ open-addressing table,
    API-compatible with ops.directory.SlotDirectory for the paths the
    window operators use (assign/take_bin/bin_entries/items/peek_bin).
    Keys surface as n-tuples like the python impl; `take_bin_arrays`
    and the 2-D `bin_entries` matrix are the vectorized emission paths
    (no python tuple per key)."""

    def __init__(self, native_mod, n_keys: int = 1):
        # n_keys 0 = unkeyed: one synthetic zero key word, empty tuples out
        self.n_keys = n_keys
        self._stride = max(1, n_keys)
        self._d = native_mod.SlotDir(self._stride)
        self.free: list = []  # parity attribute; slot reuse lives natively

    @property
    def n_live(self) -> int:
        return self._d.n_live()

    def required_capacity(self) -> int:
        return self._d.required_capacity()

    def assign(self, bins: np.ndarray, key_cols: List[np.ndarray]) -> np.ndarray:
        n = len(bins)
        if not key_cols:
            flat = np.zeros(n, dtype=np.int64)
        elif self._stride == 1:
            flat = np.ascontiguousarray(_i64_view(key_cols[0]),
                                        dtype=np.int64)
        else:
            mat = np.empty((n, self._stride), dtype=np.int64)
            for j, c in enumerate(key_cols):
                mat[:, j] = _i64_view(c)
            flat = mat.reshape(-1)
        out = self._d.assign(
            np.ascontiguousarray(bins, dtype=np.int64), flat
        )
        return np.frombuffer(out, dtype=np.int64)

    def _rows_to_tuples(self, kmat: np.ndarray) -> list:
        """Key matrix -> list of python-int tuples in C-level passes
        (a per-row genexpr over numpy scalars is ~10x slower)."""
        if self.n_keys == 0:
            return [()] * len(kmat)
        if self._stride == 1:
            return [(k,) for k in kmat[:, 0].tolist()]
        return list(zip(*(kmat[:, j].tolist()
                          for j in range(self._stride))))

    def _keys_matrix(self, keys_raw: bytes) -> np.ndarray:
        return np.frombuffer(keys_raw, dtype=np.int64).reshape(
            -1, self._stride
        )

    def take_bin(self, b: int) -> Tuple[List[tuple], np.ndarray]:
        keys_raw, slots_raw = self._d.take_bin(int(b))
        keys = self._keys_matrix(keys_raw)
        slots = np.frombuffer(slots_raw, dtype=np.int64).copy()
        return self._rows_to_tuples(keys), slots

    def take_bin_arrays(
        self, b: int
    ) -> Tuple[List[np.ndarray], np.ndarray]:
        """Vectorized take_bin: key columns as int64 arrays (the synthetic
        zero column when unkeyed — callers use it only for row count)."""
        keys_raw, slots_raw = self._d.take_bin(int(b))
        keys = self._keys_matrix(keys_raw)
        slots = np.frombuffer(slots_raw, dtype=np.int64).copy()
        return [keys[:, j] for j in range(self._stride)], slots

    def bin_entries(self, b: int) -> Tuple[np.ndarray, np.ndarray]:
        """(keys int64 matrix (count, stride), slots int64) of a live bin,
        without removal."""
        keys_raw, slots_raw = self._d.get_bin(int(b))
        return (
            self._keys_matrix(keys_raw),
            np.frombuffer(slots_raw, dtype=np.int64),
        )

    def bin_entries_multi(self, bins) -> Tuple[np.ndarray, np.ndarray]:
        """Concatenated (keys matrix, slots) over SEVERAL live bins in one
        C call (the sliding merge reads width/slide bins per emission and
        only ever concatenates them; per-bin identity is not needed)."""
        keys_raw, slots_raw = self._d.get_bins(
            np.ascontiguousarray(np.asarray(bins, dtype=np.int64))
        )
        return (
            self._keys_matrix(keys_raw),
            np.frombuffer(slots_raw, dtype=np.int64),
        )

    @property
    def by_bin(self):
        # truthiness probe used by the sliding operator ("anything live?")
        return {b: True for b in self._d.live_bins()}

    def peek_bin(self, b: int):
        keys, slots = self.bin_entries(b)
        if not len(keys):
            return None
        return dict(zip(self._rows_to_tuples(keys), slots.tolist()))

    def slots_for_keys(self, b: int, keys) -> dict:
        """{key: slot} for the subset of `keys` live in bin b — point
        lookups (O(len(keys))), not a whole-bin materialization."""
        if not keys:
            return {}
        mat = self._keys_to_matrix(keys)
        present, slots_raw = self._d.lookup(
            int(b), np.ascontiguousarray(mat.reshape(-1))
        )
        slots = np.frombuffer(slots_raw, dtype=np.int64)
        return {
            key: int(slots[i])
            for i, key in enumerate(keys) if present[i]
        }

    def _keys_to_matrix(self, keys) -> np.ndarray:
        if self.n_keys == 0:
            return np.zeros((len(keys), 1), dtype=np.int64)
        return np.asarray(keys, dtype=np.int64).reshape(
            len(keys), self._stride
        )

    def remove(self, b: int, keys) -> np.ndarray:
        """Remove specific keys from a bin (TTL eviction / retracted
        keys); returns the freed slots."""
        if not keys:
            return np.empty(0, dtype=np.int64)
        mat = self._keys_to_matrix(keys)
        freed = self._d.remove(int(b), np.ascontiguousarray(mat.reshape(-1)))
        return np.frombuffer(freed, dtype=np.int64).copy()

    def keys_for_slots(self, slots: np.ndarray):
        """Resolve slots back to their live (bin, key) via the native
        reverse index — O(len(slots)), like the python directory's
        key_of map (updating-aggregate dirty tracking)."""
        arr = np.ascontiguousarray(np.asarray(slots, dtype=np.int64))
        present, bins_raw, keys_raw = self._d.keys_for_slots(arr)
        # tolist() yields plain python ints in one C pass — a per-row
        # genexpr over numpy scalars dominated the updating flush
        bins = np.frombuffer(bins_raw, dtype=np.int64).tolist()
        keys = self._rows_to_tuples(self._keys_matrix(keys_raw))
        return [
            (bins[i], keys[i]) if ok else None
            for i, ok in enumerate(present)
        ]

    def live_bins(self) -> List[int]:
        return sorted(self._d.live_bins())

    def bins_up_to(self, limit: int) -> List[int]:
        return sorted(b for b in self._d.live_bins() if b < limit)

    def entries_arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """All live entries as (bins, keys matrix, slots) arrays — one C
        call, no python tuple per key (checkpoint snapshots and the mesh
        facade's per-shard items() ride this)."""
        bins_raw, keys_raw, slots_raw = self._d.entries()
        return (
            np.frombuffer(bins_raw, dtype=np.int64),
            self._keys_matrix(keys_raw),
            np.frombuffer(slots_raw, dtype=np.int64),
        )

    def items(self):
        bins, keys, slots = self.entries_arrays()
        # C-level passes end to end: tolist()/zip instead of a python
        # int()+tuple() per row (the round-5 snapshot profile's cost)
        yield from zip(
            bins.tolist(), self._rows_to_tuples(keys), slots.tolist()
        )


def _i64able(t) -> bool:
    import pyarrow as pa

    # bool keys stay on the python path: native returns python ints and
    # pa.array(ints, type=bool_) is rejected at emission
    return pa.types.is_integer(t) or pa.types.is_timestamp(t)


def flat_key_widths(key_types):
    """Per-key-column int64 word counts for the native directory, or None
    when any column can't ride it (or the native module is absent)."""
    if load_native() is None:
        return None
    return key_word_widths(key_types)


def key_word_widths(key_types):
    """Per-key-column int64 word counts for flat-word directories (native
    C++ and device), or None when any column can't be int64-flattened.
    Struct columns (window structs) flatten into their child words when
    every child is integer/timestamp."""
    import pyarrow as pa

    widths = []
    for t in key_types:
        if pa.types.is_struct(t):
            if t.num_fields == 0 or not all(
                _i64able(t.field(j).type) for j in range(t.num_fields)
            ):
                return None
            widths.append(t.num_fields)
        elif _i64able(t):
            widths.append(1)
        else:
            return None
    return widths if sum(widths) <= 16 else None
