CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE out (
  start TIMESTAMP,
  end TIMESTAMP,
  driver_id BIGINT,
  locations BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO out
SELECT window.start, window.end, driver_id, locations FROM (
  SELECT session(interval '20 second') as window, driver_id,
         count(DISTINCT location) as locations
  FROM cars
  GROUP BY window, driver_id
);
