"""MQTT connector (reference: crates/arroyo-connectors/src/mqtt/, 1,264 LoC
with rumqttc + QoS levels). Client gated on paho-mqtt/aiomqtt."""

from __future__ import annotations

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from ._gated import require_client
from .base import ConnectionSchema, Connector, register_connector


class MqttSource(SourceOperator):
    def __init__(self, url: str, topic: str, qos: int, schema, format, bad_data):
        super().__init__("mqtt_source")
        self.url = url
        self.topic = topic
        self.qos = qos
        self.out_schema = schema
        self.format = format
        self.bad_data = bad_data

    async def run(self, ctx, collector) -> SourceFinishType:
        aiomqtt = require_client("aiomqtt", "paho.mqtt.client")
        deser = Deserializer(self.out_schema, format=self.format or "json",
                             bad_data=self.bad_data)
        async with aiomqtt.Client(self.url) as client:
            await client.subscribe(self.topic, qos=self.qos)
            async for message in client.messages:
                finish = await ctx.check_control(collector)
                if finish is not None:
                    return finish
                for row in deser.deserialize_slice(
                    bytes(message.payload), error_reporter=ctx.error_reporter
                ):
                    ctx.buffer_row(row)
                if ctx.should_flush():
                    await self.flush_buffer(ctx, collector)
        return SourceFinishType.FINAL


class MqttSink(Operator):
    def __init__(self, url: str, topic: str, qos: int, retain: bool, format):
        super().__init__("mqtt_sink")
        self.url = url
        self.topic = topic
        self.qos = qos
        self.retain = retain
        self.serializer = Serializer(format=format or "json")
        self.client = None
        self._stack = None

    async def on_start(self, ctx):
        aiomqtt = require_client("aiomqtt")
        import contextlib

        self._stack = contextlib.AsyncExitStack()
        self.client = await self._stack.enter_async_context(
            aiomqtt.Client(self.url)
        )

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        for rec in self.serializer.serialize(batch):
            await self.client.publish(
                self.topic, rec, qos=self.qos, retain=self.retain
            )

    async def on_close(self, ctx, collector, is_eod: bool):
        if self._stack is not None:
            await self._stack.aclose()
        return None


@register_connector
class MqttConnector(Connector):
    name = "mqtt"
    description = "MQTT source and sink"
    source = True
    sink = True
    config_schema = {
        "url": {"type": "string", "required": True},
        "topic": {"type": "string", "required": True},
        "qos": {"type": "integer"},
        "retain": {"type": "boolean"},
    }

    def validate_options(self, options, schema):
        for k in ("url", "topic"):
            if k not in options:
                raise ValueError(f"mqtt requires a {k} option")
        return {
            "url": options["url"],
            "topic": options["topic"],
            "qos": int(options.get("qos", 0)),
            "retain": str(options.get("retain", "false")).lower() == "true",
        }

    def make_source(self, config, schema: ConnectionSchema):
        return MqttSource(config["url"], config["topic"], config.get("qos", 0),
                          config.get("schema"), config.get("format"),
                          config.get("bad_data", "fail"))

    def make_sink(self, config, schema: ConnectionSchema):
        return MqttSink(config["url"], config["topic"], config.get("qos", 0),
                        config.get("retain", False), config.get("format"))
