"""In-process engine: spawn subtasks, drive checkpoints, await completion.

Capability parity with the reference's Engine::start / RunningEngine
(/root/reference/crates/arroyo-worker/src/engine.rs:385-565): barrier-
synchronized start, per-subtask control handles, checkpoint initiation on
sources only (barriers flow in-band), failure propagation. The full
multi-process job controller lives in arroyo_tpu.controller; this engine is
the worker-local core it drives (and what `run()` uses for local mode).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

from .. import obs
from ..types import CheckpointBarrier, StopMode, now_nanos
from ..utils.logging import get_logger
from ..operators.control import (
    CheckpointCompletedResp,
    CheckpointEventResp,
    CheckpointMsg,
    CommitMsg,
    StopMsg,
    TaskFailedResp,
    TaskFinishedResp,
)
from .program import Program

logger = get_logger("engine")


class JobFailed(Exception):
    pass


class RunningEngine:
    def __init__(self, program: Program, prefinished: Optional[set] = None):
        self.program = program
        self.backend = program._state_backend
        self.tasks: List[asyncio.Task] = []
        self.finished: set = set()
        self.failed: Optional[TaskFailedResp] = None
        self.checkpoint_events: List[CheckpointEventResp] = []
        # epoch -> task_id -> CheckpointCompletedResp
        self.checkpoints: Dict[int, Dict[str, CheckpointCompletedResp]] = {}
        self._epoch = 0
        # epoch -> (trace_id, span_id) of the minted checkpoint trace
        self._ck_trace: Dict[int, tuple] = {}
        # task_ids recorded finished in the restore manifest: their output
        # is fully reflected in the restored state, so they must not re-run
        self.prefinished: set = prefinished or set()

    @property
    def n_subtasks(self) -> int:
        return len(self.program.subtasks)

    def start(self):
        for sub in self.program.subtasks:
            if sub.runner.task_info.task_id in self.prefinished:
                self.tasks.append(
                    asyncio.ensure_future(sub.runner.run_prefinished())
                )
            else:
                self.tasks.append(asyncio.ensure_future(sub.runner.run()))
        return self

    # -- control ------------------------------------------------------------

    async def checkpoint(self, epoch: Optional[int] = None, then_stop: bool = False) -> int:
        """Inject a checkpoint barrier at all sources; in-band alignment does
        the rest. Returns the epoch used."""
        if epoch is None:
            self._epoch += 1
            epoch = self._epoch
        else:
            self._epoch = max(self._epoch, epoch)
        # in-process engine mints the epoch trace itself (no controller
        # hop); wait_checkpoint re-uses it for the publish leg
        with obs.span(
            "checkpoint",
            trace=obs.new_trace(self.program.job_id, f"ck-{epoch}"),
            cat="controller", job=self.program.job_id, epoch=epoch,
            then_stop=then_stop,
        ) as sp:
            self._ck_trace[epoch] = (sp.trace_id, sp.span_id)
            barrier = CheckpointBarrier(
                epoch=epoch, min_epoch=0, timestamp=now_nanos(),
                then_stop=then_stop,
                trace_id=sp.trace_id, span_id=sp.span_id,
            )
            for sub in self.program.source_subtasks():
                sub.control_rx.put_nowait(CheckpointMsg(barrier))
        return epoch

    async def wait_checkpoint(self, epoch: int, timeout: float = 60.0):
        """Wait until every subtask reported CheckpointCompleted for epoch,
        then publish the manifest (durability point).

        A subtask that reaches end-of-stream before the barrier arrives
        will never report it; counting finished subtasks as settled keeps a
        checkpoint racing completion from hanging this wait. The epoch is
        still a consistent cut: a finished task emitted everything before
        its EOS, downstream aligned past the closed input, so the reported
        state already reflects the finished task's full output. It is
        published with those tasks recorded in `finished_tasks`; restore
        re-creates them as pre-finished (EOS immediately, no re-run)."""
        deadline = time.monotonic() + timeout
        while (
            len(self.checkpoints.get(epoch, {}) | {
                t: None for t in self.finished
            }) < self.n_subtasks
        ):
            await self._pump(deadline)
        reports = self.checkpoints.get(epoch, {})
        finished_unreported = sorted(self.finished - set(reports))
        if finished_unreported:
            logger.info(
                "checkpoint %s: %d finished task(s) carried as finished",
                epoch, len(finished_unreported),
            )
        if self.backend is not None:
            tid, sid = self._ck_trace.get(epoch, (None, None))
            with obs.span("checkpoint.publish", trace=tid, parent=sid,
                          cat="controller", epoch=epoch):
                manifest = self.backend.publish_checkpoint(
                    epoch, reports, finished_tasks=finished_unreported
                )
                if manifest.get("committing"):
                    await self.commit_epoch(epoch, manifest["committing"])
                await self._compact(epoch, manifest)
            self._ck_trace.pop(epoch, None)
        return reports

    async def _compact(self, epoch: int, manifest: dict):
        """Controller-side compaction cadence: merge operators' small
        carried-forward files (off the event loop) and tell their subtasks
        to swap references (reference ControlMessage::LoadCompacted); then
        GC epochs nothing references anymore."""
        swaps = await asyncio.to_thread(
            self.backend.compact_epoch, epoch, manifest
        )
        for swap in swaps:
            self.program.send_load_compacted(swap)
        await asyncio.to_thread(self.backend.retire_unreferenced)

    async def commit_epoch(self, epoch: int, committing: Dict[str, dict]):
        """Second phase of 2PC: authorized exactly-once via the commit
        record, then fanned out to sink subtasks."""
        if self.backend is not None and not self.backend.claim_commit(epoch):
            return  # another (older-generation) controller already committed
        data: Dict[int, dict] = {}
        for node_id, subs in committing.items():
            data[int(node_id)] = {
                "data": {int(s): v for s, v in subs.items()}
            }
        msg = CommitMsg(epoch, data)
        ctx = obs.current()
        if ctx is not None:
            msg.trace_id, msg.span_id = ctx
        for sub in self.program.subtasks:
            sub.control_rx.put_nowait(msg)

    async def checkpoint_and_wait(self, then_stop: bool = False) -> Dict[str, CheckpointCompletedResp]:
        epoch = await self.checkpoint(then_stop=then_stop)
        return await self.wait_checkpoint(epoch)

    async def commit(self, epoch: int, committing_data: Optional[dict] = None):
        for sub in self.program.subtasks:
            sub.control_rx.put_nowait(CommitMsg(epoch, committing_data or {}))

    async def stop(self, mode: StopMode = StopMode.GRACEFUL):
        targets = (
            self.program.source_subtasks()
            if mode == StopMode.GRACEFUL
            else self.program.subtasks
        )
        for sub in targets:
            sub.control_rx.put_nowait(StopMsg(mode))

    async def join(self, timeout: float = 300.0):
        """Wait for all subtasks to finish; raises JobFailed on task error."""
        deadline = time.monotonic() + timeout
        while len(self.finished) < self.n_subtasks:
            await self._pump(deadline)
        await asyncio.gather(*self.tasks, return_exceptions=True)

    # -- response pump -------------------------------------------------------

    async def _pump(self, deadline: float):
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError("engine wait timed out")
        try:
            resp = await asyncio.wait_for(
                self.program.control_resp.get(), timeout=min(remaining, 1.0)
            )
        except asyncio.TimeoutError:
            return
        self._handle_resp(resp)

    def _handle_resp(self, resp):
        if isinstance(resp, TaskFinishedResp):
            self.finished.add(resp.task_id)
        elif isinstance(resp, TaskFailedResp):
            self.failed = resp
            for t in self.tasks:
                t.cancel()
            raise JobFailed(f"task {resp.task_id} failed:\n{resp.error}")
        elif isinstance(resp, CheckpointCompletedResp):
            self.checkpoints.setdefault(resp.epoch, {})[resp.task_id] = resp
        elif isinstance(resp, CheckpointEventResp):
            self.checkpoint_events.append(resp)

    def drain_responses(self):
        while True:
            try:
                self._handle_resp(self.program.control_resp.get_nowait())
            except asyncio.QueueEmpty:
                return


class Engine:
    """Convenience façade: build a program from a logical graph and run it.

    With `storage_url`, state is checkpointed through a StateBackend; if the
    job has a durable checkpoint it restores from it (epoch pinned via
    `restore_epoch`)."""

    def __init__(self, graph, job_id: str = "job", state_backend=None,
                 storage_url: Optional[str] = None,
                 restore_epoch: Optional[int] = None):
        self.program = Program(graph, job_id)
        if state_backend is None and storage_url is not None:
            from ..state.backend import StateBackend

            state_backend = StateBackend(storage_url, job_id).initialize(
                restore_epoch
            )
        if state_backend is not None:
            self.program.with_state(state_backend)

    def start(self) -> RunningEngine:
        self.program.build()
        backend = self.program._state_backend
        prefinished = set()
        if backend is not None and backend.restore_manifest:
            prefinished = set(
                backend.restore_manifest.get("finished_tasks", [])
            )
        eng = RunningEngine(self.program, prefinished=prefinished).start()
        if backend is not None:
            eng._epoch = backend.restore_epoch or 0
        return eng
