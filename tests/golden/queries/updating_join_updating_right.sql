--pk=left_counter,counter_mod_2
CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE output (
  left_counter BIGINT,
  counter_mod_2 BIGINT,
  right_count BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO output
SELECT counter as left_counter, counter_mod_2, right_count FROM impulse
JOIN (
  SELECT counter % 2 as counter_mod_2, count(*) as right_count
  FROM impulse WHERE counter < 3 GROUP BY 1
) ON counter = right_count WHERE counter < 3;
