import numpy as np
import pytest

from arroyo_tpu.types import (
    Watermark,
    hash_arrays,
    hash_column,
    range_for_server,
    server_for_hash,
    server_for_hash_array,
)


def test_ranges_cover_u64_space_exactly():
    for n in (1, 2, 3, 7, 8, 128):
        prev_end = 0
        for i in range(n):
            lo, hi = range_for_server(i, n)
            assert lo == prev_end
            assert hi > lo
            prev_end = hi
        assert prev_end == 1 << 64


def test_server_for_hash_matches_ranges():
    rng = np.random.default_rng(0)
    hashes = rng.integers(0, 1 << 64, size=1000, dtype=np.uint64)
    for n in (1, 2, 5, 16):
        vec = server_for_hash_array(hashes, n)
        for h, p in zip(hashes[:50], vec[:50]):
            assert server_for_hash(int(h), n) == p
            lo, hi = range_for_server(int(p), n)
            assert lo <= int(h) < hi
        assert vec.min() >= 0 and vec.max() < n


def test_hash_deterministic_across_dtypes():
    a = hash_column(np.array([1, 2, 3], dtype=np.int64))
    b = hash_column(np.array([1, 2, 3], dtype=np.int32))
    np.testing.assert_array_equal(a, b)
    s1 = hash_column(np.array(["x", "y", "x"], dtype=object))
    assert s1[0] == s1[2] and s1[0] != s1[1]


def test_hash_combine_order_sensitive():
    c1 = hash_column(np.array([1, 2]))
    c2 = hash_column(np.array([5, 6]))
    combined = hash_arrays([c1, c2])
    swapped = hash_arrays([c2, c1])
    assert combined.dtype == np.uint64
    assert not np.array_equal(combined, swapped)


def test_float_negative_zero_normalized():
    h = hash_column(np.array([0.0, -0.0]))
    assert h[0] == h[1]


def test_watermark_kinds():
    w = Watermark.event_time(100)
    assert not w.is_idle() and w.timestamp == 100
    assert Watermark.idle().is_idle()
