"""MUST fire RACE001: `counter` is written from two task-spawn roots
(`drive` and `checkpoint`) with no common lock and is not declared
``multi_writer`` — last-writer-wins here is an accident, not a policy."""
import asyncio

from arroyo_tpu.analysis.races import shared_state


@shared_state("counter")
class Job:
    def __init__(self):
        self.counter = 0


class Engine:
    async def drive(self, job):
        job.counter = 1

    async def checkpoint(self, job):
        job.counter = 2

    def start(self, job):
        asyncio.ensure_future(self.drive(job))
        asyncio.ensure_future(self.checkpoint(job))
