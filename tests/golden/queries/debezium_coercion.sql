--pk=counter
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE output (
  counter BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO output
SELECT counter FROM impulse_source;
