"""SQLite/Postgres persistence for the REST API.

Capability parity with the reference's database layer
(/root/reference/crates/arroyo-api: cornucopia-generated queries over
Postgres, parallel SQLite migrations for `arroyo run`): pipelines, jobs,
udfs, connection profiles/tables. Backend selection mirrors the
reference (`database.backend: sqlite | postgres`): SQLite is the
embedded/`run` path; Postgres (via psycopg 3 or psycopg2, whichever is
installed) is the shared-cluster path — one DDL, one query set, a thin
placeholder/row adapter bridging the two DBAPI dialects.

With `remote_url` set (reference MaybeLocalDb, crates/arroyo run.rs:
remote state dirs sync the sqlite file through object storage), the db
file downloads from the storage URL when no local copy exists yet and
mirrors up after mutations (skipped when nothing changed). Single-writer
semantics, like the reference's run path: one process owns the remote
copy at a time; concurrent writers are last-writer-wins.
"""

from __future__ import annotations

import json
import sqlite3
import time
import uuid
from pathlib import Path
from typing import List, Optional

_V1_TABLES = [
    """
    CREATE TABLE IF NOT EXISTS pipelines (
        id TEXT PRIMARY KEY,
        name TEXT NOT NULL,
        query TEXT NOT NULL,
        parallelism INTEGER NOT NULL DEFAULT 1,
        state TEXT NOT NULL DEFAULT 'Created',
        graph_json TEXT,
        created_at REAL NOT NULL
    )
    """,
    """
    CREATE TABLE IF NOT EXISTS jobs (
        id TEXT PRIMARY KEY,
        pipeline_id TEXT NOT NULL REFERENCES pipelines(id),
        state TEXT NOT NULL,
        restarts INTEGER NOT NULL DEFAULT 0,
        created_at REAL NOT NULL,
        finished_at REAL
    )
    """,
    """
    CREATE TABLE IF NOT EXISTS udfs (
        id TEXT PRIMARY KEY,
        prefix TEXT,
        name TEXT NOT NULL,
        definition TEXT NOT NULL,
        language TEXT NOT NULL DEFAULT 'python',
        created_at REAL NOT NULL
    )
    """,
    """
    CREATE TABLE IF NOT EXISTS connection_profiles (
        id TEXT PRIMARY KEY,
        name TEXT NOT NULL,
        connector TEXT NOT NULL,
        config TEXT NOT NULL,
        created_at REAL NOT NULL
    )
    """,
    """
    CREATE TABLE IF NOT EXISTS connection_tables (
        id TEXT PRIMARY KEY,
        name TEXT NOT NULL,
        connector TEXT NOT NULL,
        profile_id TEXT,
        config TEXT NOT NULL,
        schema_json TEXT,
        table_type TEXT,
        created_at REAL NOT NULL
    )
    """,
]

# Versioned, append-only migrations: each entry is (version,
# [statements]). The applied version persists in schema_version; on open
# only entries above the stored version run, in order — the reference
# ships 32 numbered Postgres migrations + a parallel SQLite set
# (arroyo-api/migrations/), and round 4 flagged the bare
# CREATE-IF-NOT-EXISTS approach as breaking at the first schema change.
# NEVER edit a shipped version; append a new one.
MIGRATIONS = [
    (1, _V1_TABLES),
    (2, [
        "CREATE INDEX IF NOT EXISTS idx_jobs_pipeline "
        "ON jobs(pipeline_id)",
    ]),
    # multi-tenant control plane: pipelines belong to a tenant whose
    # admission quota + fair share govern slot scheduling
    (3, [
        "ALTER TABLE pipelines ADD COLUMN tenant TEXT "
        "NOT NULL DEFAULT 'default'",
    ]),
]


def apply_migrations(conn) -> int:
    """Apply every migration above the stored schema version, in order;
    returns the resulting version. Works over both the sqlite3 and the
    postgres adapter connection (dict-like rows either way)."""
    conn.execute(
        "CREATE TABLE IF NOT EXISTS schema_version ("
        "version INTEGER NOT NULL, applied_at REAL NOT NULL)"
    )
    row = conn.execute(
        "SELECT MAX(version) AS v FROM schema_version"
    ).fetchone()
    current = (row["v"] if row is not None else None) or 0
    for version, stmts in MIGRATIONS:
        if version <= current:
            continue
        for s in stmts:
            conn.execute(s)
        conn.execute(
            "INSERT INTO schema_version (version, applied_at) "
            "VALUES (?, ?)",
            (version, time.time()),
        )
        current = version
    return current


class _PgCursor:
    """Cursor facade: dict rows regardless of driver flavor."""

    def __init__(self, cur):
        self._cur = cur

    def _row(self, r):
        if r is None or isinstance(r, dict):
            return r
        # psycopg2 without RealDictCursor: zip against the description
        return {
            d[0]: v for d, v in zip(self._cur.description, r)
        }

    def fetchone(self):
        return self._row(self._cur.fetchone())

    def fetchall(self):
        return [self._row(r) for r in self._cur.fetchall()]


class _PgConn:
    """Adapter giving a Postgres DBAPI connection the sqlite3 surface
    ApiDb uses: `?` placeholders, dict rows, total_changes."""

    def __init__(self, raw):
        self.raw = raw
        self.total_changes = 0

    def execute(self, sql, params=()):
        cur = self.raw.cursor()
        try:
            cur.execute(sql.replace("?", "%s"), tuple(params))
        except Exception:
            # a failed statement aborts the postgres transaction; without
            # a rollback every later query raises InFailedSqlTransaction
            # and one bad request wedges the whole API
            self.raw.rollback()
            raise
        if not sql.lstrip().upper().startswith(("SELECT", "CREATE")):
            self.total_changes += max(cur.rowcount, 0)
        return _PgCursor(cur)

    def commit(self):
        self.raw.commit()


def connect_postgres(dsn: str) -> _PgConn:
    """psycopg (3) preferred, psycopg2 fallback; loud gated error when
    neither is installed (parity note: the reference links tokio-postgres
    unconditionally; this build treats the driver as optional)."""
    try:
        import psycopg
        from psycopg.rows import dict_row

        return _PgConn(psycopg.connect(dsn, row_factory=dict_row))
    except ImportError:
        pass
    try:
        import psycopg2
        import psycopg2.extras

        return _PgConn(
            psycopg2.connect(
                dsn, cursor_factory=psycopg2.extras.RealDictCursor
            )
        )
    except ImportError:
        raise RuntimeError(
            "database.backend = postgres requires psycopg (3) or "
            "psycopg2, neither of which is installed; use the sqlite "
            "backend or install a driver"
        )


class ApiDb:
    REMOTE_KEY = "api/arroyo.db"

    def __init__(self, path: str = ":memory:",
                 remote_url: Optional[str] = None,
                 backend: str = "sqlite",
                 dsn: str = "",
                 _pg_conn=None):
        self.backend = backend
        if backend == "postgres" or _pg_conn is not None:
            self.backend = "postgres"
            self.remote = None
            self.path = None
            self.conn = _pg_conn if _pg_conn is not None else (
                connect_postgres(dsn or path)
            )
            apply_migrations(self.conn)
            self.conn.commit()
            return
        self.remote = None
        self._synced_changes = 0
        if remote_url:
            import hashlib
            import tempfile

            from ..state.storage import StorageProvider

            self.remote = StorageProvider(remote_url)
            if path == ":memory:":
                # deterministic per-remote local cache (reused, not leaked)
                tag = hashlib.sha1(remote_url.encode()).hexdigest()[:10]
                path = str(Path(tempfile.gettempdir())
                           / f"arroyo-api-{tag}.db")
            if not Path(path).exists():
                # only seed from the remote when there is no local copy —
                # never silently clobber a populated newer local db
                blob = self.remote.get(self.REMOTE_KEY)
                if blob is not None:
                    Path(path).parent.mkdir(parents=True, exist_ok=True)
                    Path(path).write_bytes(blob)
        if path != ":memory:":
            Path(path).parent.mkdir(parents=True, exist_ok=True)
        self.path = path
        self.conn = sqlite3.connect(path)
        self.conn.row_factory = sqlite3.Row
        apply_migrations(self.conn)
        self.conn.commit()

    def _commit(self):
        """Commit locally, then mirror the whole db file to the remote
        (the file is small; the reference syncs it wholesale too). The
        upload is skipped when no rows actually changed (polling callers
        re-write identical state at 5Hz) and is best-effort: a transient
        storage error must not fail a mutation that already committed."""
        self.conn.commit()
        if self.remote is None or self.path == ":memory:":
            return
        if self.conn.total_changes == self._synced_changes:
            return
        try:
            self.remote.put(self.REMOTE_KEY, Path(self.path).read_bytes())
            self._synced_changes = self.conn.total_changes
        except Exception as e:  # noqa: BLE001
            import logging

            logging.getLogger("arroyo.api").warning(
                "remote db sync failed (will retry on next change): %s", e
            )

    # -- pipelines ----------------------------------------------------------

    def create_pipeline(self, name: str, query: str, parallelism: int,
                        graph_json: Optional[dict] = None,
                        tenant: str = "default") -> dict:
        pid = "pl_" + uuid.uuid4().hex[:12]
        self.conn.execute(
            "INSERT INTO pipelines (id, name, query, parallelism, state, "
            "graph_json, created_at, tenant) VALUES (?,?,?,?,?,?,?,?)",
            (pid, name, query, parallelism, "Created",
             json.dumps(graph_json) if graph_json else None, time.time(),
             tenant or "default"),
        )
        self._commit()
        return self.get_pipeline(pid)

    def list_pipelines(self) -> List[dict]:
        rows = self.conn.execute(
            "SELECT * FROM pipelines ORDER BY created_at DESC"
        ).fetchall()
        return [self._pipeline(r) for r in rows]

    def get_pipeline(self, pid: str) -> Optional[dict]:
        r = self.conn.execute(
            "SELECT * FROM pipelines WHERE id = ?", (pid,)
        ).fetchone()
        return self._pipeline(r) if r else None

    def set_pipeline_parallelism(self, pid: str, parallelism: int):
        self.conn.execute(
            "UPDATE pipelines SET parallelism = ? WHERE id = ?",
            (parallelism, pid),
        )
        self._commit()

    def set_pipeline_state(self, pid: str, state: str):
        # value-guarded: pollers re-write identical state at 5Hz, and a
        # no-op UPDATE would still count as a change for the remote sync
        self.conn.execute(
            "UPDATE pipelines SET state = ? WHERE id = ? AND state != ?",
            (state, pid, state),
        )
        self._commit()

    def delete_pipeline(self, pid: str):
        self.conn.execute("DELETE FROM jobs WHERE pipeline_id = ?", (pid,))
        self.conn.execute("DELETE FROM pipelines WHERE id = ?", (pid,))
        self._commit()

    @staticmethod
    def _pipeline(r) -> dict:
        keys = r.keys() if hasattr(r, "keys") else []
        return {
            "id": r["id"],
            "name": r["name"],
            "query": r["query"],
            "parallelism": r["parallelism"],
            "state": r["state"],
            "created_at": r["created_at"],
            "tenant": r["tenant"] if "tenant" in keys else "default",
        }

    # -- jobs ---------------------------------------------------------------

    def create_job(self, pipeline_id: str) -> dict:
        jid = "job_" + uuid.uuid4().hex[:12]
        self.conn.execute(
            "INSERT INTO jobs (id, pipeline_id, state, created_at) "
            "VALUES (?,?,?,?)",
            (jid, pipeline_id, "Created", time.time()),
        )
        self._commit()
        return {"id": jid, "pipeline_id": pipeline_id, "state": "Created"}

    def update_job(self, jid: str, state: str,
                   restarts: Optional[int] = None):
        finished = (
            time.time()
            if state in ("Finished", "Failed", "Stopped")
            else None
        )
        # value-guarded like set_pipeline_state (5Hz pollers)
        self.conn.execute(
            "UPDATE jobs SET state = ?, restarts = COALESCE(?, restarts), "
            "finished_at = COALESCE(?, finished_at) WHERE id = ? AND "
            "(state != ? OR restarts != COALESCE(?, restarts))",
            (state, restarts, finished, jid, state, restarts),
        )
        self._commit()

    def jobs_for_pipeline(self, pid: str) -> List[dict]:
        rows = self.conn.execute(
            "SELECT * FROM jobs WHERE pipeline_id = ? ORDER BY created_at",
            (pid,),
        ).fetchall()
        return [dict(r) for r in rows]

    def all_jobs(self) -> List[dict]:
        return [dict(r) for r in self.conn.execute(
            "SELECT * FROM jobs ORDER BY created_at DESC"
        ).fetchall()]

    # -- udfs ---------------------------------------------------------------

    def create_udf(self, name: str, definition: str, prefix: str = "",
                   language: str = "python") -> dict:
        uid = "udf_" + uuid.uuid4().hex[:12]
        self.conn.execute(
            "INSERT INTO udfs (id, prefix, name, definition, language, "
            "created_at) VALUES (?,?,?,?,?,?)",
            (uid, prefix, name, definition, language, time.time()),
        )
        self._commit()
        return {"id": uid, "name": name, "definition": definition,
                "language": language}

    def list_udfs(self) -> List[dict]:
        return [dict(r) for r in self.conn.execute(
            "SELECT * FROM udfs ORDER BY created_at"
        ).fetchall()]

    def delete_udf(self, uid: str):
        self.conn.execute("DELETE FROM udfs WHERE id = ?", (uid,))
        self._commit()

    # -- connections --------------------------------------------------------

    def create_connection_profile(self, name: str, connector: str,
                                  config: dict) -> dict:
        cid = "cp_" + uuid.uuid4().hex[:12]
        self.conn.execute(
            "INSERT INTO connection_profiles (id, name, connector, config, "
            "created_at) VALUES (?,?,?,?,?)",
            (cid, name, connector, json.dumps(config), time.time()),
        )
        self._commit()
        return {"id": cid, "name": name, "connector": connector,
                "config": config}

    def list_connection_profiles(self) -> List[dict]:
        out = []
        for r in self.conn.execute(
            "SELECT * FROM connection_profiles ORDER BY created_at"
        ).fetchall():
            d = dict(r)
            d["config"] = json.loads(d["config"])
            out.append(d)
        return out

    def create_connection_table(self, name: str, connector: str, config: dict,
                                schema: Optional[dict], table_type: str,
                                profile_id: Optional[str]) -> dict:
        cid = "ct_" + uuid.uuid4().hex[:12]
        self.conn.execute(
            "INSERT INTO connection_tables (id, name, connector, profile_id, "
            "config, schema_json, table_type, created_at) "
            "VALUES (?,?,?,?,?,?,?,?)",
            (cid, name, connector, profile_id, json.dumps(config),
             json.dumps(schema) if schema else None, table_type, time.time()),
        )
        self._commit()
        return {"id": cid, "name": name, "connector": connector,
                "config": config, "table_type": table_type}

    def list_connection_tables(self) -> List[dict]:
        out = []
        for r in self.conn.execute(
            "SELECT * FROM connection_tables ORDER BY created_at"
        ).fetchall():
            d = dict(r)
            d["config"] = json.loads(d["config"])
            if d["schema_json"]:
                d["schema"] = json.loads(d["schema_json"])
            del d["schema_json"]
            out.append(d)
        return out

    def delete_connection_table(self, cid: str):
        self.conn.execute("DELETE FROM connection_tables WHERE id = ?", (cid,))
        self._commit()
