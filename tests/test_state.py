"""State subsystem: storage CAS, generation fencing, table round-trips,
and full checkpoint -> stop -> restore -> identical output through the
engine (the reference smoke-test fault-tolerance pattern)."""

import asyncio

import numpy as np
import pyarrow as pa
import pytest

from arroyo_tpu.config import update
from arroyo_tpu.connectors.impulse import IMPULSE_SCHEMA
from arroyo_tpu.engine import Engine
from arroyo_tpu.graph import EdgeType, LogicalGraph, OperatorName
from arroyo_tpu.graph.logical import ChainedOp, LogicalNode
from arroyo_tpu.schema import StreamSchema
from arroyo_tpu.state import protocol
from arroyo_tpu.state.backend import StateBackend
from arroyo_tpu.state.protocol import Fenced, ProtocolPaths
from arroyo_tpu.state.storage import CasConflict, StorageProvider
from arroyo_tpu.state.table_config import time_key_table
from arroyo_tpu.state.tables import TimeKeyTable

MS = 1_000_000


def test_storage_cas(tmp_storage):
    s = StorageProvider(tmp_storage)
    s.put_if_not_exists("a/b.json", b"1")
    with pytest.raises(CasConflict):
        s.put_if_not_exists("a/b.json", b"2")
    assert s.get("a/b.json") == b"1"
    assert s.list("a") == ["a/b.json"]
    s.delete_directory("a")
    assert s.get("a/b.json") is None


def test_generation_fencing(tmp_storage):
    s = StorageProvider(tmp_storage)
    paths = ProtocolPaths("job1")
    g1 = protocol.initialize_generation(s, paths)
    g2 = protocol.initialize_generation(s, paths)  # new controller takes over
    assert g2 == g1 + 1
    # the old generation can no longer publish
    with pytest.raises(Fenced):
        protocol.publish_checkpoint(s, paths, g1, 1, {"tasks": {}})
    protocol.publish_checkpoint(s, paths, g2, 1, {"tasks": {}})
    latest = protocol.resolve_latest(s, paths)
    assert latest["epoch"] == 1 and latest["generation"] == g2


def test_commit_claims_exactly_once(tmp_storage):
    s = StorageProvider(tmp_storage)
    paths = ProtocolPaths("job1")
    g = protocol.initialize_generation(s, paths)
    protocol.prepare_commit(s, paths, g, 3, {"5": {"0": "data"}})
    assert protocol.pending_commit(s, paths, 3)["committing"] == {"5": {"0": "data"}}
    assert protocol.claim_commit(s, paths, g, 3) is True
    assert protocol.claim_commit(s, paths, g, 3) is False  # second claimant loses
    assert protocol.pending_commit(s, paths, 3) is None


def test_time_key_table_retention_and_restore(tmp_storage):
    cfg = time_key_table("j", retention_nanos=10 * MS, key_fields=("k",))
    t = TimeKeyTable(cfg)
    schema = pa.schema([("k", pa.int64()), ("_timestamp", pa.int64())])
    t.insert(pa.RecordBatch.from_arrays(
        [pa.array([1, 2]), pa.array([0, 1 * MS])], schema=schema))
    t.insert(pa.RecordBatch.from_arrays(
        [pa.array([3, 4]), pa.array([20 * MS, 21 * MS])], schema=schema))
    t.expire(25 * MS)  # cutoff 15ms: first batch fully expired
    assert sum(b.num_rows for b in t.all_batches()) == 2
    # key-range filtered restore: two partitions split keys
    t2 = TimeKeyTable(cfg)
    t2.load_batches(t.all_batches(), parallelism=2, task_index=0)
    t3 = TimeKeyTable(cfg)
    t3.load_batches(t.all_batches(), parallelism=2, task_index=1)
    n2 = sum(b.num_rows for b in t2.all_batches())
    n3 = sum(b.num_rows for b in t3.all_batches())
    assert n2 + n3 == 2


# -- engine-level fault tolerance -------------------------------------------


def agg_pipeline(results, storage_seed=0, parallelism=1, throttle=0.0):
    g = LogicalGraph()
    g.add_node(
        LogicalNode(
            1,
            "impulse",
            [
                ChainedOp(
                    OperatorName.CONNECTOR_SOURCE,
                    {
                        "connector": "impulse",
                        "event_rate": 1e6,
                        "message_count": 10_000,
                        "start_time": 0,
                        "schema": IMPULSE_SCHEMA,
                    },
                ),
                ChainedOp(OperatorName.EXPRESSION_WATERMARK, {}),
            ],
            1,
        )
    )

    def with_key(batch):
        import time as _time

        import pyarrow.compute as pc

        if throttle:
            # wall-clock drag per batch (event time untouched): keeps
            # windows live long enough for a mid-stream checkpoint to
            # capture keyed state deterministically
            _time.sleep(throttle)
        k = pc.bit_wise_and(batch.column(0), 7)
        return pa.RecordBatch.from_arrays(
            [k, batch.column(1), batch.column(2)],
            schema=pa.schema([
                pa.field("counter", pa.uint64()),
                batch.schema.field(1),
                batch.schema.field(2),
            ]),
        )

    g.nodes[1].chain.insert(
        1, ChainedOp(OperatorName.ARROW_VALUE, {"py_fn": with_key})
    )
    out_schema = StreamSchema.from_fields(
        [("counter", pa.uint64()), ("cnt", pa.int64()), ("total", pa.int64())]
    )
    g.add_node(
        LogicalNode.single(
            2,
            OperatorName.TUMBLING_WINDOW_AGGREGATE,
            {
                "width_nanos": MS,
                "aggregates": [
                    {"kind": "count", "name": "cnt"},
                    {"kind": "sum", "col": 0, "name": "total"},
                ],
                "key_cols": [0],
                "schema": out_schema,
                "backend": "numpy",
            },
            parallelism=parallelism,
        )
    )
    g.add_node(
        LogicalNode.single(
            3,
            OperatorName.CONNECTOR_SINK,
            {"connector": "vec", "results": results},
            parallelism=parallelism,
        )
    )
    g.add_edge(1, 2, EdgeType.SHUFFLE, IMPULSE_SCHEMA.with_keys(["counter"]))
    g.add_edge(2, 3, EdgeType.FORWARD, out_schema)
    return g


def golden_run():
    results = []
    g = agg_pipeline(results)

    async def go():
        eng = Engine(g).start()
        await eng.join(60)

    asyncio.run(go())
    return sorted(
        (r["counter"], r["cnt"], r["total"], r["_timestamp"]) for r in results
    )


def checkpoint_restore_run(tmp_storage, restart_parallelism=1):
    url = f"{tmp_storage}/ckpt"
    part1 = []
    g = agg_pipeline(part1)

    async def run1():
        eng = Engine(g, job_id="ft", storage_url=url).start()
        # let some data flow, then checkpoint-and-stop
        while not part1:
            await asyncio.sleep(0.01)
            eng.drain_responses()
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(run1())

    part2 = []
    g2 = agg_pipeline(part2, parallelism=restart_parallelism)

    async def run2():
        eng = Engine(g2, job_id="ft", storage_url=url).start()
        await eng.join(60)

    asyncio.run(run2())
    combined = part1 + part2
    return sorted(
        (r["counter"], r["cnt"], r["total"], r["_timestamp"]) for r in combined
    )


def test_checkpoint_restore_identical_output(tmp_storage):
    with update(pipeline={"source_batch_size": 128}):
        want = golden_run()
        got = checkpoint_restore_run(tmp_storage)
    assert len(want) == 10 * 8  # 10 bins x 8 keys
    assert got == want


def test_checkpoint_restore_with_rescale(tmp_storage):
    """Restart at parallelism 2: key-range sharded state re-reads."""
    with update(pipeline={"source_batch_size": 128}):
        want = golden_run()
        got = checkpoint_restore_run(tmp_storage, restart_parallelism=2)
    assert got == want


def _assert_agg_key_ownership(eng, node_id=2) -> int:
    """Every key currently held by an agg subtask's slot directory must
    hash into that subtask's range — a restore that failed to re-filter
    by key range leaves foreign keys behind. Returns subtasks checked."""
    from arroyo_tpu.types import (
        hash_arrays,
        hash_column,
        server_for_hash_array,
    )

    checked = 0
    for sub in eng.program.subtasks:
        ti = sub.runner.task_info
        if ti.node_id != node_id or ti.parallelism <= 1:
            continue
        for op in sub.runner.ops:
            d = getattr(op, "dir", None)
            if d is None:
                continue
            keys = [key for _b, key, _s in d.items()]
            if not keys:
                continue
            col = hash_column(np.asarray(
                [k[0] if isinstance(k, tuple) else k for k in keys],
                dtype=np.int64,
            ))
            owners = server_for_hash_array(hash_arrays([col]), ti.parallelism)
            assert (owners == ti.task_index).all(), (
                f"subtask {ti.task_id} holds keys outside its range: "
                f"{sorted(set(k[0] for k in keys))}"
            )
            checked += 1
    return checked


def test_rescale_round_trip_1_4_2(tmp_storage):
    """ISSUE 5 satellite: windowed agg at parallelism 1 -> checkpoint ->
    restore at 4 -> checkpoint -> restore at 2 — exactly-once canonical
    output across all three phases, and at each restored parallelism the
    live slot directories hold only keys in their own hash range."""
    url = f"{tmp_storage}/rt"

    import time as _time

    # per-batch wall-clock throttle (event time untouched, so the golden
    # output is identical): guarantees each phase's stop checkpoint lands
    # while windows are still live, making the key-ownership checks and
    # the phase hand-offs deterministic instead of racing the final flush
    throttle = 0.003

    def run_phase(results, parallelism, stop_after_output):
        """Start (or restore) at `parallelism`, wait for the first new
        output while checking key ownership on every scheduler step, then
        either checkpoint-stop or run to completion. Returns the max
        subtasks seen holding keyed state."""
        g = agg_pipeline(results, parallelism=parallelism,
                         throttle=throttle)
        checked = 0

        async def go():
            nonlocal checked
            eng = Engine(g, job_id="rt", storage_url=url).start()
            seen = len(results)
            deadline = _time.monotonic() + 30
            while len(results) <= seen:
                checked = max(checked, _assert_agg_key_ownership(eng))
                assert _time.monotonic() < deadline, (
                    f"parallelism-{parallelism} phase produced no output"
                )
                await asyncio.sleep(0)
                eng.drain_responses()
            checked = max(checked, _assert_agg_key_ownership(eng))
            if stop_after_output:
                await eng.checkpoint_and_wait(then_stop=True)
            await eng.join(60)

        asyncio.run(go())
        return checked

    with update(pipeline={"source_batch_size": 128}):
        want = golden_run()

        part1 = []
        run_phase(part1, 1, stop_after_output=True)
        assert part1, "phase 1 produced no output before its stop"

        part2 = []
        checked4 = run_phase(part2, 4, stop_after_output=True)
        assert checked4 >= 2, "parallelism-4 phase never held keyed state"

        part3 = []
        checked2 = run_phase(part3, 2, stop_after_output=False)
        assert checked2 >= 1

    got = sorted(
        (r["counter"], r["cnt"], r["total"], r["_timestamp"])
        for r in part1 + part2 + part3
    )
    assert got == want, (
        f"rescale round-trip lost or duplicated rows: "
        f"{len(got)} vs {len(want)}"
    )


def test_backend_manifest_roundtrip(tmp_storage):
    from arroyo_tpu.operators.control import CheckpointCompletedResp

    b = StateBackend(f"{tmp_storage}/m", "j1").initialize()
    resp = CheckpointCompletedResp(
        "2-0", 2, 0, 1,
        subtask_metadata={"op0": {"t": {"kind": "global", "path": "x"}}},
        watermark=123,
    )
    b.publish_checkpoint(1, {"2-0": resp})
    b2 = StateBackend(f"{tmp_storage}/m", "j1").initialize()
    assert b2.restore_epoch == 1
    assert b2.tables_for(2, 0) == [
        {"subtask": 0, "tables": {"t": {"kind": "global", "path": "x"}}}
    ]
    assert b2.restore_watermark("2-0") == 123


def test_compaction_cadence_and_gc(tmp_storage):
    """Controller-driven compaction: once an operator carries
    compaction_epoch_threshold small files, compact_epoch merges them, the
    table swaps references (LoadCompacted), restore reads the compacted
    file, and epochs nothing references anymore are GC'd."""
    from arroyo_tpu.operators.control import CheckpointCompletedResp
    from arroyo_tpu.state.table_manager import TableManager
    from arroyo_tpu.types import TaskInfo

    url = f"{tmp_storage}/c"

    def batch(v):
        return pa.RecordBatch.from_arrays(
            [pa.array([v]),
             pa.array([v * MS]).cast(pa.timestamp("ns"))],
            names=["v", "_timestamp"],
        )

    async def run():
        b = StateBackend(url, "cj").initialize()
        ti = TaskInfo("cj", 5, "op", 0, 1)
        tm = TableManager(b, ti, 0)
        await tm.open({"tk": time_key_table("tk")})
        table = await tm.get_table("tk")
        all_swaps = []
        for epoch in range(1, 9):
            table.insert(batch(epoch))
            meta = await tm.checkpoint(epoch, None)
            resp = CheckpointCompletedResp(
                "5-0", 5, 0, epoch, subtask_metadata={"op0": meta},
                watermark=None,
            )
            manifest = b.publish_checkpoint(epoch, {"5-0": resp})
            swaps = b.compact_epoch(epoch, manifest)
            for s in swaps:
                assert (s["node_id"], s["op_idx"], s["table"]) == (5, 0, "tk")
                await tm.load_compacted(s["table"], s["files"])
            all_swaps.extend(swaps)
            b.retire_unreferenced()
        return all_swaps, table

    with update(pipeline={"checkpointing": {
            "compaction_enabled": True, "compaction_epoch_threshold": 4}}):
        swaps, table = asyncio.run(run())
        # threshold 4 -> merge at epoch 4 (4 small files) and a re-merge at
        # epoch 7 ([compacted4, f5, f6, f7])
        assert [s["files"][0]["rows"] for s in swaps] == [4, 7]
        assert all("/compacted/" in s["files"][0]["path"] for s in swaps)
        assert len(table.files) == 2  # [compacted7, epoch-8 file]
        s = StorageProvider(url)
        # epochs 1-7 unreferenced by the latest manifest and GC'd
        dirs = {k.split("/")[2] for k in s.list("cj/checkpoints")}
        assert dirs == {"checkpoint-0000008"}
        # the epoch-4 merge was superseded by the epoch-7 re-merge and GC'd
        compacted = s.list("cj/compacted")
        assert len(compacted) == 1 and "epoch0000007" in compacted[0]

        async def restore():
            b2 = StateBackend(url, "cj").initialize()
            assert b2.restore_epoch == 8
            tm2 = TableManager(b2, TaskInfo("cj", 5, "op", 0, 1), 0)
            await tm2.open({"tk": time_key_table("tk")})
            t2 = await tm2.get_table("tk")
            return sorted(
                v for bt in t2.all_batches() for v in bt.column(0).to_pylist()
            )

        assert asyncio.run(restore()) == [1, 2, 3, 4, 5, 6, 7, 8]
