"""Protobuf decoding/encoding via dynamic messages.

Capability parity with the reference's prost-reflect path
(/root/reference/crates/arroyo-formats/src/proto/* for decode and
ser.rs protobuf encode): a compiled FileDescriptorSet (bytes of
`protoc --descriptor_set_out`) + message name produce a dynamic message
class; fields map to columns by name in both directions.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


def message_class(descriptor: Optional[dict]):
    """Dynamic message class from {'descriptor_set': bytes,
    'message_name': str} (shared by decoder and encoder)."""
    if not descriptor or "descriptor_set" not in descriptor:
        raise ValueError(
            "protobuf format requires protobuf.descriptor_set (bytes of a "
            "compiled FileDescriptorSet) and protobuf.message_name"
        )
    from google.protobuf import (
        descriptor_pb2,
        descriptor_pool,
        message_factory,
    )

    fds = descriptor_pb2.FileDescriptorSet()
    ds = descriptor["descriptor_set"]
    if isinstance(ds, str):
        ds = bytes.fromhex(ds)
    fds.ParseFromString(ds)
    pool = descriptor_pool.DescriptorPool()
    for f in fds.file:
        pool.Add(f)
    desc = pool.FindMessageTypeByName(descriptor["message_name"])
    return message_factory.GetMessageClass(desc)


def _is_repeated(field) -> bool:
    if hasattr(field, "is_repeated"):
        return field.is_repeated
    return field.label == field.LABEL_REPEATED


def _msg_to_dict(msg) -> Dict[str, Any]:
    """Structured decode: nested/repeated messages become dicts/lists so a
    proto source piped to a proto sink round-trips losslessly."""
    out: Dict[str, Any] = {}
    for field in msg.DESCRIPTOR.fields:
        v = getattr(msg, field.name)
        if _is_repeated(field):
            if field.type == field.TYPE_MESSAGE:
                out[field.name] = [_msg_to_dict(m) for m in v]
            else:
                out[field.name] = list(v)
        elif field.type == field.TYPE_MESSAGE:
            # proto3 message fields have explicit presence: unset -> NULL
            # (not a struct of zero-defaults), and re-encoding must not
            # mark the field present
            out[field.name] = (
                _msg_to_dict(v) if msg.HasField(field.name) else None
            )
        else:
            out[field.name] = v
    return out


class ProtoDecoder:
    def __init__(self, descriptor: Optional[dict]):
        self.cls = message_class(descriptor)

    def decode(self, record: bytes) -> Dict[str, Any]:
        msg = self.cls()
        msg.ParseFromString(record)
        return _msg_to_dict(msg)


def _coerce_scalar(field, v):
    """Column value -> settable proto scalar. Arrow timestamps surface as
    datetime.datetime; int proto fields get exact epoch nanos."""
    import datetime

    if isinstance(v, datetime.datetime):
        if field.type == field.TYPE_STRING:
            return v.isoformat()
        if v.tzinfo is None:
            v = v.replace(tzinfo=datetime.timezone.utc)
        delta = v - datetime.datetime(
            1970, 1, 1, tzinfo=datetime.timezone.utc
        )
        return ((delta.days * 86400 + delta.seconds) * 10**9
                + delta.microseconds * 1000)
    if hasattr(v, "value") and not isinstance(
        v, (int, float, str, bytes, bool)
    ):
        return v.value  # pandas Timestamp -> epoch nanos
    if field.type == field.TYPE_STRING and not isinstance(v, str):
        return str(v)
    return v


def _fill(msg, row: Dict[str, Any]):
    for field in msg.DESCRIPTOR.fields:
        v = row.get(field.name)
        if v is None:
            continue
        if field.type == field.TYPE_MESSAGE:
            if _is_repeated(field):
                container = getattr(msg, field.name)
                for item in v:
                    if isinstance(item, dict):
                        _fill(container.add(), item)
            elif isinstance(v, dict):
                _fill(getattr(msg, field.name), v)
        elif _is_repeated(field):
            getattr(msg, field.name).extend(
                _coerce_scalar(field, x) for x in v
            )
        else:
            setattr(msg, field.name, _coerce_scalar(field, v))


class ProtoEncoder:
    def __init__(self, descriptor: Optional[dict]):
        self.cls = message_class(descriptor)

    def encode(self, row: Dict[str, Any]) -> bytes:
        msg = self.cls()
        _fill(msg, row)
        return msg.SerializeToString()
