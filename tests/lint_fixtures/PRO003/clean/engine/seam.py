"""Must NOT fire PRO003: only registered literals fired."""
from .. import chaos


def pump():
    if chaos.fire("network.drop"):
        raise ConnectionError("injected")
