"""Phoenix: hot-standby generations + task-local recovery (ISSUE 17).

Sub-second failover for durable jobs on a shared pool: the controller
keeps a WARM standby incarnation per job — staged via the PR 15
`StartExecution{staged}` path, restored at arm time, then continuously
re-restored by tailing each published epoch's delta chains (PR 8) instead
of full restores. On heartbeat loss (or a task failure while RUNNING) the
standby is PROMOTED in place of a cold recovery: a fresh generation is
claimed (fencing the possibly-merely-slow primary), the standby catches
up to the latest published manifest, and its runners start processing —
no SCHEDULING pass, no worker acquisition, no cold restore.

The promotion protocol is modeled first (analysis/model): the
`promote_while_primary_alive` mutant shows why promotion must re-resolve
the LATEST published manifest at claim time rather than trusting the
standby's tailed epoch — a blacked-out primary may have published and
committed a later epoch, and promoting behind it re-emits visible output
(the generalized `overlap_double_emission` violation).

Task-local recovery rides along in `state/chain_cache.py`: workers keep
their last flushed chain blobs in process memory so a same-worker restart
or tailing standby skips the storage round-trip.
"""

from .manager import StandbyManager

__all__ = ["StandbyManager"]
