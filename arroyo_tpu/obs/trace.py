"""Flight-recorder trace spans: cross-process causal tracing primitives.

Dapper-style spans (Sigelman et al. 2010) layered over the engine's
aligned-snapshot checkpoints (Carbone et al. 2015): the controller mints
one trace per checkpoint epoch / job lifecycle event, and the trace
context — a (trace_id, span_id) pair — propagates through the gRPC-analog
control plane (`__trace__` message key), ControlMsg barriers
(CheckpointBarrier.trace_id/span_id), and the TCP Arrow-IPC data plane
(frame headers carry a send timestamp on every frame plus a sampled trace
preamble), so controller → worker → operator runner → state storage
stitch into one tree across processes.

Spans land in a bounded per-process ring buffer (`TraceRecorder`) on
finish; exports are Chrome trace-event JSON (Perfetto-loadable) via
`chrome_trace()`. Everything is a no-op when `obs.enabled` is off or no
trace context is active, so the hot path pays one contextvar read.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# the active trace context: (trace_id, span_id) or None
_CTX: contextvars.ContextVar[Optional[Tuple[str, str]]] = contextvars.ContextVar(
    "arroyo_trace_ctx", default=None
)


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def new_trace(*parts) -> str:
    """Canonical trace id: '/'-joined parts, job id first, so per-job
    exports can filter on the `{job_id}/` prefix."""
    return "/".join(str(p) for p in parts)


class Span:
    """One timed operation. Use as a context manager (attaches the trace
    context for the dynamic extent) or finish() explicitly for async hops
    that outlive the creating frame."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "cat", "attrs",
        "events", "start_us", "end_us", "_token", "_finished",
    )

    def __init__(self, trace_id: str, span_id: str, parent_id: Optional[str],
                 name: str, cat: str, attrs: Dict[str, Any]):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.events: List[tuple] = []
        self.start_us = time.time() * 1e6
        self.end_us: Optional[float] = None
        self._token = None
        self._finished = False

    @property
    def recording(self) -> bool:
        return True

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs) -> None:
        self.events.append((time.time() * 1e6, name, attrs))

    def attach(self):
        """Make this span the ambient trace context (returns a token for
        detach). Used on async hops where `with` can't scope the extent."""
        return _CTX.set((self.trace_id, self.span_id))

    @staticmethod
    def detach(token) -> None:
        _CTX.reset(token)

    def finish(self, recorder: Optional["TraceRecorder"] = None) -> None:
        if self._finished:
            return
        self._finished = True
        self.end_us = time.time() * 1e6
        if recorder is None:
            from . import recorder as _get_recorder

            recorder = _get_recorder()
        recorder.record(self.to_dict())

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "cat": self.cat,
            "ts": self.start_us,
            "dur": (self.end_us or time.time() * 1e6) - self.start_us,
            "attrs": dict(self.attrs),
            "events": [
                {"ts": ts, "name": n, "attrs": a} for ts, n, a in self.events
            ],
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }

    def __enter__(self) -> "Span":
        self._token = self.attach()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            _CTX.reset(self._token)
            self._token = None
        if exc is not None:
            self.attrs["error"] = repr(exc)[:300]
        self.finish()


class _NullSpan:
    """Inert span: returned when tracing is disabled or no context is
    active, so call sites never branch."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = None
    recording = False

    def set(self, **attrs) -> "_NullSpan":
        return self

    def event(self, name: str, **attrs) -> None:
        pass

    def attach(self):
        return None

    @staticmethod
    def detach(token) -> None:
        pass

    def finish(self, recorder=None) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


NULL_SPAN = _NullSpan()


class TraceRecorder:
    """Bounded in-memory ring buffer of finished spans (oldest dropped
    first); thread-safe — storage spans finish from to_thread workers."""

    def __init__(self, capacity: int, role: str = ""):
        self.capacity = max(1, int(capacity))
        self.role = role or f"proc-{os.getpid()}"
        self.spans: deque = deque(maxlen=self.capacity)
        self.dropped = 0
        self._lock = threading.Lock()

    def record(self, span_dict: Dict[str, Any]) -> None:
        with self._lock:
            if len(self.spans) == self.capacity:
                self.dropped += 1
                dropped = True
            else:
                dropped = False
            span_dict.setdefault("role", self.role)
            self.spans.append(span_dict)
        if dropped:
            # exported as a real counter so sustained overflow is
            # alertable (watchtower trace_drops rule) instead of only
            # visible to someone reading /debug/trace at the right moment
            from ..metrics import TRACE_DROPPED_SPANS

            TRACE_DROPPED_SPANS.labels().inc()

    def snapshot(self, trace_prefix: Optional[str] = None,
                 trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            spans = list(self.spans)
        if trace_prefix is not None:
            spans = [s for s in spans
                     if s.get("trace_id", "").startswith(trace_prefix)]
        if trace_id is not None:
            spans = [s for s in spans if s.get("trace_id") == trace_id]
        return spans

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()
            self.dropped = 0

    def expunge_job(self, job_id: str) -> int:
        """Job-scoped GC: drop every span of one job (trace ids are
        '{job_id}/...'-prefixed by new_trace). Without this, a torn-down
        job's spans linger in the ring until overwrite — wired into the
        StopJob / Registry.drop_job metrics-GC path so trace exports of
        a multiplexed worker only show live tenants. Returns the number
        of spans removed."""
        prefix = f"{job_id}/"
        with self._lock:
            kept = [s for s in self.spans
                    if not s.get("trace_id", "").startswith(prefix)]
            removed = len(self.spans) - len(kept)
            self.spans.clear()
            self.spans.extend(kept)
        return removed

    def __len__(self) -> int:
        with self._lock:
            return len(self.spans)


def current() -> Optional[Tuple[str, str]]:
    """The ambient (trace_id, span_id), or None."""
    return _CTX.get()


def attach(trace_id: str, span_id: str):
    return _CTX.set((trace_id, span_id))


def detach(token) -> None:
    _CTX.reset(token)


def chrome_trace(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Spans → Chrome trace-event JSON (load in Perfetto / chrome://tracing).
    Complete spans become 'X' events; span events and instants become 'i'
    events; per-pid process_name metadata names each role."""
    events: List[Dict[str, Any]] = []
    roles: Dict[int, str] = {}
    for s in spans:
        pid = s.get("pid", 0)
        roles.setdefault(pid, s.get("role", str(pid)))
        args = {
            "trace_id": s.get("trace_id"),
            "span_id": s.get("span_id"),
            "parent_id": s.get("parent_id"),
            **(s.get("attrs") or {}),
        }
        if s.get("instant"):
            events.append({
                "name": s["name"], "cat": s.get("cat", "obs"), "ph": "i",
                "ts": s["ts"], "pid": pid, "tid": s.get("tid", 0),
                "s": "p", "args": args,
            })
            continue
        events.append({
            "name": s["name"], "cat": s.get("cat", "obs"), "ph": "X",
            "ts": s["ts"], "dur": max(0.0, s.get("dur") or 0.0),
            "pid": pid, "tid": s.get("tid", 0), "args": args,
        })
        for ev in s.get("events", []):
            events.append({
                "name": ev["name"], "cat": s.get("cat", "obs"), "ph": "i",
                "ts": ev["ts"], "pid": pid, "tid": s.get("tid", 0),
                "s": "t",
                "args": {"span_id": s.get("span_id"), **(ev.get("attrs") or {})},
            })
    for pid, role in roles.items():
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": role},
        })
    events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _phase_tid(job: str, phase: str) -> int:
    """Stable synthetic thread id for one (job, phase) ledger track —
    kept far above real thread idents' low range is impossible (idents
    are arbitrary), so phase tracks get their own namespace via a
    deterministic hash with bit 62 set: collisions with a real tid would
    merge a phase track into a span track."""
    h = 0
    for ch in f"{job}\x00{phase}":
        h = (h * 131 + ord(ch)) & 0x3FFFFFFFFFFFFFFF
    return h | (1 << 62)


def perfetto_trace(spans: List[Dict[str, Any]],
                   timeline: Optional[List[Dict[str, Any]]] = None,
                   job: Optional[str] = None) -> Dict[str, Any]:
    """Spans (+ the batch-phase ledger) as Perfetto-ready Chrome
    trace-event JSON. On top of `chrome_trace`:

    * each (job, phase) pair of the timeline ledger renders as its own
      named track ('X' events with thread_name metadata), so a q5
      checkpoint epoch or a rescale shows decode/dispatch/exchange/
      emit/flush as parallel swimlanes under the process;
    * `job` filters both spans (trace-id prefix) and ledger entries to
      one tenant.

    Served by `/debug/trace?fmt=perfetto`, the REST traces route, and
    `tools/trace_report.py --perfetto`."""
    if job is not None:
        prefix = f"{job}/"
        spans = [s for s in spans
                 if s.get("trace_id", "").startswith(prefix)]
    doc = chrome_trace(spans)
    events = doc["traceEvents"]
    if timeline is None:
        from . import timeline as _timeline

        timeline = _timeline.snapshot(job)
    elif job is not None:
        timeline = [e for e in timeline if e.get("job") == job]
    pid = os.getpid()
    named: set = set()
    for e in timeline:
        tid = _phase_tid(e.get("job", ""), e["phase"])
        if tid not in named:
            named.add(tid)
            jlabel = e.get("job") or "worker"
            events.append({
                "name": "thread_name", "ph": "M", "pid": e.get("pid", pid),
                "tid": tid,
                "args": {"name": f"{jlabel} · {e['phase']}"},
            })
        events.append({
            "name": f"phase.{e['phase']}", "cat": "phase", "ph": "X",
            "ts": e["ts"], "dur": max(0.0, e.get("dur") or 0.0),
            "pid": e.get("pid", pid), "tid": tid,
            "args": {"job": e.get("job", ""), "task": e.get("task", "")},
        })
    events.sort(key=lambda ev: (ev.get("ts", 0), ev.get("pid", 0)))
    doc["phaseCount"] = len(timeline)
    return doc
