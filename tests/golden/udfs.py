# Golden-suite UDFs, mirroring the reference's smoke-test functions
# (/root/reference/crates/arroyo-sql-testing/src/udfs.rs): a scalar UDF,
# an ordered async UDF, and UDAFs over the grouped values vector.
# Registered by the harness via `--udf=udfs.py` headers through
# arroyo_tpu.udf.registry.register_from_source.


@udf(pa.int64(), [pa.uint64()], name="double_negative")
def double_negative(xs):
    return -2 * xs.astype(np.int64)


@udf(pa.int64(), [pa.uint64()], name="async_double_negative")
async def async_double_negative(x):
    import asyncio

    await asyncio.sleep((int(x) % 20) / 1000.0)
    return -2 * int(x)


@udaf(pa.float64(), [pa.uint64()], name="my_median")
def my_median(values):
    vs = np.sort(values)
    mid = len(vs) // 2
    if len(vs) % 2 == 0:
        return (float(vs[mid]) + float(vs[mid - 1])) / 2.0
    return float(vs[mid])


@udaf(pa.float64(), [pa.uint64()], name="none_udf")
def none_udf(values):
    return None


@udaf(pa.uint64(), [pa.uint64(), pa.uint64()], name="max_product")
def max_product(first_arg, second_arg):
    return int(np.max(first_arg * second_arg))
