"""Must NOT fire RACE004: the only root that awaits under `_lock` is
also the only root that writes its guarded field — no concurrent writer
is shut out; the reader root releases the lock before awaiting."""
import asyncio

from arroyo_tpu.analysis.races import guarded_by


@guarded_by("_lock", "fired")
class Plan:
    def __init__(self):
        self.fired = []
        self._lock = None


class Driver:
    async def hold(self, plan):
        with plan._lock:
            await asyncio.sleep(0)
            plan.fired.append(1)

    async def reader(self, plan):
        with plan._lock:
            n = len(plan.fired)
        await asyncio.sleep(0)
        return n

    def start(self, plan):
        asyncio.ensure_future(self.hold(plan))
        asyncio.ensure_future(self.reader(plan))
