"""Parallelism policies: signals in, per-operator targets out.

Dhalion-style separation (Floratou et al., VLDB '17): the POLICY is a pure
function from observed signals to target parallelism — it holds no clock,
no actuation state, no job handles — while the manager (manager.py) owns
sampling, warmup/cooldown gating, and the stop-checkpoint actuation. That
split is what makes policies pluggable (the `Policy` protocol + registry
below) and offline-testable (sim.py replays rate traces through the same
decide() the live controller calls).

The built-in `ds2` policy is the DS2 rate-ratio algorithm (Kalavri et al.,
OSDI '18): propagate demanded rates along the DAG from the sources, size
each operator to ceil(demand / true_rate_per_instance), with guardrails:

  * utilization band: scale up only above `busy_high` (or under upstream
    backpressure), scale down only below `busy_low`;
  * saturation fallback: under sustained backpressure the measured rates
    are throttled lower bounds, so when the rate ratio alone says "hold",
    grow geometrically by `saturation_step` instead (Dhalion's
    symptom-driven diagnosis);
  * hysteresis dead band, per-step scale-factor cap, unconditional
    min/max clamps.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional, Protocol

from .signals import OperatorSignals


# source connectors whose offset state repartitions (split/merge at the
# checkpoint boundary — connectors/splits.py): the actuator can change
# their parallelism without gap or replay. Kafka's offsets are split-
# keyed too, but its partition count is broker-side and unknowable here,
# so it stays out of AUTOMATIC source scaling.
ELASTIC_SOURCE_CONNECTORS = frozenset({"impulse", "nexmark"})


@dataclasses.dataclass
class Topology:
    """The policy's view of the job DAG: node ids in topological order,
    upstream adjacency, current parallelism, and which nodes the actuator
    may scale. Sinks keep their planned parallelism (sink fan-in is
    externally constrained); sources are scalable exactly when their
    connector's split state repartitions (ISSUE 15 — impulse/nexmark
    offset splits subdivide at the checkpoint boundary, so DS2 source
    targets are actuable instead of refused)."""

    order: List[int]
    upstream: Dict[int, List[int]]
    current: Dict[int, int]
    scalable: Dict[int, bool]
    source: Dict[int, bool] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_graph(cls, graph) -> "Topology":
        nodes = graph.topo_order()

        def _scalable(n) -> bool:
            # sources: scalable iff the connector's offset state is
            # repartitionable (split elasticity); the policy additionally
            # gates actuation on autoscale.scale_sources
            if n.is_source:
                return (
                    n.head.config.get("connector")
                    in ELASTIC_SOURCE_CONNECTORS
                )
            if n.is_sink:
                return False
            # only nodes whose every input is KEY-partitioned are safe to
            # rescale: their state re-reads by key range on restore and
            # their shuffle re-partitions by the same hash. Unkeyed inputs
            # mean either a round-robin map (harmless but unobservable
            # benefit) or a global accumulator that MUST stay at its
            # planned parallelism — the planner encodes that constraint
            # only through the edge keys, so respect it
            in_edges = graph.in_edges(n.node_id)
            return bool(in_edges) and all(
                getattr(e.schema, "key_indices", None) for e in in_edges
            )

        return cls(
            order=[n.node_id for n in nodes],
            upstream={
                n.node_id: [e.src for e in graph.in_edges(n.node_id)]
                for n in nodes
            },
            current={n.node_id: n.parallelism for n in nodes},
            scalable={n.node_id: _scalable(n) for n in nodes},
            source={n.node_id: n.is_source for n in nodes},
        )


@dataclasses.dataclass
class PolicyDecision:
    """targets covers every node (unchanged ones at current parallelism);
    reasons explains each node that differs from current."""

    targets: Dict[int, int]
    reasons: Dict[int, str] = dataclasses.field(default_factory=dict)

    def changed(self, current: Dict[int, int]) -> Dict[int, int]:
        return {
            nid: p for nid, p in self.targets.items()
            if p != current.get(nid, p)
        }


class Policy(Protocol):
    """The pluggable decide step. Implementations must be pure: same
    (topology, signals, cfg) in, same decision out — the simulation
    harness and the convergence tests rely on it."""

    def decide(self, topo: Topology,
               signals: Dict[int, OperatorSignals],
               cfg) -> PolicyDecision:
        ...


class DS2Policy:
    """Rate-ratio propagation from the sources (module docstring)."""

    def decide(self, topo: Topology,
               signals: Dict[int, OperatorSignals],
               cfg) -> PolicyDecision:
        demand_out: Dict[int, float] = {}
        targets: Dict[int, int] = {}
        reasons: Dict[int, str] = {}

        def gate(nid: int, cur: int, target: int, reason: str) -> None:
            # hysteresis dead band, then per-step cap, then hard clamps
            # (clamps last and unconditional: min_parallelism must win)
            if target != cur and cur > 0 and (
                abs(target - cur) / cur <= cfg.hysteresis
            ):
                target, reason = cur, ""
            if target > cur:
                target = min(target, math.ceil(cur * cfg.scale_factor_cap))
            elif target < cur:
                target = max(target, max(1, math.floor(
                    cur / cfg.scale_factor_cap)))
            clamped = min(max(target, cfg.min_parallelism),
                          cfg.max_parallelism)
            if clamped != cur and not reason:
                reason = (
                    f"clamped to [{cfg.min_parallelism}, "
                    f"{cfg.max_parallelism}]: {cur} -> {clamped}"
                )
            targets[nid] = clamped
            if clamped != cur and reason:
                reasons[nid] = reason

        for nid in topo.order:
            sig = signals.get(nid)
            cur = topo.current.get(nid, 1)
            if not topo.upstream.get(nid):
                # sources seed the demand with their observed output
                demand_out[nid] = sig.output_rate if sig else 0.0
                if (sig is None
                        or not topo.scalable.get(nid, False)
                        or not getattr(cfg, "scale_sources", False)):
                    targets[nid] = cur
                    continue
                # source elasticity (ISSUE 15): a source has no upstream
                # demand to propagate, so size it from its own busy
                # ratio — generation/ingest time over wall time. Busy at
                # busy_high means the source cannot hold wall pace at
                # this parallelism (the split repartition makes the
                # target actuable); deep idleness consolidates splits
                # back toward the utilization band.
                busy = sig.busy_ratio if sig.busy_ratio is not None else 0.0
                if busy >= cfg.busy_high:
                    target = math.ceil(cur * cfg.saturation_step)
                    reason = (
                        f"source busy {busy:.2f} >= {cfg.busy_high}: "
                        f"{cur} -> {target}"
                    )
                elif busy <= cfg.busy_low and cur > 1:
                    target = max(1, math.ceil(
                        cur * busy / max(cfg.busy_high, 1e-9)))
                    reason = (
                        f"source busy {busy:.2f} <= {cfg.busy_low}: "
                        f"{cur} -> {target}"
                    )
                else:
                    target, reason = cur, ""
                gate(nid, cur, target, reason)
                continue
            if sig is None or not topo.scalable.get(nid, False):
                # unscalable/unobserved nodes pass demand through
                targets[nid] = cur
                demand_out[nid] = sig.output_rate if sig else 0.0
                continue
            demand_in = sum(demand_out.get(u, 0.0) for u in topo.upstream[nid])
            bp_in = max(
                (signals[u].backpressure for u in topo.upstream[nid]
                 if u in signals),
                default=0.0,
            )
            busy = sig.busy_ratio if sig.busy_ratio is not None else 0.0
            cap = sig.true_rate_per_instance
            rate_target = (
                max(1, math.ceil(demand_in / cap)) if cap and cap > 0 else cur
            )
            if bp_in > cfg.backpressure_high and rate_target <= cur:
                # saturated: measured demand is throttled by the very
                # backpressure we're reacting to — grow geometrically
                target = math.ceil(cur * cfg.saturation_step)
                reason = (
                    f"backpressure {bp_in:.2f} with throttled rates: "
                    f"saturation step {cur} -> {target}"
                )
            elif rate_target > cur and (busy >= cfg.busy_high
                                        or bp_in > cfg.backpressure_high):
                target = rate_target
                reason = (
                    f"demand {demand_in:.0f}/s over capacity "
                    f"{(cap or 0) * cur:.0f}/s: {cur} -> {target}"
                )
            elif rate_target < cur and busy <= cfg.busy_low:
                target = rate_target
                reason = (
                    f"busy {busy:.2f} under {cfg.busy_low}: "
                    f"{cur} -> {target}"
                )
            else:
                target, reason = cur, ""
            gate(nid, cur, target, reason)
            # demand the downstream sees if this operator were scaled to
            # keep up: its full input demand times its selectivity
            demand_out[nid] = demand_in * sig.selectivity
        return PolicyDecision(targets=targets, reasons=reasons)


class ActuationGate:
    """Warmup/cooldown/pin gating between decide and actuate — shared by
    the live manager and the simulation so convergence tests exercise the
    exact actuation cadence the controller runs."""

    def __init__(self, cfg):
        self.warmup_left = cfg.warmup_periods
        self.cooldown_left = 0
        self.cooldown_periods = cfg.cooldown_periods

    def check(self, changed: Dict[int, int], pinned: bool = False) -> str:
        """Returns the action for this period: 'rescale' means actuate
        `changed` now (and starts the cooldown)."""
        if self.warmup_left > 0:
            self.warmup_left -= 1
            return "warmup"
        if pinned:
            return "pinned"
        if self.cooldown_left > 0:
            self.cooldown_left -= 1
            return "cooldown"
        if not changed:
            return "hold"
        self.cooldown_left = self.cooldown_periods
        return "rescale"

    def reset(self, warmup_periods: int) -> None:
        """A (re)schedule invalidates rate history: warm up again."""
        self.warmup_left = warmup_periods
        self.cooldown_left = 0


_POLICIES: Dict[str, Callable[[], Policy]] = {"ds2": DS2Policy}


def register_policy(name: str, factory: Callable[[], Policy]) -> None:
    _POLICIES[name] = factory


def make_policy(name: str) -> Policy:
    if name not in _POLICIES:
        raise ValueError(
            f"unknown autoscale policy {name!r}; known: {sorted(_POLICIES)}"
        )
    return _POLICIES[name]()
