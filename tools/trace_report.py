#!/usr/bin/env python3
"""Flight-recorder trace merger / summarizer (ISSUE 4, extended by the
ISSUE 11 fleet observatory).

Merge Chrome trace-event JSON dumps from multiple processes (each
worker's and the controller's `/debug/trace`, or the REST
`/api/v1/jobs/{id}/traces`) into one Perfetto-loadable file, and print a
per-trace tree summary (span counts, phase durations, orphaned spans,
chaos fire events).

Usage:
  python tools/trace_report.py dump1.json dump2.json --out merged.json
  python tools/trace_report.py merged.json --summarize
  python tools/trace_report.py merged.json --job job7 --out job7.json
  python tools/trace_report.py merged.json --doctor job7
  python tools/trace_report.py --golden-ft --perfetto --out ft.json
  python tools/trace_report.py audit.json reports.json --audit

--golden-ft runs the golden windowed-aggregate fault-tolerance cycle
(embedded cluster, seeded chaos faults, recovery from checkpoints) and
writes its flight recording — CI uploads this on red runs; with
--perfetto the recording additionally carries the batch-phase timeline
ledger as named per-(job, phase) tracks. --job filters any operation to
one tenant's events; --doctor renders the bottleneck-doctor verdict
OFFLINE from a dump (phase.* events reconstruct the signals), so a CI
artifact is enough to name the limiting factor after the fact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Dict, List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def load_events(paths: List[str]) -> List[dict]:
    events: List[dict] = []
    seen = set()
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        for ev in doc.get("traceEvents", []):
            # dedupe spans that appear in several dumps (same span_id);
            # metadata and instant events without ids always pass through
            sid = (ev.get("args") or {}).get("span_id")
            key = (sid, ev.get("ts")) if sid else None
            if key is not None:
                if key in seen:
                    continue
                seen.add(key)
            events.append(ev)
    events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
    return events


def merge(paths: List[str]) -> dict:
    return {"traceEvents": load_events(paths), "displayTimeUnit": "ms"}


def filter_job(events: List[dict], job_id: str) -> List[dict]:
    """One tenant's events: spans by `{job_id}/` trace-id prefix, phase
    ledger entries by their `job` arg, metadata rows kept (they name
    tracks)."""
    prefix = f"{job_id}/"
    out = []
    for ev in events:
        if ev.get("ph") == "M":
            out.append(ev)
            continue
        args = ev.get("args") or {}
        tid = args.get("trace_id") or ""
        if tid.startswith(prefix) or args.get("job") == job_id:
            out.append(ev)
    return out


def group_traces(events: List[dict]) -> Dict[str, List[dict]]:
    """trace_id -> complete spans (ph == 'X')."""
    out: Dict[str, List[dict]] = defaultdict(list)
    for ev in events:
        if ev.get("ph") != "X":
            continue
        tid = (ev.get("args") or {}).get("trace_id")
        if tid:
            out[tid].append(ev)
    return out


def tree_stats(spans: List[dict]) -> dict:
    """Connectivity + duration stats for one trace's spans."""
    by_id = {(s.get("args") or {}).get("span_id"): s for s in spans}
    roots, orphans = [], []
    for s in spans:
        parent = (s.get("args") or {}).get("parent_id")
        if parent is None:
            roots.append(s)
        elif parent not in by_id:
            orphans.append(s)
    by_cat: Dict[str, float] = defaultdict(float)
    for s in spans:
        by_cat[s.get("cat", "?")] += s.get("dur", 0.0)
    slowest = sorted(spans, key=lambda s: -s.get("dur", 0.0))[:5]
    return {
        "spans": len(spans),
        "roots": [s["name"] for s in roots],
        "orphans": len(orphans),
        "connected": len(roots) == 1 and not orphans,
        "duration_ms": round(
            max(s.get("dur", 0.0) for s in roots) / 1e3, 3
        ) if roots else None,
        "by_cat_ms": {k: round(v / 1e3, 3) for k, v in sorted(by_cat.items())},
        "slowest": [
            {"name": s["name"], "dur_ms": round(s.get("dur", 0.0) / 1e3, 3)}
            for s in slowest
        ],
    }


def summarize(events: List[dict], out=sys.stdout) -> None:
    chaos_fires = [
        ev for ev in events
        if ev.get("ph") == "i" and ev.get("name", "").startswith("chaos.fire")
    ]
    traces = group_traces(events)
    print(f"{len(events)} events, {len(traces)} traces, "
          f"{len(chaos_fires)} chaos fires", file=out)
    for tid in sorted(traces):
        st = tree_stats(traces[tid])
        flag = "tree" if st["connected"] else (
            f"{len(st['roots'])} roots, {st['orphans']} orphans"
        )
        print(f"\n== {tid} [{flag}] {st['spans']} spans, "
              f"{st['duration_ms']} ms", file=out)
        print(f"   by cat: {st['by_cat_ms']}", file=out)
        for s in st["slowest"]:
            print(f"   slow: {s['name']} {s['dur_ms']} ms", file=out)
    for ev in chaos_fires:
        print(f"\nchaos: {ev['name']} @ {ev.get('ts')} "
              f"{ev.get('args')}", file=out)


def latency_summary(report: dict, out=sys.stdout) -> None:
    """Pretty-print one /debug/latency (or REST /jobs/{id}/latency) dump:
    per-operator + end-to-end marker quantiles, per-program XLA compile/
    dispatch stats, padding waste per rung, and the recompile-cause log."""

    def series(title, rows):
        print(f"\n== {title}", file=out)
        if not rows:
            print("   (no samples)", file=out)
            return
        for r in rows:
            qs = " ".join(
                f"{q}={r[f'{q}_ms']}ms" for q in ("p50", "p95", "p99")
                if f"{q}_ms" in r
            )
            print(f"   {r.get('job')}/{r.get('task')}: "
                  f"n={r['samples']} mean={r['mean_ms']}ms {qs}", file=out)

    series("operator latency (marker transit source->operator)",
           report.get("operators", []))
    series("end-to-end latency (marker transit source->sink)",
           report.get("end_to_end", []))
    dev = report.get("device", {})
    progs = dev.get("programs", {})
    if progs:
        print("\n== device programs", file=out)
        for name, p in sorted(progs.items()):
            dq = p.get("dispatch_quantiles", {})
            print(f"   {name}: compiles={p.get('compiles', 0)} "
                  f"compile_s={p.get('compile_s_total', 0)} "
                  f"dispatches={p.get('dispatches', 0)} "
                  f"dispatch_p95={dq.get('p95', 'n/a')}s "
                  f"cache={p.get('cache_hit', 0)}h/"
                  f"{p.get('cache_miss', 0)}m", file=out)
    waste = [w for w in dev.get("padding_waste", []) if w.get("waste")]
    if waste:
        print("\n== padding waste (last dispatch per program/rung)",
              file=out)
        for w in waste:
            print(f"   {w['program']} rung={w['rung']}: "
                  f"{100.0 * w['waste']:.1f}%", file=out)
    recompiles = dev.get("recompiles", [])
    if recompiles:
        print(f"\n== recompile causes ({len(recompiles)})", file=out)
        for r in recompiles[-20:]:
            print(f"   {r['program']} #{r['nth_compile']} [{r['cause']}] "
                  f"rung={r['rung']} {r['compile_s']}s sig={r['signature']}",
                  file=out)


def doctor_summary(events: List[dict], job_id: str, out=sys.stdout) -> int:
    """Offline bottleneck doctor: reconstruct signals from a dump's
    phase.* events and render the ranked verdict. Returns 0 when a
    verdict could be produced, 1 when the dump carries no phase ledger
    for the job (nothing to diagnose)."""
    from arroyo_tpu.obs import doctor

    sig = doctor.signals_from_trace(events, job_id)
    if not sig["phases"] and not sig["neighbors"]:
        print(f"no phase-ledger events for job {job_id!r} in the dump "
              "(export with fmt=perfetto / --perfetto)", file=out)
        return 1
    rep = doctor.diagnose(sig)
    v = rep["verdict"]
    print(f"== doctor: {job_id}", file=out)
    print(f"   verdict: {v['cause']} (score {v['score']}, confidence "
          f"{v['confidence']})", file=out)
    if v.get("suspect"):
        print(f"   suspect: {v['suspect']}", file=out)
    print(f"   {v['detail']}", file=out)
    for r in rep["ranked"]:
        print(f"   {r['cause']:<15} {r['score']}", file=out)
    print(f"   busy_ratio={sig['busy_ratio']} window_s={sig['window_s']} "
          f"loop_lag_ms_p99={sig['loop_lag_ms_p99']}", file=out)
    if sig["phases"]:
        print("   phases: " + " ".join(
            f"{p}={s:.4f}s" for p, s in sorted(sig["phases"].items())
        ), file=out)
    for n in sig["neighbors"][:5]:
        print(f"   neighbor {n['job']}: busy={n['busy_s']}s", file=out)
    return 0


def audit_report(paths: List[str], out=sys.stdout) -> int:
    """Offline conservation reconciliation (ISSUE 19). Accepts two
    artifact shapes per input file:

      * a `/debug/audit` (or `GET /api/v1/jobs/{id}/audit`) payload —
        the reconciler's own status, rendered as-is;
      * a raw checkpoint-report dump (a JSON list of
        {job_id, task_id, epoch, audit} dicts, in arrival order) —
        REPLAYED through a fresh Reconciler, so a CI artifact of the
        reports is enough to re-derive the breach verdict after the
        fact, intake fencing included.

    Prints a per-edge attestation table per job and points at the first
    divergence. Returns 1 when any breach is present, 0 when the ledger
    is clean."""
    from arroyo_tpu.obs import audit as audit_mod

    jobs: Dict[str, dict] = {}
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        if isinstance(doc, list):
            job_id = next(
                (r.get("job_id") for r in doc if r.get("job_id")),
                os.path.basename(p),
            )
            rec = audit_mod.Reconciler(job_id)
            # replay in arrival order: an epoch reconciles (and becomes
            # the published horizon) once a later epoch starts reporting,
            # which is exactly when the controller's pipelined publish
            # would have sealed it
            pending: Dict[int, Dict[str, dict]] = {}
            published = 0
            for r in doc:
                if r.get("audit") is None:
                    continue
                epoch = int(r["epoch"])
                for done in sorted(e for e in pending if e < epoch):
                    rec.reconcile(done, {
                        t: rr.get("audit")
                        for t, rr in pending.pop(done).items()
                    })
                    published = max(published, done)
                if rec.intake(r.get("task_id", "?"), epoch, r["audit"],
                              published or None):
                    continue
                pending.setdefault(epoch, {})[r.get("task_id", "?")] = r
            for done in sorted(pending):
                rec.reconcile(done, {
                    t: rr.get("audit") for t, rr in pending[done].items()
                })
            jobs[job_id] = rec.status()
        elif "jobs" in doc:
            jobs.update(doc["jobs"])
        elif doc.get("job"):
            jobs[doc["job"]] = doc
    if not jobs:
        print("no audit payloads found in the inputs", file=out)
        return 1
    breached = False
    for job_id, st in sorted(jobs.items()):
        print(f"== audit: {job_id}", file=out)
        print(f"   incarnation={st.get('incarnation')} "
              f"epochs_reconciled={st.get('epochs_reconciled', 0)} "
              f"edges_verified={st.get('edges_verified', 0)} "
              f"rows_attested={st.get('rows_attested', 0)}", file=out)
        edges = st.get("edges") or {}
        if edges:
            print(f"   {'edge':<24} {'epoch':>5} "
                  f"{'tx rows':>8} {'rx rows':>8}  digest ok", file=out)
            for edge, v in sorted(edges.items()):
                tx, rx = v.get("tx") or [0, 0], v.get("rx") or [0, 0]
                print(f"   {edge:<24} {v.get('epoch', 0):>5} "
                      f"{tx[0]:>8} {rx[0]:>8}  "
                      f"{'ok' if v.get('ok') else 'DIVERGED'}", file=out)
        breaches = st.get("breaches") or []
        if breaches:
            breached = True
            first = min(breaches, key=lambda b: (b.get("epoch", 0),
                                                 b.get("ts", 0)))
            print(f"   BREACHES ({len(breaches)}):", file=out)
            for b in breaches:
                print(f"     [{b.get('kind')}] edge={b.get('edge')} "
                      f"epoch={b.get('epoch')}: {b.get('detail')}",
                      file=out)
            print(f"   first divergence: epoch {first.get('epoch')} "
                  f"edge {first.get('edge')} [{first.get('kind')}]",
                  file=out)
        else:
            print("   conservation ledger clean", file=out)
    return 1 if breached else 0


def run_golden_ft(out_path: str, perfetto: bool = False) -> int:
    """Run the golden windowed-agg fault-tolerance cycle (embedded
    cluster + seeded faults + recovery) and write its flight recording.
    Returns 0 when the drill passed AND the checkpoint traces recorded."""
    from arroyo_tpu import obs
    from arroyo_tpu.chaos import drill

    import tempfile

    obs.reset()
    with tempfile.TemporaryDirectory() as tmp:
        res = drill.run_drill(
            drill.DEFAULT_DRILL_QUERIES[0], seed=20260804, workdir=tmp,
            plan_factory=drill.fast_plan, throttle=400.0,
        )
    spans = obs.recorder().snapshot()
    doc = obs.perfetto_trace(spans) if perfetto else obs.chrome_trace(spans)
    doc["drill"] = {"passed": res.passed, "error": res.error,
                    "restarts": res.restarts,
                    "fired": res.comparable_log}
    with open(out_path, "w") as f:
        json.dump(doc, f)
    print(f"golden FT cycle: passed={res.passed} restarts={res.restarts} "
          f"spans={len(spans)} -> {out_path}")
    summarize(doc["traceEvents"])
    return 0 if res.passed and spans else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("inputs", nargs="*", help="Chrome trace JSON dumps")
    ap.add_argument("--out", help="write the merged trace JSON here")
    ap.add_argument("--summarize", action="store_true",
                    help="print per-trace tree summaries")
    ap.add_argument("--golden-ft", action="store_true",
                    help="run the golden fault-tolerance cycle and dump "
                         "its flight recording (requires --out)")
    ap.add_argument("--latency", action="store_true",
                    help="treat inputs as /debug/latency dumps and print "
                         "the device-tier observatory summary")
    ap.add_argument("--job", help="filter every operation to one job's "
                                  "events (spans by trace-id prefix, "
                                  "phase entries by their job arg)")
    ap.add_argument("--perfetto", action="store_true",
                    help="with --golden-ft: include the batch-phase "
                         "timeline ledger in the recording (named "
                         "per-(job, phase) tracks)")
    ap.add_argument("--doctor", metavar="JOB",
                    help="render the bottleneck-doctor verdict OFFLINE "
                         "from the input dumps' phase-ledger events")
    ap.add_argument("--audit", action="store_true",
                    help="treat inputs as conservation-ledger artifacts "
                         "(/debug/audit payloads or raw checkpoint-report "
                         "dumps) and reconcile them offline: per-edge "
                         "attestation table + first-divergence pointer")
    args = ap.parse_args(argv)
    if args.golden_ft:
        if not args.out:
            ap.error("--golden-ft requires --out")
        return run_golden_ft(args.out, perfetto=args.perfetto)
    if args.latency:
        if not args.inputs:
            ap.error("no latency dumps given")
        for p in args.inputs:
            with open(p) as f:
                report = json.load(f)
            print(f"--- {p}")
            latency_summary(report)
        return 0
    if not args.inputs:
        ap.error("no input dumps given")
    if args.audit:
        return audit_report(args.inputs)
    doc = merge(args.inputs)
    if args.job:
        doc["traceEvents"] = filter_job(doc["traceEvents"], args.job)
    if args.doctor:
        return doctor_summary(doc["traceEvents"], args.doctor)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f)
        print(f"merged {len(args.inputs)} dumps "
              f"({len(doc['traceEvents'])} events) -> {args.out}")
    if args.summarize or not args.out:
        summarize(doc["traceEvents"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
