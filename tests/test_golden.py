"""Golden-query smoke harness.

Capability parity with the reference's smoke-test strategy
(/root/reference/crates/arroyo-sql-testing/src/smoke_tests.rs): one test
per tests/golden/queries/*.sql; each query's sources/sinks use the
deterministic single_file connector over committed fixtures; outputs are
compared to committed golden files; and EVERY query is additionally run
through the fault-tolerance cycle — run with mid-stream checkpoints,
stop after epoch 3, restart from the checkpoint, and require output
identical to the uninterrupted run. Internal parallelism is forced to 2 so
shuffles and barrier alignment are exercised (reference
set_internal_parallelism, smoke_tests.rs:259).

Regenerate goldens (after intentional semantic changes):
    REGEN_GOLDEN=1 python -m pytest tests/test_golden.py
"""

import asyncio
import glob
import json
import os

import pytest

from arroyo_tpu.engine import Engine
from arroyo_tpu.sql import plan_query
from arroyo_tpu.sql.lexer import SqlError

HERE = os.path.dirname(os.path.abspath(__file__))
GOLDEN = os.path.join(HERE, "golden")
QUERIES = sorted(glob.glob(os.path.join(GOLDEN, "queries", "*.sql")))


def query_headers(path):
    """Leading `--key=value` comment lines (reference smoke_tests.rs
    parses the same headers out of its .sql files)."""
    headers = {}
    for line in open(path):
        line = line.strip()
        if not line.startswith("--") or "=" not in line:
            break
        k, v = line[2:].split("=", 1)
        headers[k.strip()] = v.strip()
    return headers


def register_query_udfs(headers):
    """`--udf=<file>` registers UDFs from tests/golden/<file> before
    planning (the reference links its smoke-test UDFs via udfs.rs)."""
    if "udf" in headers:
        from arroyo_tpu.udf import registry

        src = open(os.path.join(GOLDEN, headers["udf"])).read()
        registry.register_from_source(src)


def load_query(path, output_path, throttle=None):
    sql = open(path).read()
    sql = sql.replace("$input_dir", os.path.join(GOLDEN, "inputs"))
    sql = sql.replace("$output_path", output_path)
    if throttle:
        sql = sql.replace(
            "type = 'source'", f"type = 'source',\n  throttle_per_sec = '{throttle}'"
        )
    return sql


def read_rows(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def canonical(rows):
    return sorted(json.dumps(r, sort_keys=True, default=str) for r in rows)


def merge_debezium(rows, pk: list):
    """Replay debezium envelopes to final state keyed by pk (reference
    smoke_tests merge_debezium :519): the intermediate retract/append
    sequence is timing-dependent, the net state is not."""
    state = {}
    for env in rows:
        if env["op"] == "d":
            key = tuple(env["before"][c] for c in pk)
            state.pop(key, None)
        else:
            row = env["after"]
            state[tuple(row[c] for c in pk)] = row
    return [state[k] for k in sorted(state)]


def canonicalize_output(path, sql):
    rows = read_rows(path)
    if "debezium_json" in sql:
        pk = None
        for line in sql.splitlines():
            if line.strip().startswith("--pk="):
                pk = line.strip()[len("--pk="):].split(",")
        assert pk, "debezium golden queries need a --pk= header"
        return canonical(merge_debezium(rows, pk))
    return canonical(rows)


def run_full(sql, parallelism=2):
    plan = plan_query(sql, parallelism=parallelism)

    async def go():
        eng = Engine(plan.graph).start()
        await eng.join(120)

    asyncio.run(go())


def run_with_restore(sql_throttled, sql_fast, storage_url, job_id):
    """Run with 3 mid-stream checkpoints then stop; restart and finish."""

    async def phase1():
        plan = plan_query(sql_throttled, parallelism=2)
        eng = Engine(plan.graph, job_id=job_id, storage_url=storage_url).start()
        for epoch in range(1, 3):
            await asyncio.sleep(0.08)
            await eng.checkpoint_and_wait()
        await asyncio.sleep(0.08)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(120)

    asyncio.run(phase1())

    async def phase2():
        plan = plan_query(sql_fast, parallelism=2)
        eng = Engine(plan.graph, job_id=job_id, storage_url=storage_url).start()
        await eng.join(120)

    asyncio.run(phase2())


@pytest.mark.parametrize(
    "query_path", QUERIES, ids=[os.path.basename(q)[:-4] for q in QUERIES]
)
def test_golden_query(query_path, tmp_path):
    name = os.path.basename(query_path)[:-4]
    golden_path = os.path.join(GOLDEN, "golden_outputs", f"{name}.json")
    headers = query_headers(query_path)
    register_query_udfs(headers)

    if "fail" in headers:
        # error-message golden (reference smoke_tests.rs --fail= queries):
        # planning must reject the query with the documented message
        with pytest.raises(SqlError) as err:
            plan_query(load_query(query_path, str(tmp_path / "never.json")),
                       parallelism=2)
        assert headers["fail"] in str(err.value), (
            f"{name}: expected error containing {headers['fail']!r}, "
            f"got {err.value}"
        )
        return

    # 1. uninterrupted run
    out1 = str(tmp_path / "full.json")
    sql = load_query(query_path, out1)
    run_full(sql)
    full_rows = canonicalize_output(out1, sql)
    assert full_rows, f"{name} produced no output"

    if os.environ.get("REGEN_GOLDEN"):
        with open(golden_path, "w") as f:
            for line in full_rows:
                f.write(line + "\n")
    want = [line.strip() for line in open(golden_path)] if os.path.exists(
        golden_path
    ) else None
    assert want is not None, (
        f"no golden output for {name}; run with REGEN_GOLDEN=1"
    )
    assert full_rows == want, f"{name}: output diverged from golden"

    # 2. fault-tolerance cycle: checkpoint mid-stream, stop, restore
    out2 = str(tmp_path / "restored.json")
    run_with_restore(
        load_query(query_path, out2, throttle=2000),
        load_query(query_path, out2),
        storage_url=str(tmp_path / "ckpt"),
        job_id=f"golden-{name}",
    )
    restored_rows = canonicalize_output(out2, sql)
    assert restored_rows == want, (
        f"{name}: restored output differs from the uninterrupted run"
    )
