"""The shared-state annotation DSL the RACE00x rules and the dynamic
sanitizer key on.

Like ``@protocol_effect`` (analysis/model/effects.py) these decorators
are runtime no-ops — they only tag the class — but load-bearing
statically: ``races.callgraph`` extracts the declarations by AST (no
import of the annotated module needed), and the RACE00x rules analyze
ONLY declared fields, which is what keeps a name-heuristic
interprocedural analysis at zero false positives on the real tree.

    @shared_state("stop_requested", "pending_epochs",
                  multi_writer=("failure",))
    class JobHandle: ...

declares the listed attributes as shared mutable state reachable from
more than one task. The contract the rules enforce:

  * single-writer by default: a field written from >= 2 task-spawn
    roots must be listed in ``multi_writer`` (an explicit, reviewable
    acknowledgment that concurrent last-writer-wins stores are the
    design) or RACE001 fires;
  * no stale read-modify-write: any write whose value (or guarding
    read) crossed an ``await`` since the field was last read must
    revalidate first, or RACE002 fires — ``multi_writer`` does NOT
    waive this, lost updates are never the design.

    @guarded_by("_lock", "fired_events")
    class FaultPlan: ...

declares that ``self.fired_events`` may only be touched while holding
``self._lock`` (RACE003), and that holding ``self._lock`` across an
``await`` is a hazard when another root mutates its fields (RACE004).
``guarded_by`` fields are implicitly shared state.

When the dynamic sanitizer is enabled (``ARROYO_RACE_SANITIZER=1`` or
``sanitizer.enable()``), decorated classes additionally get
access-recording instrumentation for the declared fields; with it off,
decoration costs two class attributes and nothing per instance.
"""

from __future__ import annotations

from typing import Dict, Iterable, Tuple

SHARED_STATE_ATTR = "__shared_state__"
GUARDED_BY_ATTR = "__guarded_by__"

# every decorated class, in decoration order — the sanitizer's
# instrumentation registry (enable() may run after the classes loaded)
_DECORATED: list = []


def _check_names(names: Iterable[str], what: str) -> Tuple[str, ...]:
    out = tuple(names)
    for n in out:
        if not n or not isinstance(n, str):
            raise ValueError(f"{what} needs non-empty literal field names")
    return out


def shared_state(*fields: str, multi_writer: Tuple[str, ...] = ()):
    """Declare instance attributes as cross-task shared mutable state."""
    fields = _check_names(fields, "shared_state")
    multi_writer = _check_names(multi_writer, "multi_writer")
    unknown = set(multi_writer) - set(fields)
    if unknown:
        raise ValueError(
            f"multi_writer names not declared as fields: {sorted(unknown)}"
        )

    def deco(cls):
        decl: Dict[str, dict] = dict(cls.__dict__.get(SHARED_STATE_ATTR, {}))
        for f in fields:
            decl[f] = {"multi_writer": f in multi_writer}
        setattr(cls, SHARED_STATE_ATTR, decl)
        _register(cls)
        return cls

    return deco


def guarded_by(lock: str, *fields: str):
    """Declare that `fields` may only be accessed holding `self.<lock>`."""
    if not lock or not isinstance(lock, str):
        raise ValueError("guarded_by needs a non-empty literal lock name")
    fields = _check_names(fields, "guarded_by")
    if not fields:
        raise ValueError("guarded_by needs at least one guarded field")

    def deco(cls):
        guards: Dict[str, str] = dict(cls.__dict__.get(GUARDED_BY_ATTR, {}))
        decl: Dict[str, dict] = dict(cls.__dict__.get(SHARED_STATE_ATTR, {}))
        for f in fields:
            guards[f] = lock
            decl.setdefault(f, {"multi_writer": True})  # lock IS the policy
        setattr(cls, GUARDED_BY_ATTR, guards)
        setattr(cls, SHARED_STATE_ATTR, decl)
        _register(cls)
        return cls

    return deco


def _register(cls) -> None:
    if cls not in _DECORATED:
        _DECORATED.append(cls)
    # lazy import: annotations must stay importable with zero overhead;
    # the sanitizer only instruments when it is switched on
    from . import sanitizer

    if sanitizer.is_enabled():
        sanitizer.instrument_class(cls)


def decorated_classes() -> list:
    return list(_DECORATED)
