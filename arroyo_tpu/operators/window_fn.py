"""Placeholder: SQL window functions (ROW_NUMBER etc., reference
window_fn.rs) land with the window-function milestone."""
