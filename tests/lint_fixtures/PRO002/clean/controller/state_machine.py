"""Mini job state machine: every non-terminal state has outgoing moves."""
import enum


class JobState(enum.Enum):
    CREATED = "Created"
    RUNNING = "Running"
    STOPPED = "Stopped"
    FAILED = "Failed"

    def is_terminal(self):
        return self in (JobState.STOPPED, JobState.FAILED)


TRANSITIONS = {
    JobState.CREATED: {JobState.RUNNING, JobState.FAILED},
    JobState.RUNNING: {JobState.STOPPED, JobState.FAILED},
}
