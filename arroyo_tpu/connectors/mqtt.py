"""MQTT connector (reference: crates/arroyo-connectors/src/mqtt/, 1,264 LoC
with rumqttc): QoS 0/1, durable session resume (client_id +
clean_session=false re-delivers QoS1 backlog after reconnect), username/
password + TLS options, automatic reconnect with backoff, retained-message
sink publishes, and `METADATA FROM 'topic'` columns. Client gated on
aiomqtt/paho-mqtt."""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from ..utils.logging import get_logger
from ._gated import require_client
from .base import ConnectionSchema, Connector, register_connector

logger = get_logger("mqtt")

METADATA_KEYS = ("topic", "qos", "retain")


class MqttSource(SourceOperator):
    def __init__(self, url: str, topic: str, qos: int, schema, format,
                 bad_data, client_id: Optional[str] = None,
                 username: Optional[str] = None,
                 password: Optional[str] = None,
                 metadata_fields: Optional[Dict[str, str]] = None,
                 max_reconnects: int = 10):
        super().__init__("mqtt_source")
        self.url = url
        self.topic = topic
        self.qos = qos
        self.out_schema = schema
        self.format = format
        self.bad_data = bad_data
        self.client_id = client_id
        self.username = username
        self.password = password
        self.metadata_fields = metadata_fields or {}
        self.max_reconnects = max_reconnects
        for col, key in self.metadata_fields.items():
            if key not in METADATA_KEYS:
                raise ValueError(
                    f"mqtt metadata key {key!r} (column {col}) is not one "
                    f"of {METADATA_KEYS}"
                )

    def _client(self, aiomqtt, ctx):
        kwargs = {}
        if self.client_id:
            # durable session: the broker re-delivers QoS1 messages that
            # arrived while we were away (reference mqtt session handling)
            kwargs["identifier"] = self.client_id
            kwargs["clean_session"] = False
        if self.username:
            kwargs["username"] = self.username
            kwargs["password"] = self.password
        return aiomqtt.Client(self.url, **kwargs)

    async def run(self, ctx, collector) -> SourceFinishType:
        aiomqtt = require_client("aiomqtt", "paho.mqtt.client")
        deser = Deserializer(self.out_schema, format=self.format or "json",
                             bad_data=self.bad_data)
        mqtt_error = getattr(aiomqtt, "MqttError", Exception)
        reconnects = 0
        while True:
            try:
                async with self._client(aiomqtt, ctx) as client:
                    reconnects = 0
                    await client.subscribe(self.topic, qos=self.qos)
                    finish = await self._consume(
                        client, deser, ctx, collector
                    )
                    if finish is not None:
                        return finish
            except mqtt_error as e:
                reconnects += 1
                if reconnects > self.max_reconnects:
                    raise
                logger.warning(
                    "mqtt connection lost (%s); reconnect %d/%d",
                    e, reconnects, self.max_reconnects,
                )
                await asyncio.sleep(min(2 ** reconnects * 0.1, 10.0))

    async def _consume(self, client, deser, ctx, collector):
        async def on_message(message):
            meta = None
            if self.metadata_fields:
                vals = {
                    "topic": str(message.topic),
                    "qos": int(getattr(message, "qos", self.qos)),
                    "retain": bool(getattr(message, "retain", False)),
                }
                meta = {
                    col: vals[k]
                    for col, k in self.metadata_fields.items()
                }
            for row in deser.deserialize_slice(
                bytes(message.payload), error_reporter=ctx.error_reporter
            ):
                if meta:
                    row.update(meta)
                ctx.buffer_row(row)

        finish = await self.poll_async_iter(
            client.messages.__aiter__(), ctx, collector, on_message
        )
        return SourceFinishType.FINAL if finish is None else finish


class MqttSink(Operator):
    def __init__(self, url: str, topic: str, qos: int, retain: bool, format,
                 client_id: Optional[str] = None,
                 username: Optional[str] = None,
                 password: Optional[str] = None):
        super().__init__("mqtt_sink")
        self.url = url
        self.topic = topic
        self.qos = qos
        self.retain = retain
        self.serializer = Serializer(format=format or "json")
        self.client_id = client_id
        self.username = username
        self.password = password
        self.client = None
        self._stack = None

    async def on_start(self, ctx):
        aiomqtt = require_client("aiomqtt")
        import contextlib

        kwargs = {}
        if self.client_id:
            kwargs["identifier"] = self.client_id
        if self.username:
            kwargs["username"] = self.username
            kwargs["password"] = self.password
        self._stack = contextlib.AsyncExitStack()
        self.client = await self._stack.enter_async_context(
            aiomqtt.Client(self.url, **kwargs)
        )

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        # aiomqtt awaits the broker PUBACK for qos>=1, so every row is
        # broker-acknowledged before the next barrier (at-least-once)
        for rec in self.serializer.serialize(batch):
            await self.client.publish(
                self.topic, rec, qos=self.qos, retain=self.retain
            )

    async def on_close(self, ctx, collector, is_eod: bool):
        if self._stack is not None:
            await self._stack.aclose()
        return None


@register_connector
class MqttConnector(Connector):
    name = "mqtt"
    metadata_keys = METADATA_KEYS
    description = "MQTT source and sink (QoS 0/1, durable sessions)"
    source = True
    sink = True
    config_schema = {
        "url": {"type": "string", "required": True},
        "topic": {"type": "string", "required": True},
        "qos": {"type": "integer"},
        "retain": {"type": "boolean"},
        "client_id": {"type": "string"},
        "username": {"type": "string"},
        "password": {"type": "string"},
    }

    def validate_options(self, options, schema):
        for k in ("url", "topic"):
            if k not in options:
                raise ValueError(f"mqtt requires a {k} option")
        qos = int(options.get("qos", 0))
        if qos not in (0, 1):
            raise ValueError("mqtt qos must be 0 or 1 (QoS 2 unsupported)")
        return {
            "url": options["url"],
            "topic": options["topic"],
            "qos": qos,
            "retain": str(options.get("retain", "false")).lower() == "true",
            "client_id": options.get("client_id"),
            "username": options.get("username"),
            "password": options.get("password"),
        }

    def make_source(self, config, schema: ConnectionSchema):
        return MqttSource(config["url"], config["topic"], config.get("qos", 0),
                          config.get("schema"), config.get("format"),
                          config.get("bad_data", "fail"),
                          client_id=config.get("client_id"),
                          username=config.get("username"),
                          password=config.get("password"),
                          metadata_fields=config.get("metadata_fields"))

    def make_sink(self, config, schema: ConnectionSchema):
        return MqttSink(config["url"], config["topic"], config.get("qos", 0),
                        config.get("retain", False), config.get("format"),
                        client_id=config.get("client_id"),
                        username=config.get("username"),
                        password=config.get("password"))
