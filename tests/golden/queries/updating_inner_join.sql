--pk=left_count
CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE VIEW impulse_odd AS (
  SELECT counter FROM impulse WHERE counter % 2 == 1
);
CREATE TABLE output (left_count BIGINT, right_count BIGINT) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO output
SELECT A.counter, B.counter
FROM impulse A
JOIN impulse_odd B ON A.counter = B.counter;
