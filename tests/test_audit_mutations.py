"""Mutation harness for the conservation ledger (ISSUE 19 acceptance).

Each `audit.*` chaos seam injects exactly one conservation violation into
a live embedded cluster — a duplicated TCP data frame, a batch dropped
after sender attestation, a checkpoint report re-emitted for an epoch
behind the published one, a report stamped with a fenced generation —
and the reconciler must flag it with the CORRECT breach kind, edge, and
epoch, pulled from the chaos plan's fired log so the assertions name the
exact mutation site. The mutations corrupt accounting, not liveness: the
job itself must still FINISH under every one of them."""

import json
import os

import pytest

from arroyo_tpu import chaos
from arroyo_tpu.chaos import FaultPlan
from arroyo_tpu.chaos.drill import PIPELINE_DRILL_SQL, _run_embedded
from arroyo_tpu.obs import audit


@pytest.fixture(autouse=True)
def _clean(tmp_path):
    chaos.clear()
    audit.reset()
    yield
    chaos.clear()
    audit.reset()


def _write_src(tmp_path, n=2400):
    src = os.path.join(str(tmp_path), "in.json")
    with open(src, "w") as f:
        for i in range(n):
            mins, secs = (i // 1200) % 60, (i // 20) % 60
            f.write(json.dumps({
                "k": i % 64,
                "v": (i * 37) % 1000 + 1,
                "timestamp": f"2023-03-01T00:{mins:02d}:{secs:02d}."
                             f"{(i % 20) * 50:03d}Z",
            }) + "\n")
    return src


def _run_mutated(tmp_path, job_id, point, at_hits, params=None, n_workers=1):
    """Run the pipeline-drill query with a single scheduled mutation;
    return (fired_log, breaches_for_job). The source is throttled so the
    run spans many checkpoint epochs and the mutation lands mid-stream,
    well inside sealed attestations (not the unattested trailing
    segment). Raises if the job does not FINISH."""
    src = _write_src(tmp_path)
    out = os.path.join(str(tmp_path), "out.json")
    sql = PIPELINE_DRILL_SQL.replace("$src", src).replace("$out", out).format(
        throttle=",\n  throttle_per_sec = '1200'")
    plan = chaos.install(
        FaultPlan(1).add(point, at_hits=at_hits, params=params or {})
    )
    mark = audit.breach_mark()
    try:
        _run_embedded(
            sql, job_id, os.path.join(str(tmp_path), "ck"), n_workers, 2,
            max_restarts=0, heartbeat_interval=0.1, heartbeat_timeout=30.0,
            checkpoint_interval=0.15, timeout=120.0,
        )
    finally:
        fired = plan.fired_log()
        hits = plan.specs[0].hits
        chaos.clear()
    assert [e["point"] for e in fired] == [point], (
        f"mutation did not fire ({hits} hits observed): {fired}"
    )
    return fired[0], audit.breaches_since(mark, job_id)


def test_duplicated_remote_frame_is_flagged(tmp_path):
    """audit.dup_frame double-delivers one data frame past the TCP layer
    (needs 2 workers so edges actually cross the data plane): receiver
    attests more rows than the sender on exactly that edge."""
    fired, breaches = _run_mutated(
        tmp_path, "mut-dup", "audit.dup_frame", at_hits=(40,), n_workers=2,
    )
    assert breaches, "duplicated frame went unflagged"
    kinds = {b["kind"] for b in breaches}
    assert kinds == {"count_mismatch"}
    (b,) = breaches
    assert b["edge"] == fired["ctx"]["edge"]
    assert b["epoch"] >= 1
    assert "receiver" in b["detail"]


def test_dropped_batch_is_flagged(tmp_path):
    """audit.drop_batch swallows one batch AFTER the sender tap attested
    it: rows the sender swears it emitted never reach the receiver."""
    fired, breaches = _run_mutated(
        tmp_path, "mut-drop", "audit.drop_batch", at_hits=(30,),
    )
    assert breaches, "dropped batch went unflagged"
    kinds = {b["kind"] for b in breaches}
    assert kinds == {"count_mismatch"}
    (b,) = breaches
    assert b["edge"] == fired["ctx"]["edge"]
    assert b["epoch"] >= 1


def test_rewound_epoch_report_is_flagged(tmp_path):
    """audit.rewind_epoch re-emits a checkpoint report for an epoch
    strictly behind the published epoch — the source-rewind-behind-
    committed-output shape. Flagged with the stale epoch, not the live
    one."""
    fired, breaches = _run_mutated(
        tmp_path, "mut-rewind", "audit.rewind_epoch", at_hits=(48,),
        params={"back": 4},
    )
    assert breaches, "rewound epoch report went unflagged"
    kinds = {b["kind"] for b in breaches}
    assert kinds == {"rewind_behind_commit"}
    live_epoch = int(fired["ctx"]["epoch"])
    assert all(b["epoch"] == max(1, live_epoch - 4) for b in breaches)


def test_zombie_generation_report_is_flagged(tmp_path):
    """audit.zombie_append delivers an extra NEXT-epoch report stamped
    with the PREVIOUS data-plane generation: an old incarnation appending
    a new epoch past its fencing. Flagged at the epoch the zombie wrote
    into (one past the live report it rode in on)."""
    fired, breaches = _run_mutated(
        tmp_path, "mut-zombie", "audit.zombie_append", at_hits=(12,),
    )
    assert breaches, "zombie-generation report went unflagged"
    kinds = {b["kind"] for b in breaches}
    assert kinds == {"zombie_generation"}
    zombie_epoch = int(fired["ctx"]["epoch"]) + 1
    assert all(b["epoch"] == zombie_epoch for b in breaches)
    assert all("fenced generation" in b["detail"]
               or "mixed generations" in b["detail"] for b in breaches)
