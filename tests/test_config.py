import arroyo_tpu.config as cfg_mod
from arroyo_tpu.config import Config, load_config, parse_duration, parse_size, update


def test_defaults():
    c = Config()
    assert c.pipeline.source_batch_size == 512
    assert c.pipeline.checkpointing.interval == 10.0


def test_parse_duration_and_size():
    assert parse_duration("10ms") == 0.01
    assert parse_duration("5s") == 5.0
    assert parse_duration("2m") == 120.0
    assert parse_size("64KB") == 64_000
    assert parse_size("1MiB") == 2**20


def test_env_overrides():
    c = load_config(environ={
        "ARROYO__PIPELINE__SOURCE_BATCH_SIZE": "32",
        "ARROYO__PIPELINE__CHECKPOINTING__INTERVAL": "250ms",
        "ARROYO__TPU__ENABLED": "false",
    })
    assert c.pipeline.source_batch_size == 32
    assert c.pipeline.checkpointing.interval == 0.25
    assert c.tpu.enabled is False


def test_yaml_file(tmp_path):
    f = tmp_path / "arroyo.yaml"
    f.write_text("pipeline:\n  queue_size: 7\n  checkpointing:\n    interval: 1s\n")
    c = load_config(str(f), environ={})
    assert c.pipeline.queue_size == 7
    assert c.pipeline.checkpointing.interval == 1.0


def test_scoped_update():
    base = cfg_mod.config().pipeline.source_batch_size
    with update(pipeline={"source_batch_size": 9}):
        assert cfg_mod.config().pipeline.source_batch_size == 9
    assert cfg_mod.config().pipeline.source_batch_size == base
