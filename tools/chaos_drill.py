#!/usr/bin/env python
"""Run seeded exactly-once chaos drills against the embedded cluster.

    python tools/chaos_drill.py --list
        Enumerate every registered fault point (name, seam, effect).
        New injection seams MUST register here (arroyo_tpu/chaos/plan.py
        FAULT_POINTS); tests/test_chaos.py fails if a chaos.fire() call
        site and the registry ever disagree.

    python tools/chaos_drill.py --seed 20260804 --out CHAOS_DRILL.json
        The acceptance drill: for each golden query (default: one
        windowed aggregate, one join, one updating query) run fault-free,
        then under a seeded plan that SIGKILLs a worker mid-window, drops
        a data-plane connection, and fails a manifest CAS write; require
        byte-identical canonical sink output. Writes the results AND the
        fired-fault log to --out (commit it alongside the change).

    python tools/chaos_drill.py --fast
        The smoke drill the default test suite runs: 1 golden, 2 faults.

    python tools/chaos_drill.py --kafka
        Exactly-once through the transactional kafka sink (in-memory
        protocol-shaped fake broker) under worker kill + manifest CAS
        loss.

    python tools/chaos_drill.py --rescale
        Exactly-once through an AUTOSCALER-triggered rescale: a worker
        SIGKILL lands mid-rescale and a later rescale fails between its
        durable stop checkpoint and the reschedule; output must be
        byte-identical and the decision audit log is written next to
        the results.

    python tools/chaos_drill.py --failover
        ISSUE 17 acceptance: SIGKILL the primary under load with a hot
        standby armed and tailing; the standby must promote with zero
        cold restarts, sub-500ms gap (failover.promote span, recorded
        in the drill extras) and byte-identical output — then the
        standby-also-dies variant kills BOTH workers and requires the
        cold-restore fallback. With --plan, the serialized
        counterexample (e.g. promote_while_primary_alive's heartbeat
        blackout from tools/model_check.py --trace-dir) replays against
        the armed fleet: the standby promotes over an alive-but-silent
        primary and the fenced zombie must not double-emit.

    python tools/chaos_drill.py --plan COUNTEREXAMPLE.json
        Replay a model-checker counterexample (tools/model_check.py
        --trace-dir) — or any serialized FaultPlan — against the real
        embedded cluster: the golden drill runs under exactly that fault
        schedule. Accepts either a bare FaultPlan JSON or a
        counterexample payload with a "fault_plan" key. On fixed code
        the drill passes byte-identical; were the modeled bug live,
        this is the plan that demonstrates it end-to-end.

    python tools/chaos_drill.py --state-bloat
        ROADMAP item 4 acceptance: session state grows ~10x during the
        run, a worker is SIGKILLed mid-upload (storage latency widens
        the in-flight flush window), and the drill requires
        byte-identical output AND ~flat checkpoint capture time +
        delta byte RATE (bytes per second of epoch wall time) as state
        grows (<= 2x early-run medians;
        a full-snapshot design shows ~10x on both).

    python tools/chaos_drill.py --shared
        ISSUE 16 acceptance: two tenants whose scans fingerprint
        identically mount ONE shared host scan, a worker SIGKILL lands
        mid-checkpoint, and each tenant's output must be byte-identical
        to its own SOLO unshared fault-free run. With --plan, the
        serialized counterexample (e.g. the sharedplan model's
        leaked_barrier_across_tenants kill schedule from
        tools/model_check.py --shared --trace-dir) replays against the
        shared fleet instead of a golden query.

    python tools/chaos_drill.py --follower
        ISSUE 20 acceptance: a durable windowed pipeline with a
        follower read replica tailing its checkpoint stream, read
        continuously through the real serve gateway. Once reads route
        follower-first, the `replica.kill` seam drops the follower
        abruptly mid-tail: reads must fail over worker-ward with zero
        wrong values, the follower must reattach through the full
        _subscribe path (re-resolving latest.json — never an in-memory
        epoch), reads must come back follower-sourced, staleness stays
        <= 1 checkpoint interval throughout, and the sink output is
        byte-identical to the replica-off fault-free run.

    python tools/chaos_drill.py --starvation
        ROADMAP double-emit watch item: blocking `runner.stall` hits
        (params.block — a UDF that never yields) wedge one tenant's
        input loop and starve the shared event loop while heartbeat and
        checkpoint cadences are squeezed around the stall width, with
        the interleaving sanitizer (ARROYO_RACE_SANITIZER machinery)
        recording every shared-state access. Requires byte-identical
        output for BOTH tenants, no (key, window) row emitted twice,
        zero restarts, and a sanitizer-clean log; on failure the access
        log + Perfetto trace land in the workdir.

    python tools/chaos_drill.py --pipeline
        ISSUE 14 acceptance: a stateless chain fused into ONE segment
        with the two-deep staging pipeline on, worker SIGKILL lands
        while a batch is staged; requires byte-identical output vs the
        UNFUSED fault-free run AND runner.pipeline_drain evidence that
        a barrier actually drained a staged batch. (Every standard
        drill is also a fused-vs-unfused A/B: clean references run with
        segment fusion OFF, faulted runs keep the fused default.)
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# keep drills off any real accelerator and off the axon relay
os.environ.setdefault("JAX_PLATFORMS", "cpu")
for _var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
    os.environ.pop(_var, None)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--list", action="store_true",
                    help="enumerate registered fault points and exit")
    ap.add_argument("--seed", type=int, default=20260804)
    ap.add_argument("--queries", type=str, default="",
                    help="comma-separated golden query names")
    ap.add_argument("--fast", action="store_true",
                    help="smoke drill: 1 golden, 2 quickly-detected faults")
    ap.add_argument("--kafka", action="store_true",
                    help="also run the transactional-kafka exactly-once drill")
    ap.add_argument("--rescale", action="store_true",
                    help="also run the autoscaler-rescale drill: worker "
                    "kill mid-automatic-rescale + reschedule failure, "
                    "byte-identical output required")
    ap.add_argument("--state-bloat", action="store_true",
                    help="also run the state-bloat drill: 10x state "
                    "growth + SIGKILL mid-upload; requires byte-identical "
                    "output and ~flat capture time / delta bytes")
    ap.add_argument("--pipeline", action="store_true",
                    help="also run the fused-pipeline drill: a stateless "
                    "chain fused into one segment with two-deep staging, "
                    "SIGKILL mid-flight; requires byte-identical output "
                    "vs the UNFUSED clean run and proof that a barrier "
                    "drained a staged batch")
    ap.add_argument("--shared", action="store_true",
                    help="also run the shared-plan fleet drill: two "
                    "tenants mount ONE shared scan, a worker SIGKILL "
                    "lands mid-checkpoint; each tenant's output must be "
                    "byte-identical to its SOLO unshared run (with "
                    "--plan: the counterexample replays against the "
                    "shared fleet instead of a golden)")
    ap.add_argument("--failover", action="store_true",
                    help="also run the hot-standby failover drill: "
                    "SIGKILL the primary with a standby armed "
                    "(sub-500ms promotion, byte-identical output) plus "
                    "the standby-also-dies cold-restore fallback (with "
                    "--plan: replay the counterexample against the "
                    "armed fleet)")
    ap.add_argument("--follower", action="store_true",
                    help="also run the follower-replica drill: kill the "
                    "follower abruptly mid-tail via the replica.kill "
                    "seam; requires worker-ward failover with zero wrong "
                    "values, a full _subscribe reattach off latest.json, "
                    "staleness <= 1 checkpoint interval throughout, and "
                    "byte-identical sink output")
    ap.add_argument("--starvation", action="store_true",
                    help="also run the event-loop starvation drill: "
                    "blocking runner.stall hits on one tenant under "
                    "squeezed heartbeat/checkpoint cadences with the "
                    "race sanitizer recording shared-state accesses; "
                    "requires byte-identical output, no duplicated "
                    "(key, window) row, zero restarts, and a "
                    "sanitizer-clean interleaving log (ROADMAP "
                    "double-emit watch item)")
    ap.add_argument("--no-golden", action="store_true",
                    help="skip the golden-query drills; run only the "
                    "specialty drills selected by the other flags")
    ap.add_argument("--plan", type=str, default="",
                    help="run the drill under a serialized FaultPlan JSON "
                    "(bare plan or a model-check counterexample payload "
                    "with a 'fault_plan' key)")
    ap.add_argument("--out", type=str, default="",
                    help="write results + fired-fault log to this JSON file")
    ap.add_argument("--workdir", type=str, default="")
    args = ap.parse_args()

    from arroyo_tpu.chaos import FAULT_POINTS, FaultPlan
    from arroyo_tpu.chaos import drill as d

    if args.list:
        width = max(len(n) for n in FAULT_POINTS)
        for name in sorted(FAULT_POINTS):
            print(f"{name:<{width}}  {FAULT_POINTS[name]}")
        return 0

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos-drill-")
    if args.plan:
        with open(args.plan) as f:
            doc = json.load(f)
        plan_doc = doc.get("fault_plan", doc)  # payload or bare plan
        plan_text = json.dumps(plan_doc)
        trace = doc.get("trace", {})
        if trace:
            print(f"replaying counterexample: {trace.get('violation')} "
                  f"(mutant {trace.get('mutant') or 'none'}, "
                  f"{len(trace.get('events', []))} model events)")
        queries = [] if (args.shared or args.failover) else (
            [q for q in args.queries.split(",") if q.strip()]
            or [d.DEFAULT_DRILL_QUERIES[0]]
        )
        # a fresh plan per drill run: hit counters are stateful
        plan_factory = lambda seed: FaultPlan.from_json(plan_text)  # noqa: E731
    elif args.fast:
        queries = [d.DEFAULT_DRILL_QUERIES[0]]
        plan_factory = d.fast_plan
    else:
        queries = (
            [q for q in args.queries.split(",") if q.strip()]
            or list(d.DEFAULT_DRILL_QUERIES)
        )
        plan_factory = d.standard_plan

    results = [] if args.no_golden else d.run_drills(
        queries, args.seed, workdir, plan_factory=plan_factory)
    if args.kafka:
        results.append(
            d.run_kafka_drill(args.seed, os.path.join(workdir, "kafka"))
        )
    if args.rescale:
        results.append(
            d.run_rescale_drill(args.seed, os.path.join(workdir, "rescale"))
        )
    if args.state_bloat:
        results.append(
            d.run_state_bloat_drill(
                args.seed, os.path.join(workdir, "state-bloat")
            )
        )
    if args.pipeline:
        results.append(
            d.run_pipeline_drill(
                args.seed, os.path.join(workdir, "pipeline")
            )
        )
    if args.shared:
        shared_kw = {"plan_factory": plan_factory} if args.plan else {}
        results.append(
            d.run_shared_drill(
                args.seed, os.path.join(workdir, "shared"), **shared_kw
            )
        )
    if args.failover:
        fo_kw = {"plan_factory": plan_factory} if args.plan else {}
        results.append(
            d.run_failover_drill(
                args.seed, os.path.join(workdir, "failover"), **fo_kw
            )
        )
    if args.follower:
        results.append(
            d.run_follower_drill(
                args.seed, os.path.join(workdir, "follower")
            )
        )
    if args.starvation:
        results.append(
            d.run_starvation_drill(
                args.seed, os.path.join(workdir, "starvation")
            )
        )

    ok = all(r.passed for r in results)
    for r in results:
        status = "PASS" if r.passed else f"FAIL ({r.error})"
        fired = ", ".join(
            f"{e['point']}@{e['hit']}" for e in r.comparable_log
        )
        print(f"{r.query:<24} {status:<10} rows={r.rows} "
              f"restarts={r.restarts} fired=[{fired}]")

    # conservation-ledger dump (ISSUE 19): the reconciler registry in the
    # /debug/audit shape, with ring breaches folded back in for jobs whose
    # reconciler was already expunged with the job (the ring survives
    # expunge precisely for this). Consumable offline by
    # `python tools/trace_report.py <file> --audit`.
    from arroyo_tpu.obs import audit

    audit_doc = audit.status()
    ring = [b for b in audit.breaches_since(0)
            if (b.get("job") or "?") not in audit_doc["jobs"]]
    for b in ring:
        j = audit_doc["jobs"].setdefault(
            b["job"], {"job": b["job"], "breaches": []})
        j["breaches"].append(b)
        j["breach_count"] = len(j["breaches"])
    os.makedirs(workdir, exist_ok=True)
    audit_path = os.path.join(workdir, "audit_status.json")
    with open(audit_path, "w") as f:
        json.dump(audit_doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {audit_path}")

    payload = {
        "seed": args.seed,
        "mode": ("plan" if args.plan else
                 "fast" if args.fast else "standard"),
        "passed": ok,
        "results": [r.to_json() for r in results],
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    rc = main()
    # skip interpreter-exit finalizers: leaked grpc-aio servers from the
    # embedded clusters can deadlock atexit (same reason
    # tools/tpu_probe_daemon.py hard-exits); all results are flushed
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
