"""Shared-plan admission + lifecycle (ISSUE 16): mount-vs-spawn.

The controller-side half of shared-plan multi-tenancy. At submission
(`try_mount`, called from ControllerServer.submit_job) a job's graph is
fingerprinted (sql/fingerprint.py); when its source scan matches a
shareable configuration, the job is MOUNTED instead of spawned whole:

  * the first eligible job triggers a hidden, registry-owned host job
    `__shared/<fp>` — just `source -> shared_bus` (engine/shared.py) at
    parallelism 1 — and EVERY eligible job, including the first, mounts
    symmetrically as a bus subscriber (its source op is rewritten to
    the `mounted` connector, the rest of its pipeline untouched);
  * the mount is refcounted: each tenant detaches on ITS terminal
    release (`on_job_expunged`), and only the last detach stops the
    host. One tenant's stop/rescale/failure never tears down or stalls
    the others (modeled: V_ORPHAN in analysis/model/sharedplan.py).

The publication gate (`gate_blocks`, consulted by the controller's
_checkpoint_reap for host jobs) is the shared-fate barrier contract:
one host barrier, per-tenant epochs reconciled. A host epoch E captured
at bus offset F may only PUBLISH once every mounted durable tenant's
own durable position has reached F — otherwise a host restart would
resume the scan beyond rows a tenant restore still needs (the model's
V_LOSS violation; the `leaked_barrier_across_tenants` mutant is exactly
this gate deleted). Tenants without durable state restore from offset 0
and rely on the bus's retained log instead, so they don't gate. While
a host epoch is gated, waiting tenants get `checkpoint_asap` so their
next cadence fires immediately — reconciliation is bounded by a tenant
checkpoint round-trip, not a full cadence interval.

Attribution rides the bus's per-subscriber consumed-row counts: the
apportioner (obs/attribution.py) splits the host job's busy/device
seconds across mounted tenants pro-rata, sum-preserving, so per-tenant
cost accounting survives the collapse of N scans into one.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Set, TYPE_CHECKING

from ..config import config
from ..engine.shared import BUS, HOST_PREFIX
from ..graph.logical import (
    EdgeType,
    LogicalGraph,
    LogicalNode,
    OperatorName,
)
from ..sql.fingerprint import apply_mount, shareable_source

if TYPE_CHECKING:  # pragma: no cover
    from .controller import ControllerServer, JobHandle

logger = logging.getLogger("arroyo.sharing")


def host_job_id(fingerprint: str) -> str:
    return HOST_PREFIX + fingerprint


def is_host_job(job_id: str) -> bool:
    return job_id.startswith(HOST_PREFIX)


class SharedHost:
    """One running shared scan and its mounted tenants (the refcount)."""

    def __init__(self, fingerprint: str, connector: str, source_config: dict):
        self.fingerprint = fingerprint
        self.job_id = host_job_id(fingerprint)
        self.connector = connector
        self.source_config = dict(source_config)
        self.tenants: Set[str] = set()
        self.mounts = 0      # total mounts ever (debug surface)
        self.stopping = False
        # host job reached a terminal state while tenants were still
        # mounted (a bounded scan FINISHES on EOS long before slow or
        # late tenants drain the retained log): the channel must outlive
        # the host job until the LAST tenant detaches
        self.defunct = False
        self.spawn_task = None  # retained submit task (not GC'd mid-flight)


class SharingManager:
    def __init__(self, controller: "ControllerServer"):
        self.controller = controller
        self.hosts: Dict[str, SharedHost] = {}
        self.by_job: Dict[str, str] = {}  # tenant job_id -> fingerprint

    # -- admission ------------------------------------------------------------

    def _eligible(self, job_id: str, graph: LogicalGraph):
        if not config().sharing.enabled or is_host_job(job_id):
            return None
        scan = shareable_source(graph)
        if scan is None:
            return None
        if graph.nodes[scan.node_id].parallelism != 1:
            # the bus is one total order; source fan-out happens
            # downstream of the mount, not at the scan
            return None
        return scan

    def try_mount(self, job_id: str, graph: LogicalGraph) -> Optional[dict]:
        """Mount-vs-spawn decision. On mount: rewrites `graph`'s source
        op to the `mounted` connector IN PLACE, ensures the host job is
        running, registers the tenant, and returns the mount directive
        {node_id, fingerprint, connector} that rides StartExecution
        (workers re-plan canonical SQL, then apply the same rewrite —
        sql/fingerprint.py apply_mount). Returns None to spawn unshared
        (ineligible, or the bus no longer retains the rows a fresh
        mount needs)."""
        scan = self._eligible(job_id, graph)
        if scan is None:
            return None
        fp = scan.fingerprint
        host = self.hosts.get(fp)
        if host is not None and (host.stopping or host.defunct):
            # teardown in flight, or the host already hit a terminal
            # state (EOS/failure) and only lingers for attached readers;
            # don't race either — spawn unshared, the next submission
            # re-hosts
            return None
        channel = BUS.get(fp)
        if channel is not None and channel.base > 0:
            # retention already trimmed the prefix a fresh tenant needs
            return None
        if channel is not None and channel.closed:
            # host scan already hit EOS; a new tenant wants a live scan
            return None
        if host is None:
            host = self._spawn_host(scan)
            if host is None:
                return None
        mount = {"node_id": scan.node_id, "fingerprint": fp,
                 "connector": scan.connector}
        apply_mount(graph, mount)
        host.tenants.add(job_id)
        host.mounts += 1
        self.by_job[job_id] = fp
        # retention must hold the full log until this tenant's
        # MountedSource actually attaches (scheduling is async)
        BUS.get_or_create(fp, config().sharing.max_retained_rows).expect(
            job_id
        )
        logger.info("job %s mounted onto shared scan %s (refcount %d)",
                    job_id, fp, len(host.tenants))
        return mount

    @staticmethod
    def _source_schema(connector: str):
        from ..connectors.base import get_connector

        return get_connector(connector).table_schema()

    def _spawn_host(self, scan) -> Optional[SharedHost]:
        cfg = config().sharing
        fp = scan.fingerprint
        schema = self._source_schema(scan.connector)
        g = LogicalGraph()
        g.add_node(LogicalNode.single(
            1, OperatorName.CONNECTOR_SOURCE, dict(scan.config),
            description=f"shared_scan[{scan.connector}]",
        ))
        g.add_node(LogicalNode.single(
            2, OperatorName.CONNECTOR_SINK,
            {"connector": "shared_bus", "fingerprint": fp,
             "max_retained_rows": cfg.max_retained_rows},
            description=f"shared_bus[{fp}]",
        ))
        g.add_edge(1, 2, EdgeType.FORWARD, schema)
        host = SharedHost(fp, scan.connector, scan.config)
        self.hosts[fp] = host
        # the channel must exist before any tenant's MountedSource
        # starts (worker scheduling order is unconstrained)
        BUS.get_or_create(fp, cfg.max_retained_rows)
        import asyncio

        async def _submit():
            job = await self.controller.submit_job(
                host.job_id,
                graph=g,
                storage_url=cfg.host_storage_url or None,
                n_workers=1,
                parallelism=1,
                tenant="__shared",
            )
            # the bus is ONE total order of offsets: the scan cannot fan
            # out without making replay order nondeterministic, so the
            # autoscaler must not actuate it. Aggregate load still sizes
            # the scan's PACE — the slowest tenant's backpressure
            # throttles publish, and every faster tenant rides the same
            # retained log (see engine/shared.py).
            job.autoscale_pinned = True

        host.spawn_task = asyncio.ensure_future(_submit())
        logger.info("spawned shared host %s for scan %s", host.job_id, fp)
        return host

    # -- publication gate -----------------------------------------------------

    def gate_blocks(self, job: "JobHandle", epoch: int) -> bool:
        """True when host `job`'s epoch must NOT publish yet: some
        mounted durable tenant's durable position is still behind the
        host's captured offset for this epoch."""
        if not is_host_job(job.job_id):
            return False
        fp = job.job_id[len(HOST_PREFIX):]
        host = self.hosts.get(fp)
        channel = BUS.get(fp)
        if host is None or channel is None:
            return False
        offset = channel.epoch_offsets.get(epoch)
        if offset is None:
            return False  # pre-gate epoch (no capture recorded)
        blocked = False
        for tid in host.tenants:
            tenant = self.controller.jobs.get(tid)
            if tenant is None or tenant.backend is None:
                continue  # non-durable tenants restore from 0 (the log)
            if tenant.state.is_terminal():
                continue  # release hook will detach it momentarily
            pos = channel.tenant_durable_position(
                tid, tenant.published_epoch
            )
            if pos < offset:
                blocked = True
                # accelerate reconciliation: the tenant checkpoints on
                # its next driver pass instead of the full cadence
                if not tenant.checkpoint_asap:
                    tenant.checkpoint_asap = True
                    tenant.kick()
        return blocked

    def note_publish(self, job: "JobHandle") -> None:
        """A job published an epoch. For a mounted tenant: raise its
        durable restore floor on the bus (retention may trim below it)
        and kick the host (a gated epoch may now clear)."""
        fp = self.by_job.get(job.job_id)
        if fp is None:
            return
        channel = BUS.get(fp)
        if channel is not None:
            channel.set_floor(
                job.job_id,
                channel.tenant_durable_position(
                    job.job_id, job.published_epoch
                ),
            )
        hj = self.controller.jobs.get(host_job_id(fp))
        if hj is not None:
            hj.kick()

    # -- refcounted release ---------------------------------------------------

    async def on_job_expunged(self, job: "JobHandle") -> None:
        """Terminal release hook (controller._release_job expunge path).
        Tenants detach from the bus; the LAST detach stops the host;
        the host's own release drops the channel."""
        if is_host_job(job.job_id):
            fp = job.job_id[len(HOST_PREFIX):]
            host = self.hosts.get(fp)
            channel = BUS.get(fp)
            if host is not None and host.tenants or (
                channel is not None
                and (channel.cursors or channel.expected)
            ):
                # a bounded scan FINISHES on EOS while tenants are still
                # draining the retained log (or haven't attached yet):
                # the channel outlives the host job; the LAST tenant
                # detach below drops it. New submissions spawn unshared
                # (defunct guard in try_mount).
                if host is not None:
                    host.defunct = True
                return
            self.hosts.pop(fp, None)
            BUS.drop(fp)
            return
        fp = self.by_job.pop(job.job_id, None)
        if fp is None:
            return
        channel = BUS.get(fp)
        if channel is not None:
            await channel.detach(job.job_id)
        host = self.hosts.get(fp)
        if host is None:
            return
        host.tenants.discard(job.job_id)
        hj = self.controller.jobs.get(host.job_id)
        if hj is not None:
            hj.kick()  # a gated epoch may have been waiting on this tenant
        if not host.tenants and host.defunct:
            # the host job already finished; this was the last reader
            self.hosts.pop(fp, None)
            BUS.drop(fp)
            return
        if not host.tenants and not host.stopping:
            host.stopping = True
            logger.info("shared scan %s refcount 0: stopping host", fp)
            try:
                mode = "checkpoint" if hj is not None and hj.backend \
                    else "immediate"
                await self.controller.stop_job(host.job_id, mode=mode)
            except KeyError:
                pass  # host never finished scheduling / already gone

    # -- introspection --------------------------------------------------------

    def status(self) -> dict:
        out = {}
        for fp, host in sorted(self.hosts.items()):
            hj = self.controller.jobs.get(host.job_id)
            channel = BUS.get(fp)
            out[fp] = {
                "host_job": host.job_id,
                "host_state": hj.state.value if hj is not None else None,
                "connector": host.connector,
                "refcount": len(host.tenants),
                "tenants": sorted(host.tenants),
                "mounts": host.mounts,
                "stopping": host.stopping,
                "defunct": host.defunct,
                "bus": channel.stats() if channel is not None else None,
            }
        return out
