"""Device-tier observatory (ISSUE 6): XLA compile/dispatch telemetry,
end-to-end latency markers, Prometheus exposition conformance, and the
noise-aware bench regression gate."""

import asyncio
import copy
import json
import os
import sys

import numpy as np
import pytest

from arroyo_tpu import obs
from arroyo_tpu.config import update
from arroyo_tpu.metrics import (
    DEFAULT_BUCKETS,
    REGISTRY,
    Registry,
    hist_quantiles,
)
from arroyo_tpu.obs import device as obs_device

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.reset()
    obs_device.reset()
    yield
    obs.reset()
    obs_device.reset()


# -- Prometheus text-exposition conformance (satellite) ----------------------


def test_exposition_bucket_ordering_and_inf():
    """Histogram exposition: _bucket lines in ascending le order with
    non-decreasing cumulative counts, the +Inf bucket equal to _count,
    then _sum and _count — the shape Prometheus's text parser requires."""
    reg = Registry()
    h = reg.histogram("conf_seconds", "t", buckets=(0.1, 0.5, 1.0, 5.0))
    hd = h.labels(task="0-0")
    for v in (0.05, 0.3, 0.7, 2.0, 9.0):
        hd.observe(v)
    lines = reg.expose().splitlines()
    bucket_lines = [l for l in lines if l.startswith("conf_seconds_bucket")]
    les, counts = [], []
    for l in bucket_lines:
        le = l.split('le="')[1].split('"')[0]
        les.append(float("inf") if le == "+Inf" else float(le))
        counts.append(float(l.rsplit(" ", 1)[1]))
    assert les == sorted(les) and les[-1] == float("inf")
    assert counts == sorted(counts), "cumulative counts must not decrease"
    assert counts[-1] == 5.0  # +Inf == observation count
    sum_idx = next(i for i, l in enumerate(lines)
                   if l.startswith("conf_seconds_sum"))
    count_idx = next(i for i, l in enumerate(lines)
                     if l.startswith("conf_seconds_count"))
    last_bucket_idx = max(i for i, l in enumerate(lines)
                          if l.startswith("conf_seconds_bucket"))
    assert last_bucket_idx < sum_idx < count_idx
    assert lines[count_idx].endswith(" 5")


def test_exposition_label_escaping():
    reg = Registry()
    g = reg.gauge("esc", "t")
    g.labels(path='a"b\\c\nend').set(1.0)
    text = reg.expose()
    assert 'path="a\\"b\\\\c\\nend"' in text
    # the raw control characters must not leak into the exposition
    assert not any('a"b' in l and "\n" not in repr(l)
                   for l in text.splitlines() if "esc{" in l)


def test_counter_monotonic_and_reset_semantics():
    """Counters only move up between resets; Registry.reset() behaves
    like a process restart (values restart from 0 through the SAME
    handles — Prometheus consumers treat a counter drop as a restart)."""
    reg = Registry()
    c = reg.counter("mono_total", "t")
    hd = c.labels(task="0-0")
    seen = []
    for _ in range(5):
        hd.inc(2)
        seen.append(hd.get())
    assert seen == sorted(seen)
    reg.reset()
    assert hd.get() == 0.0
    hd.inc()
    assert "mono_total" in reg.expose()
    assert hd.get() == 1.0


# -- InstrumentedJit: compile vs dispatch classification ---------------------


def test_instrumented_jit_classifies_and_logs_recompiles():
    calls = []
    fn = obs_device.InstrumentedJit("test.prog", lambda *a: calls.append(a))
    a4, a8 = np.zeros(4), np.zeros(8)
    fn(a4, rung=4)      # compile 1 (first shape signature)
    fn(a4, rung=4)      # dispatch (cache hit)
    fn(a8, rung=8)      # compile 2 (shape change)
    fn(a8, rung=8)      # dispatch
    log = obs_device.recompile_log()
    assert [e["cause"] for e in log] == ["first-compile", "shape-change"]
    assert log[0]["rung"] == 4 and log[1]["rung"] == 8
    assert "float64[8]" in log[1]["signature"]
    assert log[1]["program"] == "test.prog"
    s = obs_device.summary()["programs"]["test.prog"]
    assert s["compiles"] == 2
    assert s["cache_miss"] == 2 and s["cache_hit"] == 2
    assert s["dispatches"] == 2
    assert len(calls) == 4


def test_exchange_histogram_tracks_exchange_programs_only():
    """arroyo_device_exchange_seconds (ISSUE 7): exchange-flagged
    programs (the mesh keyed-shuffle steps) record their steady-state
    dispatches into the collective-time histogram; plain programs do
    not, and compiles never count as exchange time."""
    ex = obs_device.InstrumentedJit("mesh.route", lambda *a: None,
                                    exchange=True)
    plain = obs_device.InstrumentedJit("mesh.sgather", lambda *a: None)
    a = np.zeros(16)
    ex(a, rung=16)     # compile — must NOT land in the exchange hist
    ex(a, rung=16)     # dispatch — must land
    ex(a, rung=16)
    plain(a, rung=16)
    plain(a, rung=16)
    from arroyo_tpu.metrics import REGISTRY

    snap = dict(REGISTRY.snapshot()).get("arroyo_device_exchange_seconds",
                                         [])
    by_prog = {labels["program"]: h for labels, h in snap}
    assert by_prog["mesh.route"]["count"] == 2
    assert "mesh.sgather" not in by_prog
    s = obs_device.summary()["programs"]["mesh.route"]
    assert s["exchange_dispatches"] == 2
    assert "exchange_quantiles" in s


def test_instrumented_jit_disabled_is_passthrough():
    with update(obs={"device_telemetry": False}):
        fn = obs_device.InstrumentedJit("off.prog", lambda x: x + 1)
        assert fn(1) == 2
    assert obs_device.recompile_log() == []
    assert "off.prog" not in obs_device.summary()["programs"]


def test_compile_span_parents_into_ambient_trace():
    fn = obs_device.InstrumentedJit("span.prog", lambda x: x)
    with obs.span("checkpoint.capture", trace="j/ck-1", cat="runner") as sp:
        fn(np.zeros(3))
    spans = obs.recorder().snapshot(trace_id="j/ck-1")
    names = {s["name"]: s for s in spans}
    assert "jax.compile:span.prog" in names
    compile_span = names["jax.compile:span.prog"]
    assert compile_span["parent_id"] == names["checkpoint.capture"]["span_id"]
    assert compile_span["attrs"]["cause"] == "first-compile"


def test_batch_anchor_materializes_only_on_compile():
    # no compile during the extent -> no spans recorded at all
    a = obs_device.anchor("j/batch-1-0", "batch.process", task="1-0")
    a.close()
    assert len(obs.recorder()) == 0
    # a compile during the extent -> anchor + jax.compile child, linked
    fn = obs_device.InstrumentedJit("anchor.prog", lambda x: x)
    a = obs_device.anchor("j/batch-1-0", "batch.process", task="1-0")
    try:
        fn(np.zeros(2))
    finally:
        a.close()
    spans = obs.recorder().snapshot(trace_id="j/batch-1-0")
    names = {s["name"]: s for s in spans}
    assert set(names) == {"batch.process", "jax.compile:anchor.prog"}
    assert (names["jax.compile:anchor.prog"]["parent_id"]
            == names["batch.process"]["span_id"])


def test_padding_waste_gauge_per_rung():
    obs_device.note_padding("mesh.step", 128, 96, 512)
    obs_device.note_padding("mesh.step", 256, 250, 1024)
    text = REGISTRY.expose()
    assert ('arroyo_device_padding_waste{program="mesh.step",rung="128"} '
            '0.8125') in text
    waste = obs_device.summary()["padding_waste"]
    assert {w["rung"] for w in waste if w["program"] == "mesh.step"} == {
        "128", "256"}


# -- forced shape change on a real jax accumulator ---------------------------


def test_forced_shape_change_names_signature_and_rung():
    """The acceptance probe: growing a batch past the current packing
    rung forces a recompile whose cause record names the new shape
    signature and the rung that produced it."""
    from arroyo_tpu.ops.aggregates import AggSpec, Accumulator

    # the compile/dispatch counters are process-global (other tests in
    # the session may already have driven agg.update): assert deltas
    before = obs_device.summary()["programs"].get(
        "agg.update", {"compiles": 0, "dispatches": 0})
    with update(tpu={"shape_buckets": (64, 256)}):
        acc = Accumulator(
            [AggSpec("count", None, "c")], capacity=1024, backend="jax"
        )
        acc.update(np.arange(8, dtype=np.int64), {})     # rung 64: compile
        acc.update(np.arange(16, dtype=np.int64), {})    # rung 64: dispatch
        acc.update(np.arange(100, dtype=np.int64), {})   # rung 256: recompile
    recs = [e for e in obs_device.recompile_log()
            if e["program"] == "agg.update"]
    assert [e["cause"] for e in recs] == ["first-compile", "shape-change"]
    assert recs[0]["rung"] == 64 and recs[1]["rung"] == 256
    assert "[256]" in recs[1]["signature"]
    stats = obs_device.summary()["programs"]["agg.update"]
    assert stats["compiles"] - before.get("compiles", 0) == 2
    assert stats["dispatches"] - before.get("dispatches", 0) == 1


# -- latency markers ----------------------------------------------------------


def test_marker_signal_wire_round_trip():
    from arroyo_tpu.engine.network import decode_signal, encode_signal
    from arroyo_tpu.types import LatencyMarker, SignalMessage

    sig = SignalMessage.marker_of(LatencyMarker("2-1", 7, 123456789))
    assert decode_signal(encode_signal(sig)) == sig


def test_marker_interval_throttles_stamps():
    from arroyo_tpu.operators.context import SourceContext
    from arroyo_tpu.operators.context import WatermarkHolder
    from arroyo_tpu.types import TaskInfo

    with update(obs={"latency_marker_interval": 3600.0}):
        ctx = SourceContext(
            TaskInfo("j", 0, "src", 0, 1), [], None, WatermarkHolder(0)
        )
        assert ctx.next_latency_marker() is not None  # first always stamps
        assert ctx.next_latency_marker() is None      # throttled
    with update(obs={"latency_marker_interval": 0}):
        ctx = SourceContext(
            TaskInfo("j", 0, "src", 0, 1), [], None, WatermarkHolder(0)
        )
        assert ctx.next_latency_marker() is None      # disabled


def _job_series(name, job_id):
    return {
        labels["task"]: h
        for labels, h in REGISTRY.snapshot().get(name, [])
        if labels.get("job") == job_id
    }


# -- the embedded-cluster q5 acceptance test ---------------------------------


Q5_CLUSTER = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '200000',
  message_count = '60000', start_time = '0'
);
CREATE TABLE top_auctions (auction BIGINT, num BIGINT) WITH (
  connector = 'single_file', path = '{out}', format = 'json', type = 'sink'
);
INSERT INTO top_auctions
SELECT AuctionBids.auction, AuctionBids.num
FROM (
  SELECT bid.auction as auction, count(*) AS num,
         hop(interval '2 second', interval '10 second') as window
  FROM nexmark WHERE bid IS NOT NULL
  GROUP BY 1, window
) AS AuctionBids
JOIN (
  SELECT max(CountBids.num) AS maxn, CountBids.window
  FROM (
    SELECT bid.auction as auction, count(*) AS num,
           hop(interval '2 second', interval '10 second') as window
    FROM nexmark WHERE bid IS NOT NULL
    GROUP BY 1, window
  ) AS CountBids
  GROUP BY CountBids.window
) AS MaxBids
ON AuctionBids.window = MaxBids.window
   AND AuctionBids.num >= MaxBids.maxn;
"""


def test_q5_cluster_markers_and_compile_spans(tmp_path):
    """ISSUE 6 acceptance: q5 on the embedded cluster (2 workers, real
    gRPC + TCP exchange) with the window aggregates on the jax backend.
    Latency markers traverse source -> shuffle -> window -> join -> sink
    with a nonzero end-to-end p99 at the sink; at least one
    `jax.compile:<program>` span is parented inside a batch/checkpoint
    trace; the job still produces q5 output rows."""
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState

    REGISTRY.reset()
    out = tmp_path / "out.json"

    async def go():
        c = await ControllerServer(EmbeddedScheduler()).start()
        with update(
            pipeline={"checkpointing": {"interval": 0.2}},
            obs={"latency_marker_interval": 0.05},
            # engage the device (jax-CPU) window tier so q5's aggregate
            # programs compile inside the run
            tpu={"require_accelerator": False,
                 "shape_buckets": (1024, 8192)},
        ):
            await c.submit_job(
                "dobs1", sql=Q5_CLUSTER.format(out=out),
                storage_url=str(tmp_path / "ck"), n_workers=2,
                parallelism=2,
            )
            state = await c.wait_for_state(
                "dobs1", JobState.FINISHED, JobState.FAILED, timeout=120
            )
        await c.stop()
        return state

    state = asyncio.run(go())
    assert state == JobState.FINISHED

    # canonical output still produced (markers never become rows)
    rows = [json.loads(l) for l in open(out) if l.strip()]
    assert rows and all("auction" in r for r in rows)

    # (1) markers traversed the graph: transit recorded at intermediate
    # operators AND end-to-end at the sink with nonzero p99
    per_op = _job_series("arroyo_worker_latency_marker_seconds", "dobs1")
    e2e = _job_series("arroyo_worker_e2e_latency_seconds", "dobs1")
    assert len(per_op) >= 2, f"markers seen at {sorted(per_op)}"
    assert e2e, "no end-to-end latency recorded at any sink subtask"
    sink_hist = next(iter(e2e.values()))
    assert sink_hist["count"] >= 1
    assert hist_quantiles(sink_hist)["p99"] > 0.0
    # the sink's transit must ride through the shuffle/window tier, so
    # some NON-sink subtask saw the marker too
    assert set(per_op) - set(e2e), "markers skipped intermediate operators"

    # (2) at least one jax.compile span inside a batch/checkpoint trace
    spans = obs.recorder().snapshot(trace_prefix="dobs1/")
    compiles = [s for s in spans if s["name"].startswith("jax.compile:")]
    assert compiles, "no jax.compile spans recorded in job traces"
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], {})[s["span_id"]] = s
    parented = [
        s for s in compiles
        if s["parent_id"] in by_trace.get(s["trace_id"], {})
    ]
    assert parented, "compile spans not parented into their traces"
    anchors = {
        by_trace[s["trace_id"]][s["parent_id"]]["name"] for s in parented
    }
    assert anchors & {"batch.process", "watermark.advance",
                      "checkpoint.capture"}, anchors

    # (3) the recompile log names program + signature + rung for the
    # compiles the run actually paid
    log = obs_device.recompile_log()
    assert any(e["program"].startswith("agg.") and e["rung"]
               and "[" in e["signature"] for e in log)


# -- surfaces -----------------------------------------------------------------


def test_latency_report_and_debug_route():
    from aiohttp.test_utils import TestClient, TestServer

    from arroyo_tpu.metrics import E2E_LATENCY_SECONDS, LATENCY_MARKER_SECONDS
    from arroyo_tpu.utils.admin import build_admin_app

    REGISTRY.reset()
    LATENCY_MARKER_SECONDS.labels(job="lr", task="1-0").observe(0.01)
    E2E_LATENCY_SECONDS.labels(job="lr", task="2-0").observe(0.02)
    obs_device.note_padding("agg.update", 256, 200, 256)

    report = obs.latency_report("lr")
    assert report["operators"][0]["task"] == "1-0"
    assert report["end_to_end"][0]["p99_ms"] > 0
    assert report["device"]["padding_waste"]

    async def go():
        app = build_admin_app("test")
        async with TestClient(TestServer(app)) as client:
            resp = await client.get("/debug/latency", params={"job": "lr"})
            assert resp.status == 200
            return await resp.json()

    doc = asyncio.run(go())
    assert doc["operators"] and doc["end_to_end"]
    assert "recompiles" in doc["device"]


def test_rest_job_latency_endpoint(tmp_path):
    from aiohttp.test_utils import TestClient, TestServer

    from arroyo_tpu.api.rest import build_app
    from arroyo_tpu.metrics import E2E_LATENCY_SECONDS

    REGISTRY.reset()
    E2E_LATENCY_SECONDS.labels(job="restlat", task="9-0").observe(0.5)

    async def go():
        app = build_app(db_path=str(tmp_path / "api.db"))
        async with TestClient(TestServer(app)) as client:
            resp = await client.get("/api/v1/jobs/restlat/latency")
            assert resp.status == 200
            doc = await resp.json()
            other = await (
                await client.get("/api/v1/jobs/other/latency")
            ).json()
            return doc, other

    doc, other = asyncio.run(go())
    assert doc["end_to_end"][0]["task"] == "9-0"
    assert other["end_to_end"] == []  # job-scoped


def test_openapi_lists_latency_route():
    from arroyo_tpu.api.openapi import build_spec

    spec = build_spec()
    assert "/api/v1/jobs/{job_id}/latency" in spec["paths"]
    assert "LatencyReport" in spec["components"]["schemas"]


def test_trace_report_latency_summary(capsys):
    sys.path.insert(0, TOOLS)
    try:
        import trace_report
    finally:
        sys.path.remove(TOOLS)
    from arroyo_tpu.metrics import E2E_LATENCY_SECONDS

    REGISTRY.reset()
    E2E_LATENCY_SECONDS.labels(job="tr", task="3-0").observe(0.1)
    obs_device.note_padding("mesh.step", 64, 32, 128)
    trace_report.latency_summary(obs.latency_report())
    out = capsys.readouterr().out
    assert "end-to-end latency" in out
    assert "tr/3-0" in out
    assert "mesh.step rung=64" in out


# -- the noise-aware bench regression gate -----------------------------------


def _bench_compare():
    sys.path.insert(0, TOOLS)
    try:
        import bench_compare
    finally:
        sys.path.remove(TOOLS)
    return bench_compare


def test_gate_flags_2x_regression_and_ignores_wobble():
    bc = _bench_compare()
    baseline = {
        "value": 100_000.0, "value_runs": [96_000.0, 100_000.0, 104_000.0],
        "q1_eps": 50_000.0, "q1_eps_runs": [48_000.0, 50_000.0, 52_000.0],
        "q5_p99_ms": 1000.0,
        "contended": False,
    }
    # in-spread wobble: every metric moves but within allowed deltas
    wobble = {"value": 92_000.0, "q1_eps": 47_000.0, "q5_p99_ms": 1150.0,
              "contended": False}
    doc = bc.compare(baseline, wobble)
    assert doc["status"] == "ok", doc
    # injected 2x steady-state regression on the headline
    bad = dict(wobble, value=50_000.0)
    doc = bc.compare(baseline, bad)
    assert doc["status"] == "regression"
    assert doc["regressions"] == ["value"]
    assert doc["metrics"]["value"]["status"] == "regression"
    # latency regressions gate in the OTHER direction
    slow = dict(wobble, q5_p99_ms=3000.0)
    doc = bc.compare(baseline, slow)
    assert "q5_p99_ms" in doc["regressions"]
    # an improvement is never a regression
    fast = dict(wobble, value=220_000.0)
    assert bc.compare(baseline, fast)["status"] == "ok"
    assert bc.compare(baseline, fast)["metrics"]["value"]["status"] == (
        "improved")


def test_gate_measured_spread_widens_threshold():
    bc = _bench_compare()
    # 30% measured spread: a 25% drop must NOT gate (inside noise),
    # where the default 10% floor alone would have flagged it
    noisy = {"value": 100_000.0,
             "value_runs": [85_000.0, 100_000.0, 115_000.0],
             "contended": False}
    doc = bc.compare(noisy, {"value": 75_000.0, "contended": False})
    assert doc["status"] == "ok"
    # a quiet baseline DOES gate the same 25% drop
    quiet = {"value": 100_000.0,
             "value_runs": [99_000.0, 100_000.0, 101_000.0],
             "contended": False}
    doc = bc.compare(quiet, {"value": 75_000.0, "contended": False})
    assert doc["status"] == "regression"


def test_gate_against_pinned_baseline(tmp_path):
    """The committed BENCH_BASELINE.json gates correctly: an unmodified
    tree (baseline vs in-spread copy of itself) passes; an injected 2x
    steady-state regression fails — pinned by this test, not by hand."""
    bc = _bench_compare()
    pinned = os.path.join(os.path.dirname(TOOLS), "BENCH_BASELINE.json")
    with open(pinned) as f:
        baseline = json.load(f)
    assert baseline["metric"] == "nexmark_q5_events_per_sec"
    assert baseline["value"] > 0
    # unmodified tree: the same measurements, jittered inside the noise
    same = copy.deepcopy(baseline)
    for k, v in list(same.items()):
        if bc.classify(k) and isinstance(v, (int, float)) and v:
            same[k] = v * 1.03
    cur = tmp_path / "cur.json"
    cur.write_text(json.dumps(same))
    assert bc.main([pinned, str(cur)]) == 0
    # injected regression: headline halves, tail latency doubles
    bad = copy.deepcopy(baseline)
    bad["value"] = baseline["value"] / 2
    bad["q5_p99_ms"] = baseline.get("q5_p99_ms", 1000.0) * 2
    badp = tmp_path / "bad.json"
    badp.write_text(json.dumps(bad))
    out_json = tmp_path / "cmp.json"
    assert bc.main([pinned, str(badp), "--json", str(out_json)]) == 1
    doc = json.loads(out_json.read_text())
    assert "value" in doc["regressions"]
