"""Shared-plan connectors (ISSUE 16): the host tail and the tenant mount.

Two halves of one seam (see engine/shared.py for the bus semantics):

  * `shared_bus` (sink) — the HOST job's tail. The hidden
    `__shared/<fp>` job is just `deterministic source -> shared_bus`;
    this sink assigns each batch its absolute cumulative row offset,
    publishes it into the SharedChannel, and checkpoints the offset so
    a host restart resumes (and rewinds the log to) exactly where the
    last published epoch left off.
  * `mounted` (source) — each TENANT job's head. The controller rewrote
    the tenant's source op to this connector at admission; it attaches
    to the channel at its checkpointed position and re-emits the host's
    batches verbatim (they already carry `_timestamp`), so the rest of
    the tenant pipeline — watermarks, windows, sinks — is untouched
    and unaware it shares a scan.

Per-tenant exactly-once rests on three legs: (1) absolute row offsets —
a restored tenant re-reads from its checkpointed position and a host
rewind re-publishes identical rows (deterministic sources only, see
sql/fingerprint.py); (2) the controller's publication gate
(controller/sharing.py) keeps the host's durable offset from
overtaking any mounted tenant's durable position; (3) positions ride
the tenants' own manifest chains (a global state table per tenant
job), so one tenant's restore never touches another's.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..engine.shared import BUS
from ..operators.base import Operator, SourceFinishType, SourceOperator
from .base import ConnectionSchema, Connector, register_connector


class SharedTailSink(Operator):
    """Host-side tail: stamps batches with absolute row offsets and
    publishes them into the shared channel."""

    def __init__(self, fingerprint: str, max_retained_rows: int = 1 << 22):
        super().__init__("shared_bus")
        self.fingerprint = fingerprint
        self.max_retained_rows = max_retained_rows
        self.offset = 0  # cumulative rows published, checkpointed
        self.channel = None

    def tables(self):
        from ..state.table_config import global_table

        return {"o": global_table("o")}

    async def on_start(self, ctx):
        if ctx.task_info.parallelism != 1:
            raise RuntimeError(
                "shared_bus requires parallelism 1 (offsets are a single "
                "total order)"
            )
        if ctx.table_manager is not None:
            table = await ctx.table("o")
            stored = table.get("offset")
            if stored is not None:
                self.offset = int(stored)
        self.channel = BUS.get_or_create(
            self.fingerprint, self.max_retained_rows
        )

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        n = batch.num_rows
        if n == 0:
            return
        await self.channel.publish(self.offset, batch)
        self.offset += n

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("o")
            table.put("offset", self.offset)
        self.channel.note_host_capture(barrier.epoch, self.offset)

    async def on_close(self, ctx, collector, is_eod: bool):
        if self.channel is not None and is_eod:
            await self.channel.close()
        return None


class MountedSource(SourceOperator):
    """Tenant-side head: replays the shared channel from this job's own
    checkpointed position, emitting the host's batches verbatim."""

    def __init__(self, fingerprint: str):
        super().__init__("mounted")
        self.fingerprint = fingerprint
        self.position = 0  # absolute row offset of the next row to emit
        self.channel = None
        self._job_id: Optional[str] = None

    def tables(self):
        from ..state.table_config import global_table

        return {"m": global_table("m")}

    async def on_start(self, ctx):
        if ctx.task_info.parallelism != 1:
            raise RuntimeError(
                "mounted source requires parallelism 1 (the channel is one "
                "total order; fan-out happens downstream)"
            )
        self._job_id = ctx.task_info.job_id
        if ctx.table_manager is not None:
            table = await ctx.table("m")
            stored = table.get("pos")
            if stored is not None:
                self.position = int(stored)
        self.channel = BUS.get(self.fingerprint)
        if self.channel is None:
            raise RuntimeError(
                f"mounted source: no shared channel {self.fingerprint!r} "
                "(host job not running?)"
            )
        ok = await self.channel.attach(self._job_id, self.position)
        if not ok:
            raise RuntimeError(
                f"mounted source: channel {self.fingerprint!r} no longer "
                f"retains offset {self.position} (base "
                f"{self.channel.base})"
            )

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("m")
            table.put("pos", self.position)
        self.channel.note_tenant_capture(
            self._job_id, barrier.epoch, self.position
        )

    def drain_status(self):
        if self.channel is None:
            return None
        if not self.channel.closed:
            return (False, "mounted: host scan still streaming")
        if self.position < self.channel.end:
            return (
                False,
                f"mounted: {self.channel.end - self.position} rows behind",
            )
        return (True, "")

    async def run(self, ctx, collector) -> SourceFinishType:
        # re-seek on every (re)entry: a rescale/restore may have reset
        # position after the attach in on_start
        await self.channel.seek(self._job_id, self.position)
        while True:
            finish = await ctx.check_control(collector)
            if finish is not None:
                return finish
            batches = await self.channel.read(self._job_id, max_wait=0.25)
            if batches is None:
                if self.channel.closed and self.position >= self.channel.end:
                    return SourceFinishType.FINAL
                # detached under us (controller tore the mount down);
                # park until control arrives with the actual verdict
                await asyncio.sleep(0.05)
                continue
            for batch in batches:
                await collector.collect(batch)
                self.position += batch.num_rows
            if batches:
                await asyncio.sleep(0)


@register_connector
class SharedBusConnector(Connector):
    name = "shared_bus"
    description = "host tail of a shared source scan (internal)"
    sink = True
    config_schema = {
        "fingerprint": {"type": "string", "required": True},
        "max_retained_rows": {"type": "integer"},
    }

    def make_sink(self, config, schema: ConnectionSchema) -> SharedTailSink:
        return SharedTailSink(
            fingerprint=config["fingerprint"],
            max_retained_rows=int(config.get("max_retained_rows", 1 << 22)),
        )


@register_connector
class MountedConnector(Connector):
    name = "mounted"
    description = "tenant mount onto a shared source scan (internal)"
    source = True
    config_schema = {
        "fingerprint": {"type": "string", "required": True},
    }

    def make_source(self, config, schema: ConnectionSchema) -> MountedSource:
        return MountedSource(fingerprint=config["fingerprint"])
