"""Mesh-sharded accumulator on the virtual 8-device CPU mesh: all_to_all
routing + scatter-reduce must match the single-device result exactly."""

import numpy as np
import pandas as pd
import pytest

from arroyo_tpu.ops.aggregates import AggSpec
from arroyo_tpu.types import hash_column, server_for_hash_array


@pytest.fixture(scope="module")
def mesh():
    import jax

    from arroyo_tpu.parallel import key_mesh

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs multiple devices")
    return key_mesh(devices)


def test_sharded_accumulator_matches_pandas(mesh):
    from arroyo_tpu.parallel import MeshSlotDirectory, ShardedAccumulator

    specs = [
        AggSpec("count", None, "cnt"),
        AggSpec("sum", 0, "total"),
        AggSpec("max", 1, "hi", is_float=True),
    ]
    acc = ShardedAccumulator(specs, mesh, capacity_per_shard=256,
                             rows_per_shard=512)
    d = MeshSlotDirectory(acc.n_shards)
    rng = np.random.default_rng(3)
    n = 6000
    keys = rng.integers(0, 40, n)
    bins = rng.integers(0, 3, n)
    ints = rng.integers(-50, 50, n)
    floats = rng.random(n) * 10
    for lo in range(0, n, 1500):
        hi = min(lo + 1500, n)
        slots = d.assign(bins[lo:hi], [keys[lo:hi]])
        acc.update(slots, {0: ints[lo:hi], 1: floats[lo:hi]})
    df = pd.DataFrame({"b": bins, "k": keys, "i": ints, "f": floats})
    want = df.groupby(["b", "k"]).agg(
        cnt=("i", "size"), total=("i", "sum"), hi=("f", "max")
    )
    seen = 0
    for b in range(3):
        keys_out, slots = d.take_bin(b)
        gathered = acc.gather(slots)
        assert len(keys_out) == len(want.loc[b])
        for key, cnt, total, hi_ in zip(
            keys_out, gathered[0], gathered[1], gathered[2]
        ):
            row = want.loc[(b, key[0])]
            assert cnt == row["cnt"]
            assert total == row["total"]
            assert hi_ == pytest.approx(row["hi"])
            seen += 1
        acc.reset_slots(slots)
    assert seen == len(want)


def test_sharded_routing_respects_hash_ranges(mesh):
    """Rows must land on the shard that owns their hash range — the same
    mapping the host shuffle and state restore use."""
    from arroyo_tpu.parallel import MeshSlotDirectory, ShardedAccumulator

    specs = [AggSpec("count", None, "cnt")]
    acc = ShardedAccumulator(specs, mesh, capacity_per_shard=64,
                             rows_per_shard=256)
    d = MeshSlotDirectory(acc.n_shards)
    keys = np.arange(100, dtype=np.int64)
    # canonical shuffle hash: per-column hashes combined with the seed
    # (types.hash_arrays), matching schema.hash_keys and restore's
    # _range_mask
    from arroyo_tpu.types import hash_arrays

    owners = server_for_hash_array(
        hash_arrays([hash_column(keys)]), acc.n_shards
    )
    slots = d.assign(np.zeros(100, dtype=np.int64), [keys])
    acc.update(slots, {})
    for shard in range(acc.n_shards):
        expect = set(keys[owners == shard].tolist())
        got = {key[0] for _, key, _ in d.dirs[shard].items()}
        assert got == expect


def test_sharded_capacity_growth(mesh):
    """More keys than a shard's initial capacity: grow() must preserve all
    live values (stride-encoded slots are stable across growth)."""
    from arroyo_tpu.parallel import MeshSlotDirectory, ShardedAccumulator

    specs = [AggSpec("sum", 0, "total")]
    acc = ShardedAccumulator(specs, mesh, capacity_per_shard=8,
                             rows_per_shard=64)
    d = MeshSlotDirectory(acc.n_shards)
    rng = np.random.default_rng(11)
    n = 4000
    keys = rng.integers(0, 500, n)
    vals = rng.integers(0, 100, n)
    bins = np.zeros(n, dtype=np.int64)
    for lo in range(0, n, 400):
        hi = min(lo + 400, n)
        slots = d.assign(bins[lo:hi], [keys[lo:hi]])
        need = d.required_capacity()
        if need > acc.capacity - 1:
            acc.grow(need + 1)
        acc.update(slots, {0: vals[lo:hi]})
    assert acc.capacity > 8
    keys_out, slots = d.take_bin(0)
    gathered = acc.gather(slots)
    want = pd.Series(vals).groupby(keys).sum()
    assert len(keys_out) == len(want)
    for key, total in zip(keys_out, gathered[0]):
        assert total == want.loc[key[0]]


def test_sharded_signed_updates(mesh):
    """Retraction path: signed updates must be invertible on the mesh."""
    from arroyo_tpu.parallel import MeshSlotDirectory, ShardedAccumulator

    specs = [AggSpec("count", None, "cnt"), AggSpec("sum", 0, "total")]
    acc = ShardedAccumulator(specs, mesh, capacity_per_shard=64,
                             rows_per_shard=64)
    d = MeshSlotDirectory(acc.n_shards)
    keys = np.array([1, 2, 3, 1, 2, 3], dtype=np.int64)
    vals = np.array([10, 20, 30, 10, 20, 30], dtype=np.int64)
    bins = np.zeros(6, dtype=np.int64)
    slots = d.assign(bins, [keys])
    acc.update(slots, {0: vals})  # two appends per key
    signs = np.array([-1, -1, -1], dtype=np.int64)
    slots_r = d.assign(bins[:3], [keys[:3]])
    acc.update(slots_r, {0: vals[:3]}, signs=signs)  # retract one each
    keys_out, slots_all = d.take_bin(0)
    gathered = acc.gather(slots_all)
    for key, cnt, total in zip(keys_out, gathered[0], gathered[1]):
        assert cnt == 1
        assert total == key[0] * 10


def test_packed_exchange_sized_to_batch(mesh):
    """The all_to_all buffer must be bucketed to the batch, not the
    configured rows_per_shard ceiling: a small uniform batch on 8 shards
    ships far fewer padding rows than the old dense S*S*rows_per_shard
    layout, while a skewed batch still lands every row (VERDICT r3
    item 2)."""
    from arroyo_tpu.parallel import MeshSlotDirectory, ShardedAccumulator

    specs = [AggSpec("count", None, "cnt"), AggSpec("sum", 0, "total")]
    acc = ShardedAccumulator(specs, mesh, capacity_per_shard=4096,
                             rows_per_shard=1024)
    d = MeshSlotDirectory(acc.n_shards)
    S = acc.n_shards

    # uniform batch: per-owner counts ~n/S, per-cell ~n/S^2 -> R buckets
    # near n/S^2, padding bounded by one bucket step (4x), not the 87%
    # of the dense layout
    n = 8192
    keys = np.arange(n) % 1000
    bins = np.zeros(n, dtype=np.int64)
    slots = d.assign(bins, [keys])
    acc.update(slots, {0: np.ones(n, dtype=np.int64)})
    dense = S * S * 1024
    # the host combiner collapses the 8192 rows to their 1000 unique
    # slots before packing; shipped rows are the combined count + rung
    # padding, far under both the raw batch and the dense layout
    assert acc.rows_sent == 1000
    total_shipped = acc.rows_sent + acc.rows_padded
    assert total_shipped < dense / 2, (
        f"shipped {total_shipped} rows, dense layout would ship {dense}"
    )
    assert total_shipped < n

    # skewed batch: every row hits one owner shard; still exact
    acc2 = ShardedAccumulator(specs, mesh, capacity_per_shard=4096,
                              rows_per_shard=1024)
    d2 = MeshSlotDirectory(acc2.n_shards)
    hot = np.full(4096, 7, dtype=np.int64)
    bins2 = np.zeros(4096, dtype=np.int64)
    s2 = d2.assign(bins2, [hot])
    acc2.update(s2, {0: np.ones(4096, dtype=np.int64)})
    _, slots_out = d2.take_bin(0)
    g = acc2.gather(slots_out)
    assert g[0][0] == 4096 and g[1][0] == 4096


def test_all_to_all_path_matches_direct(mesh):
    """host_fed=False keeps the [S, S, R] src-major packing + in-step
    all_to_all (the multi-host / device-resident-producer shuffle); it
    must produce identical state to the host-fed direct layout."""
    from arroyo_tpu.parallel import MeshSlotDirectory, ShardedAccumulator

    specs = [AggSpec("count", None, "cnt"), AggSpec("sum", 0, "total"),
             AggSpec("min", 1, "lo")]
    rng = np.random.default_rng(11)
    n = 5000
    keys = rng.integers(0, 300, n)
    bins = rng.integers(0, 2, n)
    ints = rng.integers(-100, 100, n)
    ints2 = rng.integers(0, 1000, n)

    outs = []
    for host_fed in (True, False):
        acc = ShardedAccumulator(specs, mesh, capacity_per_shard=1024,
                                 rows_per_shard=256, host_fed=host_fed)
        d = MeshSlotDirectory(acc.n_shards)
        for lo in range(0, n, 1700):
            hi = min(lo + 1700, n)
            slots = d.assign(bins[lo:hi], [keys[lo:hi]])
            acc.update(slots, {0: ints[lo:hi], 1: ints2[lo:hi]})
        rows = {}
        for b in (0, 1):
            ks, ss = d.take_bin(b)
            g = acc.gather(ss)
            for k, c, t, m in zip(ks, g[0], g[1], g[2]):
                rows[(b, k[0])] = (int(c), int(t), int(m))
        outs.append(rows)
    assert outs[0] == outs[1]


def test_salted_accumulator_low_cardinality(mesh):
    """Salted mode: rows spread round-robin across shards and fold at
    gather — results identical to pandas; shipped rows stay near the
    batch size even when every row hits ONE group (the case hash
    ownership starves to a single shard)."""
    from arroyo_tpu.parallel import (
        SharedMeshSlotDirectory,
        ShardedAccumulator,
    )

    specs = [AggSpec("count", None, "cnt"), AggSpec("sum", 0, "total"),
             AggSpec("max", 1, "hi")]
    acc = ShardedAccumulator(specs, mesh, capacity_per_shard=256,
                             rows_per_shard=1024, salted=True)
    d = SharedMeshSlotDirectory(acc.n_shards)
    rng = np.random.default_rng(21)
    n = 8000
    # 3 groups over 8 shards: unsalted, at most 3 shards would work
    keys = rng.integers(0, 3, n)
    bins = np.zeros(n, dtype=np.int64)
    ints = rng.integers(-100, 100, n)
    ints2 = rng.integers(0, 10_000, n)
    slots = d.assign(bins, [keys])
    acc.update(slots, {0: ints, 1: ints2})
    # the combiner collapses the whole batch to its 3 groups before the
    # spread — shipped rows are bounded by the packing floor, not the
    # batch, and certainly not S * max-group
    assert acc.rows_sent == 3
    assert acc.rows_sent + acc.rows_padded <= acc.n_shards * 16

    import pandas as pd

    df = pd.DataFrame({"k": keys, "i": ints, "j": ints2})
    want = df.groupby("k").agg(cnt=("i", "size"), total=("i", "sum"),
                               hi=("j", "max"))
    got_keys, got_slots = d.take_bin(0)
    g = acc.gather(got_slots)
    for key, c, t, h in zip(got_keys, g[0], g[1], g[2]):
        row = want.loc[key[0]]
        assert c == row["cnt"] and t == row["total"] and h == row["hi"]
    # reset + reuse: freed slots start neutral on every shard
    acc.reset_slots(got_slots)
    s2 = d.assign(np.ones(4, dtype=np.int64), [np.arange(4)])
    acc.update(s2, {0: np.ones(4, dtype=np.int64),
                    1: np.full(4, 7, dtype=np.int64)})
    g2 = acc.gather(s2)
    assert list(g2[0]) == [1, 1, 1, 1]


def test_salted_restore_roundtrip(mesh):
    """Checkpoint roundtrip: snapshot -> reset -> restore -> gather must
    reproduce values (restore lands on the nominal shard, rest neutral)."""
    from arroyo_tpu.parallel import (
        SharedMeshSlotDirectory,
        ShardedAccumulator,
    )

    specs = [AggSpec("count", None, "cnt"), AggSpec("min", 0, "lo")]
    acc = ShardedAccumulator(specs, mesh, capacity_per_shard=64,
                             rows_per_shard=128, salted=True)
    d = SharedMeshSlotDirectory(acc.n_shards)
    keys = np.arange(5)
    bins = np.zeros(5, dtype=np.int64)
    slots = d.assign(np.repeat(bins, 40), [np.repeat(keys, 40)])
    acc.update(slots, {0: np.tile(np.arange(40), 5)})
    uniq = d.bin_entries(0)[1]
    vals = [np.asarray(v) for v in acc.gather(uniq)]
    acc.reset_slots(uniq)
    acc.restore(uniq, vals)
    back = acc.gather(uniq)
    assert np.array_equal(np.asarray(back[0]), vals[0])
    assert np.array_equal(np.asarray(back[1]), vals[1])


# -- device-resident exchange (ISSUE 7) ---------------------------------------


def test_device_owner_hash_matches_directory():
    """Routing-contract property test: device-side owner hashing
    (device_owners_for — the jax splitmix64 mirror jitted route steps
    use for raw key words) must agree bit-for-bit with
    MeshSlotDirectory.owners_for for random key columns across shard
    counts 2/4/8, including multi-column keys and edge-pattern words."""
    from arroyo_tpu.parallel.sharded_state import (
        MeshSlotDirectory,
        device_owners_for,
    )

    rng = np.random.default_rng(7)
    edge = np.array(
        [0, 1, -1, 2**63 - 1, -(2**63), 42, -42, 2**32, -(2**32)],
        dtype=np.int64,
    )
    for n_shards in (2, 4, 8):
        d = MeshSlotDirectory(n_shards)
        for n_cols in (1, 2, 3):
            for trial in range(4):
                n = int(rng.integers(1, 2000))
                cols = [
                    np.concatenate([
                        rng.integers(-2**62, 2**62, n, dtype=np.int64),
                        edge,
                    ])
                    for _ in range(n_cols)
                ]
                host = d.owners_for(cols, len(cols[0]))
                dev = np.asarray(device_owners_for(cols, n_shards))
                assert host.dtype == np.int64
                assert (host == dev).all(), (
                    f"owner mismatch at shards={n_shards} cols={n_cols}"
                )
                assert (dev >= 0).all() and (dev < n_shards).all()


def test_device_exchange_matches_host_fed(mesh):
    """The fused route+scatter+reduce program (device exchange) must
    produce state identical to the host-fed combiner path for the same
    update stream — signs, duplicate slots, multi-phys layouts and
    growth included."""
    from arroyo_tpu.parallel import MeshSlotDirectory, ShardedAccumulator

    specs = [
        AggSpec("count", None, "cnt"),
        AggSpec("sum", 0, "total"),
        AggSpec("max", 1, "hi"),
        AggSpec("min", 1, "lo"),
    ]
    rng = np.random.default_rng(3)
    accs = {
        mode: ShardedAccumulator(specs, mesh, capacity_per_shard=128,
                                 rows_per_shard=64, exchange=mode)
        for mode in ("host_fed", "device")
    }
    assert accs["device"]._exchange == "device"
    dirs = {m: MeshSlotDirectory(a.n_shards) for m, a in accs.items()}
    all_slots = {}
    for wave in range(4):
        n = int(rng.integers(1, 700))
        keys = rng.integers(0, 97, n, dtype=np.int64)
        bins = rng.integers(0, 3, n, dtype=np.int64)
        v0 = rng.integers(-50, 50, n, dtype=np.int64)
        v1 = rng.integers(-1000, 1000, n, dtype=np.int64)
        for mode, acc in accs.items():
            slots = dirs[mode].assign(bins, [keys])
            if dirs[mode].required_capacity() > acc.capacity - 1:
                acc.grow(dirs[mode].required_capacity() + 1)
            acc.update(slots, {0: v0, 1: v1})
            all_slots[mode] = slots
        # the two directories assign identically (same hash contract)
        assert (all_slots["host_fed"] == all_slots["device"]).all()
    live = {
        m: np.asarray(sorted({int(s) for _, _, s in d.items()}))
        for m, d in dirs.items()
    }
    out_h = accs["host_fed"].gather(live["host_fed"])
    out_d = accs["device"].gather(live["device"])
    for h, dv in zip(out_h, out_d):
        np.testing.assert_array_equal(np.asarray(h), np.asarray(dv))


def test_device_exchange_salted_and_signed(mesh):
    """Salted (positional-spread) device exchange and signed retraction
    rows: fold-at-gather must match host-fed byte-for-byte."""
    from arroyo_tpu.parallel import (
        ShardedAccumulator,
        SharedMeshSlotDirectory,
    )

    specs = [AggSpec("count", None, "cnt"), AggSpec("sum", 0, "total")]
    outs = {}
    for mode in ("host_fed", "device"):
        rng = np.random.default_rng(11)  # same stream per mode
        acc = ShardedAccumulator(specs, mesh, capacity_per_shard=64,
                                 rows_per_shard=32, salted=True,
                                 exchange=mode)
        d = SharedMeshSlotDirectory(acc.n_shards)
        for wave in range(3):
            n = int(rng.integers(1, 300))
            bins = rng.integers(0, 2, n, dtype=np.int64)
            keys = bins.copy()  # window-only grouping
            slots = d.assign(bins, [keys])
            vals = rng.integers(-20, 20, n, dtype=np.int64)
            signs = rng.choice([-1, 1], n).astype(np.int64)
            acc.update(slots, {0: vals}, signs=signs)
        live = np.asarray(sorted({int(s) for _, _, s in d.items()}))
        outs[mode] = [np.asarray(c) for c in acc.gather(live)]
        # reset + reuse round-trips through the salted device path too
        acc.reset_slots(live)
        z = acc.gather(live)
        assert all(int(np.abs(np.asarray(c)).sum()) == 0 for c in z)
    for h, dv in zip(outs["host_fed"], outs["device"]):
        np.testing.assert_array_equal(h, dv)
