"""Object storage provider.

Capability parity with the reference's StorageProvider
(/root/reference/crates/arroyo-storage/src/lib.rs:56): URL-scheme-dispatched
backends (local FS, S3/GCS/Azure via pyarrow.fs), get/put/list/delete,
`put_if_not_exists` (the CAS primitive the checkpoint protocol fences with),
and recursive directory delete. CAS atomicity by backend: local FS uses
O_EXCL; S3 uses a SigV4-signed conditional PUT (`If-None-Match: *`) with
credentials from env vars or, when botocore is installed, its full chain
(IMDS/IRSA roles); GCS uses `if_generation_match=0` via the google SDK.
When no resolvable credentials/SDK support the conditional put, CAS
degrades to check-then-create and logs a loud warning that exactly-once
fencing is weakened (reference: conditional-put support in
/root/reference/crates/arroyo-storage/src/lib.rs:56 region).
"""

from __future__ import annotations

import os
import time as _time
from pathlib import Path
from typing import List, Optional, Tuple
from urllib.parse import quote, urlparse

from .. import chaos, obs
from ..metrics import STORAGE_OP_SECONDS
from ..utils.logging import get_logger

logger = get_logger("storage")


def _chaos_latency(op: str, key: str) -> None:
    spec = chaos.fire("storage.latency", op=op, key=key)
    if spec is not None:
        import time

        time.sleep(float(spec.param("delay", 0.05)))


class _OpTimer:
    """Times one storage operation into the arroyo_storage_op_seconds
    histogram and — when a trace context is active (checkpoint flush,
    manifest publish, restore) — a `storage.<op>` span. Deliberately
    includes injected chaos latency/failures: the flight recorder should
    SHOW the fault, not hide it."""

    __slots__ = ("op", "span", "t0")

    def __init__(self, op: str, key: str, nbytes: Optional[int] = None):
        self.op = op
        attrs = {"key": key}
        if nbytes is not None:
            # payload size rides on write spans: the state-bloat drill
            # reads per-epoch upload bytes from the flight recording
            # (disk listings lose GC'd epochs)
            attrs["bytes"] = nbytes
        self.span = obs.span(f"storage.{op}", cat="storage", **attrs)

    def __enter__(self):
        self.t0 = _time.perf_counter()
        self.span.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.span.__exit__(exc_type, exc, tb)
        STORAGE_OP_SECONDS.labels(op=self.op).observe(
            _time.perf_counter() - self.t0
        )


class CasConflict(Exception):
    """put_if_not_exists target already exists."""


def _s3_fs_kwargs() -> dict:
    """S3FileSystem kwargs honoring AWS_ENDPOINT_URL (used by the fake-S3
    test harness and by minio-style deployments) and AWS_DEFAULT_REGION."""
    kw = {}
    ep = os.environ.get("AWS_ENDPOINT_URL")
    if ep:
        u = urlparse(ep)
        kw["endpoint_override"] = u.netloc
        kw["scheme"] = u.scheme or "https"
        kw["allow_bucket_creation"] = True
    region = os.environ.get("AWS_DEFAULT_REGION") or os.environ.get(
        "AWS_REGION"
    )
    if region:
        kw["region"] = region
    return kw


class StorageProvider:
    def __init__(self, url: str):
        self.url = url
        scheme, path = _parse(url)
        self.scheme = scheme
        self._warned_weak_cas = False
        if scheme == "file":
            self.root = Path(path)
            self.fs = None
        else:
            import pyarrow.fs as pafs

            if scheme == "s3":
                self.fs = pafs.S3FileSystem(**_s3_fs_kwargs())
            elif scheme in ("gs", "gcs"):
                self.fs = pafs.GcsFileSystem()
            else:
                raise ValueError(f"unsupported storage scheme {scheme!r}")
            self.root = Path(path)

    # -- core ---------------------------------------------------------------

    def _full(self, key: str) -> str:
        return str(self.root / key)

    def put(self, key: str, data: bytes):
        with _OpTimer("put", key, nbytes=len(data)):
            _chaos_latency("put", key)
            if chaos.fire("storage.write_fail", key=key):
                raise IOError(
                    f"chaos[storage.write_fail]: injected transient write "
                    f"failure for {key}"
                )
            if self.fs is None:
                p = Path(self._full(key))
                p.parent.mkdir(parents=True, exist_ok=True)
                tmp = p.with_suffix(p.suffix + f".tmp{os.getpid()}")
                tmp.write_bytes(data)
                os.replace(tmp, p)
            else:
                with self.fs.open_output_stream(self._full(key)) as f:
                    f.write(data)

    def put_if_not_exists(self, key: str, data: bytes):
        """CAS create: raises CasConflict if the key exists."""
        with _OpTimer("cas", key):
            self._put_if_not_exists_inner(key, data)

    def _put_if_not_exists_inner(self, key: str, data: bytes):
        if chaos.fire("storage.cas_conflict", key=key):
            # a lost CAS race: the conflict surfaces but the key does NOT
            # exist afterwards — the hardest shape for callers to handle
            raise CasConflict(key)
        if self.fs is None:
            p = Path(self._full(key))
            p.parent.mkdir(parents=True, exist_ok=True)
            try:
                fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                raise CasConflict(key)
            with os.fdopen(fd, "wb") as f:
                f.write(data)
        elif self.scheme == "s3" and self._s3_conditional_put(key, data):
            pass
        elif self.scheme in ("gs", "gcs") and self._gcs_conditional_put(
            key, data
        ):
            pass
        else:
            if not self._warned_weak_cas:
                self._warned_weak_cas = True
                logger.warning(
                    "storage %s: no credentials/SDK for an atomic "
                    "conditional put; put_if_not_exists degrades to "
                    "NON-ATOMIC check-then-create. Exactly-once fencing "
                    "(generation claims, 2PC commit authorization) is "
                    "weakened under concurrent controllers.",
                    self.url,
                )
            if self.exists(key):
                raise CasConflict(key)
            self.put(key, data)

    def _s3_conditional_put(self, key: str, data: bytes) -> bool:
        """Atomic S3 create via SigV4-signed `PUT` + `If-None-Match: *`.
        Returns False (caller falls back) when credentials are absent;
        raises CasConflict on 412/409 (precondition failed / concurrent
        conditional write)."""
        access = os.environ.get("AWS_ACCESS_KEY_ID")
        secret = os.environ.get("AWS_SECRET_ACCESS_KEY")
        token = os.environ.get("AWS_SESSION_TOKEN")
        if not access or not secret:
            # role-based deployments (IMDS/IRSA): resolve through botocore's
            # credential chain when it's installed
            try:
                import botocore.session

                creds = botocore.session.Session().get_credentials()
                frozen = creds.get_frozen_credentials() if creds else None
            except Exception:  # noqa: BLE001 - sdk absent or chain failed
                frozen = None
            if frozen is None:
                return False
            access, secret, token = (
                frozen.access_key,
                frozen.secret_key,
                frozen.token,
            )
        import datetime
        import hashlib
        import hmac

        try:
            import requests
        except ImportError:
            return False

        region = (
            os.environ.get("AWS_DEFAULT_REGION")
            or os.environ.get("AWS_REGION")
            or "us-east-1"
        )
        full = self._full(key).lstrip("/")
        endpoint = os.environ.get("AWS_ENDPOINT_URL")
        if endpoint:
            host = urlparse(endpoint).netloc
            url = endpoint.rstrip("/") + "/" + quote(full, safe="/-_.~")
        else:
            host = f"s3.{region}.amazonaws.com"
            url = f"https://{host}/" + quote(full, safe="/-_.~")
        now = datetime.datetime.now(datetime.timezone.utc)
        amzdate = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        payload_hash = hashlib.sha256(data).hexdigest()
        headers = {
            "host": host,
            "if-none-match": "*",
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amzdate,
        }
        if token:
            headers["x-amz-security-token"] = token
        signed_names = sorted(headers)
        canonical = "\n".join(
            [
                "PUT",
                "/" + quote(full, safe="/-_.~"),
                "",
                "".join(f"{h}:{headers[h]}\n" for h in signed_names),
                ";".join(signed_names),
                payload_hash,
            ]
        )
        scope = f"{datestamp}/{region}/s3/aws4_request"
        to_sign = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amzdate,
                scope,
                hashlib.sha256(canonical.encode()).hexdigest(),
            ]
        )

        def _hmac(k: bytes, msg: str) -> bytes:
            return hmac.new(k, msg.encode(), hashlib.sha256).digest()

        sig_key = _hmac(
            _hmac(
                _hmac(_hmac(("AWS4" + secret).encode(), datestamp), region),
                "s3",
            ),
            "aws4_request",
        )
        signature = hmac.new(
            sig_key, to_sign.encode(), hashlib.sha256
        ).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
            f"SignedHeaders={';'.join(signed_names)}, Signature={signature}"
        )
        del headers["host"]  # requests sets it from the URL
        # 409 (ConditionalRequestConflict) means a concurrent conditional
        # write left the outcome unknown — retry: a real winner then shows
        # as 412, otherwise our retry lands. Transient 5xx (SlowDown etc.)
        # retries with backoff the same way before being treated as fatal.
        for attempt in range(5):
            resp = requests.put(url, data=data, headers=headers, timeout=30)
            if resp.status_code == 412:
                raise CasConflict(key)
            if resp.status_code == 409 or resp.status_code // 100 == 5:
                import time as _time

                _time.sleep(0.1 * (attempt + 1))
                continue
            break
        if resp.status_code == 409:
            raise IOError(
                f"s3 conditional put of {key}: persistent 409 conflict"
            )
        if resp.status_code in (301, 307, 400):
            # region mismatch / redirect (no region env set): degrade to
            # check-then-create (with the loud warning) — these statuses
            # mean the request never evaluated the condition
            logger.warning(
                "s3 conditional put of %s failed (%s %s); falling back to "
                "non-atomic check-then-create",
                key,
                resp.status_code,
                resp.text[:200],
            )
            return False
        if resp.status_code // 100 != 2:
            # 403/5xx are ambiguous (the object may or may not exist now);
            # degrading here could let two controllers both claim — raise
            raise IOError(
                f"s3 conditional put of {key} failed: "
                f"{resp.status_code} {resp.text[:200]}"
            )
        return True

    def _gcs_conditional_put(self, key: str, data: bytes) -> bool:
        """Atomic GCS create via `if_generation_match=0`. Returns False
        (caller falls back) when the SDK or default credentials are
        unavailable."""
        try:
            from google.api_core.exceptions import PreconditionFailed
            from google.cloud import storage as gcs
        except ImportError:
            return False
        try:
            client = gcs.Client()
        except Exception:  # noqa: BLE001 - no default credentials
            return False
        full = self._full(key).lstrip("/")
        bucket_name, _, blob_name = full.partition("/")
        blob = client.bucket(bucket_name).blob(blob_name)
        try:
            blob.upload_from_string(data, if_generation_match=0)
        except PreconditionFailed:
            raise CasConflict(key)
        return True

    def get(self, key: str) -> Optional[bytes]:
        with _OpTimer("get", key):
            _chaos_latency("get", key)
            if self.fs is None:
                p = Path(self._full(key))
                if not p.exists():
                    return None
                return p.read_bytes()
            import pyarrow.fs as pafs

            try:
                with self.fs.open_input_stream(self._full(key)) as f:
                    return f.read()
            except (FileNotFoundError, OSError):
                return None

    def exists(self, key: str) -> bool:
        if self.fs is None:
            return Path(self._full(key)).exists()
        import pyarrow.fs as pafs

        info = self.fs.get_file_info(self._full(key))
        return info.type != pafs.FileType.NotFound

    def delete(self, key: str):
        if self.fs is None:
            Path(self._full(key)).unlink(missing_ok=True)
        else:
            try:
                self.fs.delete_file(self._full(key))
            except (FileNotFoundError, OSError):
                pass

    def delete_directory(self, key: str):
        if self.fs is None:
            import shutil

            shutil.rmtree(self._full(key), ignore_errors=True)
        else:
            try:
                self.fs.delete_dir(self._full(key))
            except (FileNotFoundError, OSError):
                pass

    def list(self, prefix: str) -> List[str]:
        """Keys under prefix (relative to root)."""
        if self.fs is None:
            base = Path(self._full(prefix))
            if not base.exists():
                return []
            out = []
            for p in base.rglob("*"):
                if p.is_file():
                    out.append(str(p.relative_to(self.root)))
            return sorted(out)
        import pyarrow.fs as pafs

        sel = pafs.FileSelector(self._full(prefix), recursive=True,
                                allow_not_found=True)
        return sorted(
            str(Path(fi.path).relative_to(self.root))
            for fi in self.fs.get_file_info(sel)
            if fi.type == pafs.FileType.File
        )

    # -- arrow IO helpers ----------------------------------------------------

    def write_parquet(self, key: str, table) -> int:
        import io

        import pyarrow.parquet as pq

        buf = io.BytesIO()
        pq.write_table(table, buf)
        data = buf.getvalue()
        self.put(key, data)
        return len(data)

    def read_parquet(self, key: str):
        import io

        import pyarrow.parquet as pq

        data = self.get(key)
        if data is None:
            return None
        return pq.read_table(io.BytesIO(data))


def _parse(url: str) -> Tuple[str, str]:
    if "://" not in url:
        return "file", str(Path(url).absolute())
    u = urlparse(url)
    if u.scheme == "file":
        return "file", u.path
    return u.scheme, (u.netloc + u.path)
